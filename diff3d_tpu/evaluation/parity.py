"""Matched-seed sampler parity: how far a few-step schedule drifts from
the full-grid ancestral oracle.

The sampler's RNG contract (``diffusion/core.py::sample_loop_prepare``)
keeps every stochastic draw — init image, stochastic-conditioning
indices, uncond frames — on the carried key stream regardless of the
step schedule, so two samplers run with the SAME per-object key differ
only by their reverse-process updates.  Scoring one against the other
therefore isolates the quality cost of the schedule (DDIM-16 vs
ancestral-256), with no confound from different noise draws.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from diff3d_tpu.evaluation.metrics import psnr, ssim

#: PSNR values are capped here before averaging: bit-identical outputs
#: (e.g. the oracle scored against itself) have zero MSE and infinite
#: PSNR, which would poison the mean and break strict-JSON consumers.
PSNR_CAP = 99.0


def _resize_to(g: np.ndarray, hw: tuple) -> np.ndarray:
    """Bilinearly resize ``[V, B, h, w, 3]`` generations to ``hw`` —
    the same interpolation the cascade uses to upsample drafts, so a
    draft scored against the full-resolution oracle is compared through
    exactly the lens the refine pass sees it."""
    import jax

    shape = g.shape[:2] + (hw[0], hw[1]) + g.shape[4:]
    return np.asarray(jax.image.resize(
        np.asarray(g, np.float32), shape, method="bilinear"))


def matched_seed_parity(gens: Sequence[np.ndarray],
                        oracle_gens: Sequence[np.ndarray],
                        w_index: int = 0,
                        resize: bool = False) -> dict:
    """PSNR/SSIM of per-object generations against matched-seed oracle
    generations.

    Args:
      gens / oracle_gens: aligned per-object arrays ``[V, B, H, W, 3]``
        (any float dtype; B is the guidance sweep) produced with the same
        per-object keys by two samplers.
      w_index: guidance-sweep column to score.
      resize: allow a resolution mismatch by bilinearly upsampling
        ``gens`` to the oracle resolution before scoring (the cascade
        draft-vs-128²-oracle comparison); view count and sweep must
        still match.
    Returns:
      ``{"psnr", "psnr_std", "ssim", "views"}`` pooled over every view of
      every object (PSNR per-view values capped at :data:`PSNR_CAP`).
    """
    if len(gens) != len(oracle_gens):
        raise ValueError(
            f"{len(gens)} generations vs {len(oracle_gens)} oracle "
            "generations — the object lists must align")
    psnrs, ssims = [], []
    for g, o in zip(gens, oracle_gens):
        if resize and g.shape[:2] == o.shape[:2] \
                and g.shape[2:4] != o.shape[2:4]:
            g = _resize_to(np.asarray(g), o.shape[2:4])
        if g.shape != o.shape:
            raise ValueError(
                f"shape mismatch {g.shape} vs {o.shape}: matched-seed "
                "runs must share view count, sweep, and resolution "
                "(pass resize=True to score across resolutions)")
        if g.shape[0] == 0:
            continue
        a = np.asarray(g[:, w_index], np.float32)
        b = np.asarray(o[:, w_index], np.float32)
        psnrs.extend(np.minimum(np.asarray(psnr(a, b)), PSNR_CAP).tolist())
        ssims.extend(np.asarray(ssim(a, b)).tolist())
    if not psnrs:
        raise ValueError("no views to score: every object was empty")
    return {
        "psnr": round(float(np.mean(psnrs)), 3),
        "psnr_std": round(float(np.std(psnrs)), 3),
        "ssim": round(float(np.mean(ssims)), 4),
        "views": len(psnrs),
    }


def cascade_parity(draft_gens: Sequence[np.ndarray],
                   refined_gens: Sequence[np.ndarray],
                   oracle_gens: Sequence[np.ndarray],
                   w_index: int = 0,
                   max_objects: Optional[int] = None) -> dict:
    """Score a cascade run against the single-pass full-resolution
    oracle, draft and refined side by side.

    ``draft_gens`` are per-object draft-resolution generations
    (upsampled here through the refine pass's own interpolation),
    ``refined_gens`` the cascade's full-resolution outputs, and
    ``oracle_gens`` matched-seed single-pass generations.  Returns
    ``{"draft": {...}, "refined": {...}, "objects"}`` — each inner
    block a :func:`matched_seed_parity` record, so the delta between
    the two PSNRs is exactly what the truncated refinement buys.
    """
    if max_objects is not None:
        draft_gens = list(draft_gens)[:max_objects]
        refined_gens = list(refined_gens)[:max_objects]
        oracle_gens = list(oracle_gens)[:max_objects]
    if not (len(draft_gens) == len(refined_gens) == len(oracle_gens)):
        raise ValueError(
            f"{len(draft_gens)} draft vs {len(refined_gens)} refined vs "
            f"{len(oracle_gens)} oracle objects — the lists must align")
    return {
        "draft": matched_seed_parity(draft_gens, oracle_gens,
                                     w_index=w_index, resize=True),
        "refined": matched_seed_parity(refined_gens, oracle_gens,
                                       w_index=w_index),
        "objects": len(oracle_gens),
    }
