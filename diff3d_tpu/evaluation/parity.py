"""Matched-seed sampler parity: how far a few-step schedule drifts from
the full-grid ancestral oracle.

The sampler's RNG contract (``diffusion/core.py::sample_loop_prepare``)
keeps every stochastic draw — init image, stochastic-conditioning
indices, uncond frames — on the carried key stream regardless of the
step schedule, so two samplers run with the SAME per-object key differ
only by their reverse-process updates.  Scoring one against the other
therefore isolates the quality cost of the schedule (DDIM-16 vs
ancestral-256), with no confound from different noise draws.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from diff3d_tpu.evaluation.metrics import psnr, ssim

#: PSNR values are capped here before averaging: bit-identical outputs
#: (e.g. the oracle scored against itself) have zero MSE and infinite
#: PSNR, which would poison the mean and break strict-JSON consumers.
PSNR_CAP = 99.0


def matched_seed_parity(gens: Sequence[np.ndarray],
                        oracle_gens: Sequence[np.ndarray],
                        w_index: int = 0) -> dict:
    """PSNR/SSIM of per-object generations against matched-seed oracle
    generations.

    Args:
      gens / oracle_gens: aligned per-object arrays ``[V, B, H, W, 3]``
        (any float dtype; B is the guidance sweep) produced with the same
        per-object keys by two samplers.
      w_index: guidance-sweep column to score.
    Returns:
      ``{"psnr", "psnr_std", "ssim", "views"}`` pooled over every view of
      every object (PSNR per-view values capped at :data:`PSNR_CAP`).
    """
    if len(gens) != len(oracle_gens):
        raise ValueError(
            f"{len(gens)} generations vs {len(oracle_gens)} oracle "
            "generations — the object lists must align")
    psnrs, ssims = [], []
    for g, o in zip(gens, oracle_gens):
        if g.shape != o.shape:
            raise ValueError(
                f"shape mismatch {g.shape} vs {o.shape}: matched-seed "
                "runs must share view count, sweep, and resolution")
        if g.shape[0] == 0:
            continue
        a = np.asarray(g[:, w_index], np.float32)
        b = np.asarray(o[:, w_index], np.float32)
        psnrs.extend(np.minimum(np.asarray(psnr(a, b)), PSNR_CAP).tolist())
        ssims.extend(np.asarray(ssim(a, b)).tolist())
    if not psnrs:
        raise ValueError("no views to score: every object was empty")
    return {
        "psnr": round(float(np.mean(psnrs)), 3),
        "psnr_std": round(float(np.std(psnrs)), 3),
        "ssim": round(float(np.mean(ssims)), 4),
        "views": len(psnrs),
    }
