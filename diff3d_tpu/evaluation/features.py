"""Real-network FID feature extractors (pluggable into ``fid.py``).

The canonical FID feature space is a pretrained classifier's penultimate
activations.  This zero-egress image ships NO pretrained weights, so the
extractor takes a **local weights file** (``--feature_weights`` in
``eval_cli``): a torchvision-format VGG16 ``state_dict`` saved as ``.pth``
/``.pt`` (loaded via the baked-in cpu torch) or as an ``.npz`` with the
same key names (``features.{i}.weight``, ``classifier.{i}.weight``, ...).
The architecture is *inferred from the weight shapes* — conv widths, pool
placement (index gaps in the ``features.*`` numbering), and input
resolution (from ``classifier.0``'s fan-in) — so the same code runs the
real 224x224 VGG16 and tiny parity-test networks.

Feature definition: the 4096-d "fc2" embedding — ``classifier.3`` output
after ReLU — a documented perceptual/FID feature space (VGG16 fc2).  Every
number produced through here is labeled ``fid`` (vs the random-projection
fallback's ``fid_randfeat``) so reports always say which extractor made
them; see ``evaluation/fid.py`` and ``cli/eval_cli.py``.

(The reference has no evaluation code at all — SURVEY.md §5.5.)
"""

from __future__ import annotations

import os
import re
from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# ImageNet normalization (torchvision transforms convention), applied to
# [0, 1] inputs.
_IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
_IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def load_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Load a torchvision-style state dict from ``.npz`` or ``.pth/.pt``."""
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: np.asarray(z[k]) for k in z.files}
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):  # a full module was saved
        sd = sd.state_dict()
    return {k: v.detach().cpu().numpy() for k, v in sd.items()}


def _vgg_spec(sd: Dict[str, np.ndarray]
              ) -> Tuple[List[Tuple[int, bool]], int]:
    """Infer (conv layer list, input size) from torchvision VGG key names.

    Returns ``([(features_index, pool_after), ...], input_hw)``.  A gap of
    3 between consecutive conv indices means conv->ReLU->MaxPool; a gap of
    2 means conv->ReLU.  The trailing pool (torchvision puts one at the end
    of ``features``) is always present.  Input resolution solves
    ``classifier.0`` fan-in = C_last * s * s with s = hw / 2^n_pools.
    """
    idxs = sorted(int(m.group(1)) for k in sd
                  if (m := re.fullmatch(r"features\.(\d+)\.weight", k)))
    if not idxs or "classifier.0.weight" not in sd:
        raise ValueError(
            "weights are not a torchvision-style VGG state dict "
            f"(conv indices {idxs}, keys {sorted(sd)[:5]}...)")
    convs = []
    for a, b in zip(idxs, idxs[1:]):
        convs.append((a, b - a == 3))
    convs.append((idxs[-1], True))
    n_pools = sum(p for _, p in convs)
    c_last = sd[f"features.{idxs[-1]}.weight"].shape[0]
    fan_in = sd["classifier.0.weight"].shape[1]
    s2, rem = divmod(fan_in, c_last)
    s = int(round(np.sqrt(s2)))
    if rem or s * s != s2:
        raise ValueError(
            f"classifier.0 fan-in {fan_in} is not c_last*s^2 (c={c_last})")
    return convs, s * (2 ** n_pools)


def vgg16_feature_fn(weights_path: str
                     ) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Build a jittable ``[B, H, W, 3] in [-1, 1] -> [B, 4096]`` feature fn
    from a local VGG16 weights file (see module docstring)."""
    sd = load_state_dict(weights_path)
    convs, input_hw = _vgg_spec(sd)

    # Torch layouts -> XLA-native: conv OIHW -> HWIO, linear [out,in] kept
    # (applied as x @ W.T).
    params = {}
    for i, _ in convs:
        params[f"cw{i}"] = jnp.asarray(
            np.transpose(sd[f"features.{i}.weight"], (2, 3, 1, 0)))
        params[f"cb{i}"] = jnp.asarray(sd[f"features.{i}.bias"])
    for i in (0, 3):
        params[f"lw{i}"] = jnp.asarray(sd[f"classifier.{i}.weight"])
        params[f"lb{i}"] = jnp.asarray(sd[f"classifier.{i}.bias"])
    mean = jnp.asarray(_IMAGENET_MEAN)
    std = jnp.asarray(_IMAGENET_STD)

    def feats(imgs: jnp.ndarray) -> jnp.ndarray:
        B = imgs.shape[0]
        x = (imgs.astype(jnp.float32) + 1.0) / 2.0
        x = jax.image.resize(x, (B, input_hw, input_hw, x.shape[-1]),
                             "bilinear")
        x = (x - mean) / std
        for i, pool_after in convs:
            x = jax.lax.conv_general_dilated(
                x, params[f"cw{i}"], window_strides=(1, 1),
                padding=((1, 1), (1, 1)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + params[f"cb{i}"])
            if pool_after:
                x = jax.lax.reduce_window(
                    x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1),
                    "VALID")
        # classifier.0 fan-in is flattened NCHW (torch) order
        x = jnp.transpose(x, (0, 3, 1, 2)).reshape(B, -1)
        x = jax.nn.relu(x @ params["lw0"].T + params["lb0"])
        x = jax.nn.relu(x @ params["lw3"].T + params["lb3"])
        return x

    return feats


def resolve_feature_fn(weights_path=None):
    """Returns ``(feature_fn, label)``: the real VGG16 extractor labeled
    ``'fid'`` when a weights file exists, else the seeded random-projection
    fallback labeled ``'fid_randfeat'`` (``fid.default_feature_fn``)."""
    from diff3d_tpu.evaluation.fid import default_feature_fn

    if weights_path:
        if not os.path.exists(weights_path):
            raise FileNotFoundError(
                f"--feature_weights {weights_path} does not exist")
        return vgg16_feature_fn(weights_path), "fid"
    return default_feature_fn(), "fid_randfeat"
