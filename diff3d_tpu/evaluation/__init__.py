from diff3d_tpu.evaluation.metrics import psnr, ssim
from diff3d_tpu.evaluation.fid import (FIDStats, fid_from_stats,
                                       gaussian_stats, frechet_distance)

__all__ = ["psnr", "ssim", "FIDStats", "fid_from_stats", "gaussian_stats",
           "frechet_distance"]
