from diff3d_tpu.evaluation.metrics import psnr, ssim
from diff3d_tpu.evaluation.fid import (FIDStats, fid_from_stats,
                                       gaussian_stats, frechet_distance)
from diff3d_tpu.evaluation.parity import (PSNR_CAP, cascade_parity,
                                           matched_seed_parity)
from diff3d_tpu.evaluation.consistency import (plane_homography,
                                               reprojection_consistency,
                                               warp_frame)

__all__ = ["psnr", "ssim", "FIDStats", "fid_from_stats", "gaussian_stats",
           "frechet_distance", "PSNR_CAP", "cascade_parity",
           "matched_seed_parity",
           "plane_homography", "reprojection_consistency", "warp_frame"]
