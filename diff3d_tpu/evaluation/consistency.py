"""Multi-view 3D-consistency metric: reprojection error across views.

A geometrically consistent frame sequence (the trajectory service's
output) must agree with itself: warping frame ``j`` into frame ``i``'s
viewpoint through the scene geometry should reproduce frame ``i`` where
the views overlap.  Full geometry is unknown at serving time, so the
warp uses the classic *plane-induced homography*: the scene is
approximated by the fronto-parallel plane through the look-at target
(normal = camera ``i``'s optical axis).  For the small angular steps of
an orbit/spiral path the approximation is tight near the object, and —
crucially — it is *ranking-faithful*: sequences whose frames do not
share one 3D scene (shuffled frames, per-frame identity drift) score
strictly worse than consistent ones, which is exactly what a serving
regression gate needs.

Math (world-from-camera ``R``, camera position ``T``, shared ``K``; the
``geometry/rays.py`` convention): a point ``X_i`` in camera-``i``
coordinates maps to camera ``j`` as ``X_j = R_rel X_i + t_rel`` with
``R_rel = R_j^T R_i`` and ``t_rel = R_j^T (T_i - T_j)``.  On the plane
``n^T X_i = d`` (``n = (0,0,1)``, ``d`` = target depth in camera ``i``)
this collapses to the homography

    H_{j<-i} = K (R_rel + t_rel n^T / d) K^{-1}

mapping pixel-center homogeneous coordinates of image ``i`` to image
``j``.  Pure host-side float64 numpy — scoring never touches a device.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["plane_homography", "warp_frame", "reprojection_consistency"]

#: Pairs whose valid-overlap fraction falls below this contribute no
#: error term (warping through a nearly-perpendicular plane or a
#: behind-the-camera target is noise, not signal).
MIN_VALID_FRAC = 0.05


def plane_homography(K: np.ndarray, R_i: np.ndarray, T_i: np.ndarray,
                     R_j: np.ndarray, T_j: np.ndarray,
                     target=(0.0, 0.0, 0.0)) -> np.ndarray:
    """``H_{j<-i}``: maps homogeneous pixel coords of view ``i`` to view
    ``j`` through the fronto-parallel plane at ``target``'s depth."""
    K = np.asarray(K, np.float64)
    R_i = np.asarray(R_i, np.float64)
    R_j = np.asarray(R_j, np.float64)
    T_i = np.asarray(T_i, np.float64)
    T_j = np.asarray(T_j, np.float64)
    target = np.asarray(target, np.float64)
    d = float((R_i.T @ (target - T_i))[2])   # target depth in camera i
    if d <= 1e-9:
        raise ValueError(
            f"target is behind (or on) camera i: depth {d:.3g}")
    R_rel = R_j.T @ R_i
    t_rel = R_j.T @ (T_i - T_j)
    n = np.array([0.0, 0.0, 1.0])
    return K @ (R_rel + np.outer(t_rel, n) / d) @ np.linalg.inv(K)


def _bilinear(img: np.ndarray, x: np.ndarray,
              y: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Sample ``img [H, W, C]`` at float array coords ``(y, x)``;
    returns ``(samples, in_bounds_mask)``."""
    H, W = img.shape[:2]
    valid = (x >= 0.0) & (x <= W - 1.0) & (y >= 0.0) & (y <= H - 1.0)
    x = np.clip(x, 0.0, W - 1.0)
    y = np.clip(y, 0.0, H - 1.0)
    x0 = np.floor(x).astype(np.int64)
    y0 = np.floor(y).astype(np.int64)
    x1 = np.minimum(x0 + 1, W - 1)
    y1 = np.minimum(y0 + 1, H - 1)
    wx = (x - x0)[..., None]
    wy = (y - y0)[..., None]
    out = ((1 - wy) * ((1 - wx) * img[y0, x0] + wx * img[y0, x1])
           + wy * ((1 - wx) * img[y1, x0] + wx * img[y1, x1]))
    return out, valid


def warp_frame(frame_j: np.ndarray, H_ji: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Predict view ``i`` from ``frame_j``: for every pixel of the
    target grid, project through ``H_{j<-i}`` and sample ``frame_j``
    bilinearly.  Returns ``(warped [H, W, C], valid [H, W])`` — valid
    means the projection landed in front of the camera and inside
    ``frame_j``."""
    frame_j = np.asarray(frame_j, np.float64)
    H, W = frame_j.shape[:2]
    u = np.arange(W, dtype=np.float64) + 0.5
    v = np.arange(H, dtype=np.float64) + 0.5
    uu, vv = np.meshgrid(u, v)
    px = np.stack([uu, vv, np.ones_like(uu)], axis=-1)      # [H, W, 3]
    proj = np.einsum("ij,hwj->hwi", np.asarray(H_ji, np.float64), px)
    w = proj[..., 2]
    front = w > 1e-9
    w_safe = np.where(front, w, 1.0)
    xj = proj[..., 0] / w_safe - 0.5
    yj = proj[..., 1] / w_safe - 0.5
    warped, in_bounds = _bilinear(frame_j, xj, yj)
    return warped, front & in_bounds


def reprojection_consistency(frames: np.ndarray, R: np.ndarray,
                             T: np.ndarray, K: np.ndarray,
                             target=(0.0, 0.0, 0.0),
                             pairs: Optional[Sequence[Tuple[int, int]]]
                             = None) -> dict:
    """Score the 3D consistency of an ordered frame sequence.

    ``frames [N, H, W, 3]`` in [-1, 1] (a guidance axis
    ``[N, B, H, W, 3]`` is accepted; lane 0 is scored), with per-frame
    poses ``R [N, 3, 3]`` / ``T [N, 3]`` and shared ``K``.  ``pairs``
    defaults to adjacent ``(i, i+1)`` — the small-baseline pairs where
    the plane approximation is tightest.  For each pair, frame ``j`` is
    warped into frame ``i``'s viewpoint and compared over the valid
    overlap; the headline numbers are means over pairs clearing
    :data:`MIN_VALID_FRAC`.

    Returns ``{"consistency_l1", "consistency_psnr", "valid_frac",
    "num_pairs", "pairs": [...]}`` — lower L1 / higher PSNR = more
    consistent.
    """
    frames = np.asarray(frames, np.float64)
    if frames.ndim == 5:
        frames = frames[:, 0]
    if frames.ndim != 4 or frames.shape[-1] != 3:
        raise ValueError(
            f"frames must be [N, H, W, 3] (or [N, B, H, W, 3]), got "
            f"{frames.shape}")
    R = np.asarray(R, np.float64)
    T = np.asarray(T, np.float64)
    N = frames.shape[0]
    if R.shape[0] != N or T.shape[0] != N:
        raise ValueError(
            f"{N} frames but {R.shape[0]} R / {T.shape[0]} T poses")
    if N < 2:
        raise ValueError("need at least 2 frames to score consistency")
    if pairs is None:
        pairs = [(i, i + 1) for i in range(N - 1)]
    per_pair: List[dict] = []
    l1s, psnrs, fracs = [], [], []
    for i, j in pairs:
        H_ji = plane_homography(K, R[i], T[i], R[j], T[j], target)
        warped, valid = warp_frame(frames[j], H_ji)
        frac = float(valid.mean())
        entry = {"i": int(i), "j": int(j), "valid_frac": frac}
        if frac >= MIN_VALID_FRAC:
            diff = (warped - frames[i])[valid]
            l1 = float(np.abs(diff).mean())
            mse = float((diff ** 2).mean())
            # Data range is 2.0 ([-1, 1]); cap like evaluation.psnr.
            psnr = float(10.0 * np.log10(4.0 / max(mse, 1e-10)))
            entry.update({"l1": l1, "psnr": psnr})
            l1s.append(l1)
            psnrs.append(psnr)
            fracs.append(frac)
        else:
            entry.update({"l1": None, "psnr": None})
        per_pair.append(entry)
    return {
        "consistency_l1": float(np.mean(l1s)) if l1s else None,
        "consistency_psnr": float(np.mean(psnrs)) if psnrs else None,
        "valid_frac": float(np.mean(fracs)) if fracs else 0.0,
        "num_pairs": len(l1s),
        "pairs": per_pair,
    }
