"""Image-quality metrics, pure jnp.

The reference publishes no quality numbers and ships no evaluation code
(SURVEY.md §5.5, §6) despite FID/PSNR being the paper's headline metrics —
this harness is a capability the TPU build adds.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def psnr(a: jnp.ndarray, b: jnp.ndarray, max_val: float = 2.0) -> jnp.ndarray:
    """Peak signal-to-noise ratio per image pair.

    ``a``, ``b``: ``[..., H, W, C]``; ``max_val`` is the data range (2.0
    for the framework's [-1, 1] images).  Returns ``[...]`` dB.
    """
    mse = jnp.mean(jnp.square(a - b), axis=(-3, -2, -1))
    return 10.0 * jnp.log10(max_val ** 2 / jnp.maximum(mse, 1e-12))


def _gaussian_kernel(size: int, sigma: float) -> jnp.ndarray:
    x = jnp.arange(size, dtype=jnp.float32) - (size - 1) / 2.0
    g = jnp.exp(-0.5 * (x / sigma) ** 2)
    return g / g.sum()


def ssim(a: jnp.ndarray, b: jnp.ndarray, max_val: float = 2.0,
         filter_size: int = 11, filter_sigma: float = 1.5,
         k1: float = 0.01, k2: float = 0.03) -> jnp.ndarray:
    """Structural similarity (Wang et al. 2004) with the standard 11x1
    separable Gaussian window.  ``a``, ``b``: ``[..., H, W, C]``; returns
    mean SSIM over pixels/channels per image, ``[...]``."""
    kern = _gaussian_kernel(filter_size, filter_sigma)

    def blur(x):
        # separable conv along H then W via tensordot-free moving window
        pad = filter_size // 2
        xp = jnp.pad(x, [(0, 0)] * (x.ndim - 3) + [(pad, pad), (0, 0),
                                                   (0, 0)], mode="edge")
        xh = sum(kern[i] * xp[..., i:i + x.shape[-3], :, :]
                 for i in range(filter_size))
        xp = jnp.pad(xh, [(0, 0)] * (x.ndim - 3) + [(0, 0), (pad, pad),
                                                    (0, 0)], mode="edge")
        return sum(kern[i] * xp[..., :, i:i + x.shape[-2], :]
                   for i in range(filter_size))

    c1 = (k1 * max_val) ** 2
    c2 = (k2 * max_val) ** 2
    mu_a, mu_b = blur(a), blur(b)
    var_a = blur(a * a) - mu_a ** 2
    var_b = blur(b * b) - mu_b ** 2
    cov = blur(a * b) - mu_a * mu_b
    num = (2 * mu_a * mu_b + c1) * (2 * cov + c2)
    den = (mu_a ** 2 + mu_b ** 2 + c1) * (var_a + var_b + c2)
    return jnp.mean(num / den, axis=(-3, -2, -1))
