"""Frechet Inception Distance harness.

FID = |mu_r - mu_g|^2 + tr(C_r + C_g - 2 (C_r C_g)^{1/2}) between Gaussian
fits to feature distributions of real and generated images.  The feature
extractor is pluggable: the canonical choice is InceptionV3 pool3; this
zero-egress image has no pretrained weights, so the default extractor is a
fixed random-projection + average-pool embedding (deterministic, seeded) —
statistically meaningful for *relative* comparisons within this framework,
and swappable for true Inception features by passing ``feature_fn``.

(The reference has no evaluation code at all — SURVEY.md §5.5.)
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class FIDStats:
    mu: np.ndarray      # [D]
    cov: np.ndarray     # [D, D]
    n: int


def default_feature_fn(dim: int = 256, seed: int = 0
                       ) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """Fixed random conv features: 4x4/4 patch embed -> ReLU -> global
    mean/std pool -> projection to ``dim``.  Deterministic given ``seed``."""
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))

    w_cache = {}

    def feats(imgs: jnp.ndarray) -> jnp.ndarray:
        C = imgs.shape[-1]
        if "w" not in w_cache:
            w_cache["w"] = jax.random.normal(
                k1, (4, 4, C, dim)) / np.sqrt(4 * 4 * C)
            w_cache["p"] = jax.random.normal(k2, (2 * dim, dim)) / np.sqrt(
                2 * dim)
        h = jax.lax.conv_general_dilated(
            imgs, w_cache["w"], window_strides=(4, 4), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        h = jax.nn.relu(h)
        pooled = jnp.concatenate([h.mean(axis=(1, 2)), h.std(axis=(1, 2))],
                                 axis=-1)
        return pooled @ w_cache["p"]

    return feats


def gaussian_stats(batches: Iterable[np.ndarray],
                   feature_fn: Optional[Callable] = None) -> FIDStats:
    """Streaming mean/cov of features over image batches ``[B, H, W, C]``."""
    feature_fn = feature_fn or default_feature_fn()
    # don't re-wrap an already-jitted extractor (callers jit once and
    # reuse the executable across the real/generated stats passes)
    f = (feature_fn if isinstance(feature_fn, jax.stages.Wrapped)
         else jax.jit(feature_fn))
    s = None
    for batch in batches:
        x = np.asarray(f(jnp.asarray(batch)), np.float64)
        if s is None:
            s = {"sum": np.zeros(x.shape[1]),
                 "outer": np.zeros((x.shape[1], x.shape[1])), "n": 0}
        s["sum"] += x.sum(0)
        s["outer"] += x.T @ x
        s["n"] += x.shape[0]
    if s is None or s["n"] < 2:
        raise ValueError("need at least 2 images for FID stats")
    mu = s["sum"] / s["n"]
    cov = (s["outer"] - s["n"] * np.outer(mu, mu)) / (s["n"] - 1)
    return FIDStats(mu=mu, cov=cov, n=s["n"])


def frechet_distance(a: FIDStats, b: FIDStats, eps: float = 1e-6) -> float:
    """``|mu_a-mu_b|^2 + tr(Ca + Cb - 2 (Ca Cb)^{1/2})`` with the symmetric
    sqrt trick: ``tr((Ca Cb)^{1/2}) = tr((Ca^{1/2} Cb Ca^{1/2})^{1/2})``."""
    diff = a.mu - b.mu

    # symmetric PSD square root via eigh
    def sqrtm_psd(m):
        vals, vecs = np.linalg.eigh(m)
        vals = np.clip(vals, 0.0, None)
        return (vecs * np.sqrt(vals)) @ vecs.T

    ca = a.cov + eps * np.eye(a.cov.shape[0])
    cb = b.cov + eps * np.eye(b.cov.shape[0])
    sa = sqrtm_psd(ca)
    inner = sa @ cb @ sa
    vals = np.clip(np.linalg.eigvalsh(inner), 0.0, None)
    tr_sqrt = float(np.sqrt(vals).sum())
    return float(diff @ diff + np.trace(ca) + np.trace(cb) - 2.0 * tr_sqrt)


def fid_from_stats(real: FIDStats, gen: FIDStats) -> float:
    return frechet_distance(real, gen)
