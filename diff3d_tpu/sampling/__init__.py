from diff3d_tpu.sampling.runtime import Sampler, save_image_grid

__all__ = ["Sampler", "save_image_grid"]
