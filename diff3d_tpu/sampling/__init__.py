from diff3d_tpu.sampling.runtime import (Sampler, record_capacity,
                                         save_image)

__all__ = ["Sampler", "record_capacity", "save_image"]
