"""Autoregressive novel-view synthesis with stochastic conditioning.

Capability parity with the reference sampler (``/root/reference/
sampling.py:129-184``): seed the record with the ground-truth first view,
then for every remaining pose run 256 reverse-diffusion steps, drawing a
fresh conditioning view from the record at *each* step, with the
guidance-weight sweep ``w = [0..7]`` as the batch axis; generated views are
appended to the record (later views condition on earlier generations) and
written as ``sampling/{step}/{gt,0..7}.png``.

TPU-native architecture (vs the reference's per-step host round-trips,
``sampling.py:97-103``):
  * the whole 256-step denoise loop is ONE compiled ``lax.scan``
    (:func:`diff3d_tpu.diffusion.sample_loop`) — the record is a fixed-size
    device array indexed by pre-sampled stochastic-conditioning choices,
    and the CFG cond/uncond double forward is folded into one 2B-batch
    model call;
  * the record buffer is DEVICE-RESIDENT across the autoregressive loop
    (:func:`diff3d_tpu.diffusion.sample_view`): each view step takes the
    record as a donated jit argument, writes its output in place via
    ``lax.dynamic_update_slice``, and returns the updated carry.  The
    Python view loop just threads device handles — zero per-view
    host->device re-upload (the pre-resident loop re-staged the whole
    ``[capacity, B, H, W, 3]`` buffer every view: O(views^2) transfer
    bytes and a host round-trip bubble per view), and ONE device->host
    fetch at the end of the object;
  * with an optional :class:`~diff3d_tpu.parallel.MeshEnv`, every
    object-batched entry point compiles with ``NamedSharding`` in/out
    specs — the object axis rides the mesh's ``data`` axis, params are
    placed per the ``replicated``/``fsdp`` policy — so
    ``synthesize_many``, ``eval_cli``, and the serving engine fan one
    batched scan over every attached chip.

The device-resident record contract (shared by offline and serving paths;
see DESIGN.md): ``record_R``/``record_T`` are pre-filled with ALL target
poses up front — the stochastic-conditioning draw only reads entries
``< record_len``, so entry ``record_len`` doubles as the pose of the view
being synthesised — and the per-object ``rng`` is carried on device and
split inside the compiled step, preserving the legacy host loop's exact
key stream (the serving bit-parity tests pin this).

The per-view unit of work is public API: :meth:`Sampler.step` (one object)
and :meth:`Sampler.step_many` (N objects, per-object view steps) run one
view's full reverse diffusion and return the updated record carry;
``synthesize``/``synthesize_many`` are thin host loops over them.  The
serving layer (``diff3d_tpu/serving``) drives ``step_many`` directly so
live requests at *different* autoregressive depths share one compiled scan
(continuous batching at view granularity).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.config import Config
from diff3d_tpu.diffusion import (SAMPLER_KINDS, sample_loop_prepare,
                                  sample_loop_scan, sample_view,
                                  sample_view_commit, schedule_start_index)
from diff3d_tpu.models import XUNet


def to_uint8(img: np.ndarray) -> np.ndarray:
    """[-1, 1] float -> [0, 255] uint8."""
    return np.clip((np.asarray(img) + 1.0) * 127.5, 0, 255).astype(np.uint8)


def save_image(path: str, img: np.ndarray) -> None:
    """Save one ``[H, W, 3]`` image in [-1, 1]; parent dirs created."""
    from PIL import Image

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    Image.fromarray(to_uint8(img)).save(path)


def record_capacity(n_views: int) -> int:
    """Record-buffer capacity for an object synthesised to ``n_views``
    total views.

    Rounds up to a power of two: the compiled scan's shape depends on the
    record capacity, so objects with different view counts share a
    logarithmic number of compilations instead of one each.  The
    stochastic-conditioning draw only sees the first ``record_len``
    entries, so padding never leaks into sampling.  The serving layer's
    shape buckets use the same function, so a served request compiles (and
    caches) the exact program the offline path uses.
    """
    if n_views < 2:
        raise ValueError(f"n_views={n_views}: need at least 2 views "
                         "(one conditioning + one target)")
    return 1 << (n_views - 1).bit_length()


class Sampler:
    """Runs the full autoregressive view loop for one object.

    Args:
      model: the X-UNet.
      params: trained parameters (typically the EMA pytree).  Held as the
        *default* — every compiled entry point takes params as a jit
        argument, so callers (checkpoint hot-swap in serving) may pass a
        different same-shaped pytree per call without recompiling.
      cfg: full config (diffusion.timesteps, guidance_weights, ...).
      scan_chunks: split each view's reverse-diffusion scan into this many
        consecutive device executions (bit-identical result — the RNG
        stream is carried; `test_sampling` pins it).  Keep 1 on
        direct-attached hardware; raise it where a single multi-minute
        execution trips an RPC deadline (the full-width 128^2 sampler
        over the dev tunnel needs ~4).
      mesh: optional :class:`~diff3d_tpu.parallel.MeshEnv`.  When given,
        the object-batched entry points compile with ``NamedSharding``
        in/out specs (object axis over the mesh's data axis, params per
        the config's ``replicated``/``fsdp``/``tp`` policy) and
        :attr:`lane_multiple` becomes the data-axis size — callers of
        :meth:`step_many` must pass an object count divisible by it
        (``synthesize_many`` pads internally; the serving engine rounds
        its lane counts).  With ``cfg.mesh.context_parallel`` on, the
        single-object path additionally threads
        ``MeshEnv.activation_constraint()`` through the model.
      sampler_kind: reverse-process update — ``"ancestral"`` (the paper's
        stochastic sampler) or ``"ddim"`` (deterministic eta=0).
      steps: number of reverse steps per view; must divide
        ``cfg.diffusion.timesteps`` (the k-step grid is an exact subset
        of the dense grid — see
        :func:`~diff3d_tpu.diffusion.sample_schedule_ts`).  ``None``
        (default) runs the full grid, bit-identical to the historical
        sampler.
      start_t: truncated-schedule (cascade refine) entry point — must be
        a grid point of the ``steps``-step schedule.  When set, every
        view step takes an extra ``[B, H, W, 3]`` ``draft`` operand: the
        draft is renoised to ``start_t`` via the forward process and only
        the remaining reverse steps run.  ``start_t=1.0`` ignores the
        draft (the VP prior at t=1 is exactly N(0,1)) and reproduces the
        untruncated sampler bit-for-bit.  Requires ``scan_chunks == 1``;
        the offline ``synthesize*`` loops have no draft source and
        refuse a truncated sampler.
    """

    def __init__(self, model: XUNet, params, cfg: Config,
                 scan_chunks: int = 1, mesh=None,
                 sampler_kind: str = "ancestral",
                 steps: Optional[int] = None,
                 start_t: Optional[float] = None):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        self.w = jnp.asarray(cfg.diffusion.guidance_weights, jnp.float32)

        d = cfg.diffusion
        if sampler_kind not in SAMPLER_KINDS:
            raise ValueError(
                f"sampler_kind={sampler_kind!r} not in {SAMPLER_KINDS}")
        self.sampler_kind = sampler_kind
        steps = d.timesteps if steps is None else int(steps)
        if steps < 1 or d.timesteps % steps:
            raise ValueError(
                f"steps={steps} must be a positive divisor of "
                f"timesteps={d.timesteps}")
        self.steps = steps
        if scan_chunks < 1 or steps % scan_chunks:
            raise ValueError(
                f"scan_chunks={scan_chunks} must divide the effective "
                f"step count steps={steps}")
        self.scan_chunks = scan_chunks
        self.start_t = None if start_t is None else float(start_t)
        self.start_index = 0
        if self.start_t is not None:
            # Raises ScheduleError for an off-grid start_t.
            self.start_index = schedule_start_index(
                steps, self.start_t, timesteps=d.timesteps)
            if scan_chunks != 1:
                raise ValueError(
                    f"start_t={self.start_t} (truncated refinement) "
                    f"requires scan_chunks=1, got {scan_chunks} — the "
                    "chunk split assumes the full step count")

        # Sharding vocabulary.  lane_multiple is the divisibility quantum
        # of the object axis: NamedSharding rejects a leading dim not
        # divisible by the data-axis size, so batched callers round up to
        # a multiple (padding lanes carry live data and are discarded).
        constrain = None
        if mesh is not None:
            self.lane_multiple = mesh.data_size
            self._obj = mesh.batch()             # object axis over 'data'
            self._rep = mesh.replicated()
            self._param_shardings = mesh.params(params)
            params = jax.device_put(params, self._param_shardings)
            if cfg.mesh.context_parallel:
                constrain = mesh.activation_constraint()
        else:
            self.lane_multiple = 1
            self._obj = self._rep = self._param_shardings = None
        self.params = params

        # params is a jit ARGUMENT, not a closure constant: closing over
        # it would bake the full weight set into the compiled program
        # (hundreds of MB at srn64 scale) and force a recompile for every
        # checkpoint swap.
        def denoise_with(params, constrain=None):
            def denoise(batch, cond_mask):
                return model.apply({"params": params}, batch,
                                   cond_mask=cond_mask, constrain=constrain)
            return denoise

        # The device-resident view step: (params, record carry) ->
        # (out, record carry').  record_imgs is DONATED — the
        # dynamic_update_slice writes in place on device.
        def run_view(params, record_imgs, record_R, record_T, record_len,
                     K, rng, draft=None, constrain=None):
            return sample_view(
                denoise_with(params, constrain), record_imgs=record_imgs,
                record_R=record_R, record_T=record_T,
                record_len=record_len, K=K, w=self.w, rng=rng,
                timesteps=d.timesteps, logsnr_min=d.logsnr_min,
                logsnr_max=d.logsnr_max, clip_x0=d.clip_x0,
                steps=self.steps, sampler_kind=self.sampler_kind,
                start_t=self.start_t, draft=draft)

        def _specs(data_sharding, n_data_args, n_outs):
            """jit sharding kwargs (empty off-mesh)."""
            if mesh is None:
                return {}
            return {
                "in_shardings": ((self._param_shardings,)
                                 + (data_sharding,) * n_data_args),
                "out_shardings": ((data_sharding,) * n_outs
                                  if n_outs > 1 else data_sharding),
            }

        if scan_chunks == 1 and self.start_t is not None:
            # Truncated refinement: the draft rides as a trailing data
            # operand so the program stays params-first (shardcheck's
            # params_argnum contract).
            self._run_view = jax.jit(
                lambda p, ri, rR, rT, rl, K, rng, dr: run_view(
                    p, ri, rR, rT, rl, K, rng, draft=dr,
                    constrain=constrain),
                donate_argnums=(1,), **_specs(self._rep, 7, 4))
        elif scan_chunks == 1:
            self._run_view = jax.jit(
                lambda p, ri, rR, rT, rl, K, rng: run_view(
                    p, ri, rR, rT, rl, K, rng, constrain=constrain),
                donate_argnums=(1,), **_specs(self._rep, 6, 4))
        else:
            # Chunked pieces: `prepare` + chunks + `commit` compose to
            # exactly `run_view` (scan over xs == fold of scans over xs
            # slices; the rng split and the record write bracket them),
            # but each chunk is its own device execution.  All pieces
            # take/return device carries, so the chunked path is equally
            # host-transfer-free between views.
            def prepare_view(record_len, rng, record_imgs):
                rng, k = jax.random.split(rng)
                state, xs = sample_loop_prepare(
                    record_len=record_len, rng=k, timesteps=d.timesteps,
                    shape=(self.w.shape[0],) + record_imgs.shape[-3:],
                    logsnr_min=d.logsnr_min, logsnr_max=d.logsnr_max,
                    steps=self.steps)
                return state, xs, rng

            def chunk_view(params, state, xs, record_imgs, record_R,
                           record_T, record_len, K, constrain=None):
                return sample_loop_scan(
                    denoise_with(params, constrain), state, xs,
                    record_imgs=record_imgs, record_R=record_R,
                    record_T=record_T, target_R=record_R[record_len],
                    target_T=record_T[record_len], K=K, w=self.w,
                    logsnr_max=d.logsnr_max, clip_x0=d.clip_x0,
                    deterministic=(self.sampler_kind == "ddim"))

            n_per = self.steps // scan_chunks
            sh = {} if mesh is None else {"out_shardings": self._rep}
            jit_prepare = jax.jit(
                prepare_view,
                **({} if mesh is None
                   else {"in_shardings": (self._rep,) * 3, **sh}))
            jit_chunk = jax.jit(
                lambda p, s, xs, ri, rR, rT, rl, K: chunk_view(
                    p, s, xs, ri, rR, rT, rl, K, constrain=constrain),
                **({} if mesh is None
                   else {"in_shardings": (self._param_shardings,)
                         + (self._rep,) * 7, **sh}))
            jit_commit = jax.jit(
                sample_view_commit, donate_argnums=(0,),
                **({} if mesh is None
                   else {"in_shardings": (self._rep,) * 3,
                         "out_shardings": (self._rep,) * 3}))

            def run_view_chunked(params, record_imgs, record_R, record_T,
                                 record_len, K, rng):
                state, xs, rng = jit_prepare(record_len, rng, record_imgs)
                for c in range(scan_chunks):
                    sl = jax.tree.map(
                        lambda x: x[c * n_per:(c + 1) * n_per], xs)
                    state = jit_chunk(params, state, sl, record_imgs,
                                      record_R, record_T, record_len, K)
                out, record_imgs, record_len = jit_commit(
                    record_imgs, record_len, state.img)
                return out, record_imgs, record_len, rng

            self._run_view = run_view_chunked

        # Object-batched variant: vmap folds an extra leading object axis
        # into every model call (N*2B examples instead of 2B), so N
        # independent objects' guidance sweeps share one compiled scan —
        # at 64^2 the per-object batch of 8 underfills the chip and the
        # per-object loop was the eval cost center.  record_len is batched
        # per object (in_axes 0): the offline path passes the same step
        # for every object, while the serving engine mixes requests at
        # different autoregressive depths in one device batch.  On a mesh
        # the object axis is sharded over 'data', so one launch spans all
        # chips.  (The context-parallel constrain hook is single-object
        # only: under vmap its [B, F, H, W, C] spec would land on the
        # wrong axes.)
        if scan_chunks == 1 and self.start_t is not None:
            def run_view_draft(p, ri, rR, rT, rl, K, rng, dr):
                return run_view(p, ri, rR, rT, rl, K, rng, draft=dr)
            self._run_view_many = jax.jit(
                jax.vmap(run_view_draft,
                         in_axes=(None, 0, 0, 0, 0, 0, 0, 0)),
                donate_argnums=(1,), **_specs(self._obj, 7, 4))
        elif scan_chunks == 1:
            self._run_view_many = jax.jit(
                jax.vmap(run_view, in_axes=(None, 0, 0, 0, 0, 0, 0)),
                donate_argnums=(1,), **_specs(self._obj, 6, 4))
        else:
            jit_prepare_many = jax.jit(
                jax.vmap(prepare_view, in_axes=(0, 0, 0)),
                **({} if mesh is None
                   else {"in_shardings": (self._obj,) * 3,
                         "out_shardings": self._obj}))
            jit_chunk_many = jax.jit(
                jax.vmap(chunk_view, in_axes=(None, 0, 0, 0, 0, 0, 0, 0)),
                **({} if mesh is None
                   else {"in_shardings": (self._param_shardings,)
                         + (self._obj,) * 7,
                         "out_shardings": self._obj}))
            jit_commit_many = jax.jit(
                jax.vmap(sample_view_commit, in_axes=(0, 0, 0)),
                donate_argnums=(0,),
                **({} if mesh is None
                   else {"in_shardings": (self._obj,) * 3,
                         "out_shardings": (self._obj,) * 3}))
            n_per_many = self.steps // scan_chunks

            def run_view_many_chunked(params, record_imgs, record_R,
                                      record_T, record_len, K, rngs):
                state, xs, rngs = jit_prepare_many(record_len, rngs,
                                                   record_imgs)
                for c in range(scan_chunks):
                    sl = jax.tree.map(
                        lambda x: x[:, c * n_per_many:(c + 1) * n_per_many],
                        xs)
                    state = jit_chunk_many(params, state, sl, record_imgs,
                                           record_R, record_T, record_len,
                                           K)
                out, record_imgs, record_len = jit_commit_many(
                    record_imgs, record_len, state.img)
                return out, record_imgs, record_len, rngs

            self._run_view_many = run_view_many_chunked

    @property
    def model_calls_per_view(self) -> int:
        """Denoiser invocations per synthesised view (each reverse step is
        one 2B-batched CFG call) — the latency dial the step schedule
        turns.  A truncated (``start_t``) sampler runs only the grid tail,
        so the truncated steps are subtracted."""
        return self.steps - self.start_index

    # ------------------------------------------------------------------
    # Per-view step API (public): one view's full reverse diffusion.
    # ------------------------------------------------------------------

    def _check_draft(self, draft, batched: bool):
        """The draft operand is exactly as optional as ``start_t``: a
        truncated sampler cannot run without one, an untruncated sampler
        has no operand slot for one."""
        if self.start_t is not None and draft is None:
            raise ValueError(
                f"this sampler was built with start_t={self.start_t}: "
                "every view step needs the "
                + ("[N, B, H, W, 3] drafts" if batched
                   else "[B, H, W, 3] draft")
                + " operand to renoise from")
        if self.start_t is None and draft is not None:
            raise ValueError(
                "draft passed to an untruncated sampler — build the "
                "Sampler with start_t to enable cascade refinement")

    def step(self, record_imgs, record_R, record_T, step, K, rng, *,
             draft=None, params=None):
        """One view's reverse diffusion for ONE object, device-resident.

        Args:
          record_imgs / record_R / record_T: ``[capacity, B, H, W, 3]`` /
            ``[capacity, 3, 3]`` / ``[capacity, 3]`` record buffers
            (see :func:`record_capacity`).  The pose buffers must be
            pre-filled with every view's pose — entry ``step`` is the
            target pose of the view being synthesised.
          step: number of valid record entries (== the view index being
            synthesised).
          K: ``[3, 3]`` intrinsics.
          rng: the per-object PRNG carry (NOT a per-view key — the
            per-view key is split off inside the compiled step, exactly
            like the legacy host loop did).
          params: optional parameter pytree overriding the constructor
            default (same treedef/shapes — no recompile).
        Returns:
          ``(out, record_imgs, step + 1, rng)`` — ``out`` is the
          ``[B, H, W, 3]`` generated view (device array; callers block),
          and the rest is the updated record carry for the next view.
          ``record_imgs`` is DONATED: a passed-in device buffer is
          invalidated and the returned one must be used instead (numpy
          inputs are first copied into an XLA-owned buffer — see
          :meth:`_owned` — so the caller's array is unaffected).
        """
        self._check_draft(draft, batched=False)
        p = self.params if params is None else params
        args = (p, self._owned(record_imgs), jnp.asarray(record_R),
                jnp.asarray(record_T), jnp.asarray(step, jnp.int32),
                jnp.asarray(K), jnp.asarray(rng))
        if self.start_t is not None:
            args += (jnp.asarray(draft, jnp.float32),)
        return self._run_view(*args)

    def step_many(self, record_imgs, record_R, record_T, steps, K, rngs,
                  *, drafts=None, params=None):
        """One view step for N objects in ONE batched program.

        Everything gains a leading object axis; ``steps`` is ``[N]`` —
        per-object record lengths, so co-batched objects may sit at
        different autoregressive depths (the serving engine's continuous
        batching relies on this).  ``rngs`` is ``[N]`` stacked per-object
        PRNG carries (split per view inside, like :meth:`step`).  On a
        mesh, N must be a multiple of :attr:`lane_multiple` (the sharded
        program cannot split a non-divisible object axis).  Returns
        ``(out [N, B, H, W, 3], record_imgs, steps + 1, rngs)`` with the
        same donation contract as :meth:`step`.
        """
        n = int(np.shape(record_imgs)[0])
        if n % self.lane_multiple:
            raise ValueError(
                f"step_many: {n} objects is not a multiple of the mesh's "
                f"data-axis size {self.lane_multiple} — pad the batch "
                "(repeat a live lane; padded outputs are discarded) or "
                "use synthesize_many, which pads internally")
        self._check_draft(drafts, batched=True)
        p = self.params if params is None else params
        args = (p, self._owned(record_imgs), jnp.asarray(record_R),
                jnp.asarray(record_T), jnp.asarray(steps, jnp.int32),
                jnp.asarray(K), jnp.asarray(rngs))
        if self.start_t is not None:
            args += (jnp.asarray(drafts, jnp.float32),)
        return self._run_view_many(*args)

    def lower_step_many(self, lanes: int, capacity: int, *,
                        H: Optional[int] = None, W: Optional[int] = None):
        """Lower the :meth:`step_many` program on ABSTRACT args (no
        buffers staged) — the analysis hook shardcheck and bench use to
        audit the compiled scan's collectives/dtypes per shape bucket.

        ``lanes`` is the object count N (must satisfy the same
        :attr:`lane_multiple` divisibility as a real call), ``capacity``
        the record capacity (:func:`record_capacity`).  Returns a
        ``jax.stages.Lowered``.  Only the single-execution path
        (``scan_chunks == 1``) is one program; the chunked path is a
        Python composition and has no single lowering.
        """
        if self.scan_chunks != 1:
            raise ValueError(
                "lower_step_many: scan_chunks="
                f"{self.scan_chunks} composes multiple programs in "
                "Python; lower a scan_chunks=1 sampler instead")
        if lanes % self.lane_multiple:
            raise ValueError(
                f"lower_step_many: lanes={lanes} is not a multiple of "
                f"the mesh's data-axis size {self.lane_multiple}")
        B = int(self.w.shape[0])
        H = self.cfg.model.H if H is None else int(H)
        W = self.cfg.model.W if W is None else int(W)
        f32, i32, u32 = jnp.float32, jnp.int32, jnp.uint32
        sds = jax.ShapeDtypeStruct
        abstract_params = jax.tree.map(
            lambda x: sds(jnp.shape(x), x.dtype), self.params)
        abstract_args = [
            abstract_params,
            sds((lanes, capacity, B, H, W, 3), f32),
            sds((lanes, capacity, 3, 3), f32),
            sds((lanes, capacity, 3), f32),
            sds((lanes,), i32),
            sds((lanes, 3, 3), f32),
            sds((lanes, 2), u32)]
        if self.start_t is not None:
            abstract_args.append(sds((lanes, B, H, W, 3), f32))
        return self._run_view_many.lower(*abstract_args)

    # ------------------------------------------------------------------
    # Offline loops: thin host loops threading the device-resident carry.
    # ------------------------------------------------------------------

    def _record_init(self, imgs0, R, T, n_views):
        """Host-side record build: view 0 seeded, ALL poses pre-filled
        (the device-resident contract — see the module docstring)."""
        B = int(self.w.shape[0])
        H, W = imgs0.shape[-3:-1]
        capacity = record_capacity(n_views) if n_views > 1 else 1
        record_imgs = np.zeros((capacity, B, H, W, 3), np.float32)
        record_R = np.zeros((capacity, 3, 3), np.float32)
        record_T = np.zeros((capacity, 3), np.float32)
        record_imgs[0] = imgs0[None]
        record_R[:n_views] = R[:n_views]
        record_T[:n_views] = T[:n_views]
        return record_imgs, record_R, record_T

    def _owned(self, x, sharding=None):
        """XLA-owned device upload of a potentially-donated operand.

        ``jnp.asarray``/``device_put`` may zero-copy ALIAS an aligned
        numpy buffer (CPU backend); the view-step programs DONATE the
        record carry, and donating such an alias frees memory the XLA
        allocator does not own — heap corruption that surfaces far from
        here.  Host inputs are therefore copied into an XLA-allocated
        buffer; device arrays pass through untouched, so the
        steady-state loop still threads donated handles copy-free.
        """
        if isinstance(x, jax.Array):
            return x
        arr = (jax.device_put(x, sharding)
               if self.mesh is not None and sharding is not None
               else jnp.asarray(x))
        return jnp.copy(arr)

    def _put(self, x, sharding):
        return self._owned(x, sharding)

    def _check_no_truncation(self, entry: str) -> None:
        if self.start_t is not None:
            raise ValueError(
                f"{entry}: this sampler was built with start_t="
                f"{self.start_t} (truncated refinement) and needs a draft "
                "per view; the offline loops have no draft source — use "
                "CascadeSampler (diff3d_tpu.cascade) or the step API")

    def synthesize(self, views: Dict[str, np.ndarray], rng: jax.Array,
                   out_dir: Optional[str] = None,
                   max_views: Optional[int] = None) -> np.ndarray:
        """Autoregressively synthesise every view of ``views`` (the dict
        produced by ``SRNDataset.all_views``) from view 0.

        The record carry stays on device for the whole loop; the only
        device->host traffic is ONE fetch of the generated views at the
        end (PNGs, when requested, are written from that fetch).

        Returns ``[n_views-1, B, H, W, 3]`` generated images (B = number
        of guidance weights).  When ``out_dir`` is given, saves
        ``{out_dir}/{step}/gt.png`` and ``{out_dir}/{step}/{i}.png`` per
        view — the reference's output layout (``sampling.py:179-182``).
        """
        self._check_no_truncation("synthesize")
        imgs = np.asarray(views["imgs"], np.float32)
        R = np.asarray(views["R"], np.float32)
        T = np.asarray(views["T"], np.float32)
        K = np.asarray(views["K"], np.float32)
        n_views = imgs.shape[0] if max_views is None else min(
            imgs.shape[0], max_views)
        B = int(self.w.shape[0])
        H, W = imgs.shape[1:3]
        if n_views < 2:
            return np.zeros((0, B, H, W, 3), np.float32)

        record_imgs, record_R, record_T = self._record_init(
            imgs[0], R, T, n_views)

        # One-time upload of the carry; after this the loop only threads
        # returned device handles (rec_i is donated each step and written
        # in place).
        rec_i = self._put(record_imgs, self._rep)
        rec_R = self._put(record_R, self._rep)
        rec_T = self._put(record_T, self._rep)
        K_d = self._put(K, self._rep)
        step_d = self._put(np.asarray(1, np.int32), self._rep)
        rng_d = self._put(np.asarray(rng), self._rep)
        for _ in range(1, n_views):
            _, rec_i, step_d, rng_d = self._run_view(
                self.params, rec_i, rec_R, rec_T, step_d, K_d, rng_d)
        # Single fetch: slice the generated views on device, pull once.
        outs = np.asarray(jax.block_until_ready(rec_i[1:n_views]))

        if out_dir is not None:
            for step in range(1, n_views):
                save_image(os.path.join(out_dir, str(step), "gt.png"),
                           imgs[step])
                for i in range(B):
                    save_image(os.path.join(out_dir, str(step), f"{i}.png"),
                               outs[step - 1, i])
        return outs

    def synthesize_many(self, views_list: Sequence[Dict[str, np.ndarray]],
                        rngs: Sequence[jax.Array],
                        max_views: Optional[int] = None) -> np.ndarray:
        """Autoregressively synthesise N objects' views in ONE batched
        program (objects are independent — the reference scores them
        strictly sequentially, ``sampling.py:169-184``; here the object
        axis becomes an extra batch dim on every model call, sharded over
        the mesh's data axis when a mesh is attached).

        ``rngs`` holds one key per object.  Given the same per-object key,
        the per-object rng stream is identical to a sequential
        ``synthesize(views, key)`` call, so results match the sequential
        path to float tolerance (XLA may tile the larger batch
        differently, so bitwise equality is not guaranteed).

        On a mesh, N is padded internally to a multiple of
        :attr:`lane_multiple` by repeating object 0 (live data — zero
        lanes would run denormal-slow); padded outputs are discarded.

        Every object contributes ``n_views = min(min_i views_i,
        max_views)`` views — batch objects with equal view counts to avoid
        truncation.  Returns ``[N, n_views-1, B, H, W, 3]``.
        """
        self._check_no_truncation("synthesize_many")
        N = len(views_list)
        assert N == len(rngs)
        n_views = min(v["imgs"].shape[0] for v in views_list)
        if max_views is not None:
            n_views = min(n_views, max_views)
        B = int(self.w.shape[0])
        H, W = views_list[0]["imgs"].shape[1:3]
        if n_views < 2:
            return np.zeros((N, 0, B, H, W, 3), np.float32)

        mult = self.lane_multiple
        pad_idx = list(range(N)) + [0] * (-N % mult)
        recs = [self._record_init(
                    np.asarray(views_list[i]["imgs"][0], np.float32),
                    np.asarray(views_list[i]["R"], np.float32),
                    np.asarray(views_list[i]["T"], np.float32), n_views)
                for i in pad_idx]
        record_imgs = np.stack([r[0] for r in recs])
        record_R = np.stack([r[1] for r in recs])
        record_T = np.stack([r[2] for r in recs])
        Ks = np.stack([np.asarray(views_list[i]["K"], np.float32)
                       for i in pad_idx])
        keys = np.stack([np.asarray(rngs[i]) for i in pad_idx])
        steps = np.full((len(pad_idx),), 1, np.int32)

        rec_i = self._put(record_imgs, self._obj)
        rec_R = self._put(record_R, self._obj)
        rec_T = self._put(record_T, self._obj)
        Ks_d = self._put(Ks, self._obj)
        steps_d = self._put(steps, self._obj)
        keys_d = self._put(keys, self._obj)
        for _ in range(1, n_views):
            _, rec_i, steps_d, keys_d = self._run_view_many(
                self.params, rec_i, rec_R, rec_T, steps_d, Ks_d, keys_d)
        # Single fetch: drop padding lanes + the seeded view 0 on device.
        return np.asarray(jax.block_until_ready(rec_i[:N, 1:n_views]))
