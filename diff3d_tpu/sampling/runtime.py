"""Autoregressive novel-view synthesis with stochastic conditioning.

Capability parity with the reference sampler (``/root/reference/
sampling.py:129-184``): seed the record with the ground-truth first view,
then for every remaining pose run 256 reverse-diffusion steps, drawing a
fresh conditioning view from the record at *each* step, with the
guidance-weight sweep ``w = [0..7]`` as the batch axis; generated views are
appended to the record (later views condition on earlier generations) and
written as ``sampling/{step}/{gt,0..7}.png``.

TPU-native architecture (vs the reference's per-step host round-trips,
``sampling.py:97-103``):
  * the whole 256-step denoise loop is ONE compiled ``lax.scan``
    (:func:`diff3d_tpu.diffusion.sample_loop`) — the record is a fixed-size
    device array indexed by pre-sampled stochastic-conditioning choices,
    and the CFG cond/uncond double forward is folded into one 2B-batch
    model call;
  * the Python view loop only swaps the record buffer between scans, so
    one jit compilation serves every view.

The per-view unit of work is public API: :meth:`Sampler.step` (one object)
and :meth:`Sampler.step_many` (N objects, per-object view steps) run one
view's full reverse diffusion; ``synthesize``/``synthesize_many`` are thin
host loops over them.  The serving layer (``diff3d_tpu/serving``) drives
``step_many`` directly so live requests at *different* autoregressive
depths share one compiled scan (continuous batching at view granularity).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.config import Config
from diff3d_tpu.diffusion import (sample_loop, sample_loop_prepare,
                                  sample_loop_scan)
from diff3d_tpu.models import XUNet


def to_uint8(img: np.ndarray) -> np.ndarray:
    """[-1, 1] float -> [0, 255] uint8."""
    return np.clip((np.asarray(img) + 1.0) * 127.5, 0, 255).astype(np.uint8)


def save_image(path: str, img: np.ndarray) -> None:
    """Save one ``[H, W, 3]`` image in [-1, 1]; parent dirs created."""
    from PIL import Image

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    Image.fromarray(to_uint8(img)).save(path)


def record_capacity(n_views: int) -> int:
    """Record-buffer capacity for an object synthesised to ``n_views``
    total views.

    Rounds up to a power of two: the compiled scan's shape depends on the
    record capacity, so objects with different view counts share a
    logarithmic number of compilations instead of one each.  The
    stochastic-conditioning draw only sees the first ``record_len``
    entries, so padding never leaks into sampling.  The serving layer's
    shape buckets use the same function, so a served request compiles (and
    caches) the exact program the offline path uses.
    """
    if n_views < 2:
        raise ValueError(f"n_views={n_views}: need at least 2 views "
                         "(one conditioning + one target)")
    return 1 << (n_views - 1).bit_length()


class Sampler:
    """Runs the full autoregressive view loop for one object.

    Args:
      model: the X-UNet.
      params: trained parameters (typically the EMA pytree).  Held as the
        *default* — every compiled entry point takes params as a jit
        argument, so callers (checkpoint hot-swap in serving) may pass a
        different same-shaped pytree per call without recompiling.
      cfg: full config (diffusion.timesteps, guidance_weights, ...).
      scan_chunks: split each view's reverse-diffusion scan into this many
        consecutive device executions (bit-identical result — the RNG
        stream is carried; `test_sampling` pins it).  Keep 1 on
        direct-attached hardware; raise it where a single multi-minute
        execution trips an RPC deadline (the full-width 128^2 sampler
        over the dev tunnel needs ~4).
    """

    def __init__(self, model: XUNet, params, cfg: Config,
                 scan_chunks: int = 1):
        self.model = model
        self.params = params
        self.cfg = cfg
        self.w = jnp.asarray(cfg.diffusion.guidance_weights, jnp.float32)

        d = cfg.diffusion
        if scan_chunks < 1 or d.timesteps % scan_chunks:
            raise ValueError(
                f"scan_chunks={scan_chunks} must divide "
                f"timesteps={d.timesteps}")
        self.scan_chunks = scan_chunks

        # params is a jit ARGUMENT, not a closure constant: closing over
        # it would bake the full weight set into the compiled program
        # (hundreds of MB at srn64 scale) and force a recompile for every
        # checkpoint swap.
        def run(params, record_imgs, record_R, record_T, record_len,
                target_R, target_T, K, rng):
            def denoise(batch, cond_mask):
                return model.apply({"params": params}, batch,
                                   cond_mask=cond_mask)

            return sample_loop(
                denoise, record_imgs=record_imgs, record_R=record_R,
                record_T=record_T, record_len=record_len,
                target_R=target_R, target_T=target_T, K=K, w=self.w,
                rng=rng, timesteps=d.timesteps, logsnr_min=d.logsnr_min,
                logsnr_max=d.logsnr_max, clip_x0=d.clip_x0)

        # Chunked pieces: `prepare` + `chunk` compose to exactly `run`
        # (scan over xs == fold of scans over xs slices), but each chunk
        # is its own device execution.
        def prepare(record_len, rng, record_imgs):
            return sample_loop_prepare(
                record_len=record_len, rng=rng, timesteps=d.timesteps,
                shape=(self.w.shape[0],) + record_imgs.shape[-3:],
                logsnr_min=d.logsnr_min, logsnr_max=d.logsnr_max)

        def chunk(params, state, xs, record_imgs, record_R, record_T,
                  target_R, target_T, K):
            def denoise(batch, cond_mask):
                return model.apply({"params": params}, batch,
                                   cond_mask=cond_mask)

            return sample_loop_scan(
                denoise, state, xs, record_imgs=record_imgs,
                record_R=record_R, record_T=record_T, target_R=target_R,
                target_T=target_T, K=K, w=self.w,
                logsnr_max=d.logsnr_max, clip_x0=d.clip_x0)

        if scan_chunks == 1:
            self._run = jax.jit(run)
        else:
            jit_prepare = jax.jit(prepare)
            jit_chunk = jax.jit(chunk)
            n_per = d.timesteps // scan_chunks

            def run_chunked(params, record_imgs, record_R, record_T,
                            record_len, target_R, target_T, K, rng):
                state, xs = jit_prepare(record_len, rng, record_imgs)
                for c in range(scan_chunks):
                    sl = jax.tree.map(
                        lambda x: x[c * n_per:(c + 1) * n_per], xs)
                    state = jit_chunk(params, state, sl, record_imgs,
                                      record_R, record_T, target_R,
                                      target_T, K)
                return state.img

            self._run = run_chunked
        # Object-batched variant: vmap folds an extra leading object axis
        # into every model call (N*2B examples instead of 2B), so N
        # independent objects' guidance sweeps share one compiled scan —
        # at 64^2 the per-object batch of 8 underfills the chip and the
        # per-object loop was the eval cost center.  record_len is batched
        # per object (in_axes 0): the offline path passes the same step
        # for every object, while the serving engine mixes requests at
        # different autoregressive depths in one device batch.
        if scan_chunks == 1:
            self._run_many = jax.jit(jax.vmap(
                run, in_axes=(None, 0, 0, 0, 0, 0, 0, 0, 0)))
        else:
            jit_prepare_many = jax.jit(jax.vmap(prepare,
                                                in_axes=(0, 0, 0)))
            jit_chunk_many = jax.jit(jax.vmap(
                chunk, in_axes=(None, 0, 0, 0, 0, 0, 0, 0, 0)))
            n_per_many = d.timesteps // scan_chunks

            def run_many_chunked(params, record_imgs, record_R, record_T,
                                 record_len, target_R, target_T, K, rngs):
                state, xs = jit_prepare_many(record_len, rngs, record_imgs)
                for c in range(scan_chunks):
                    sl = jax.tree.map(
                        lambda x: x[:, c * n_per_many:(c + 1) * n_per_many],
                        xs)
                    state = jit_chunk_many(
                        params, state, sl, record_imgs, record_R,
                        record_T, target_R, target_T, K)
                return state.img

            self._run_many = run_many_chunked

    # ------------------------------------------------------------------
    # Per-view step API (public): one view's full reverse diffusion.
    # ------------------------------------------------------------------

    def step(self, record_imgs, record_R, record_T, step, target_R,
             target_T, K, key, *, params=None):
        """One view's reverse diffusion for ONE object.

        Args:
          record_imgs / record_R / record_T: ``[capacity, B, H, W, 3]`` /
            ``[capacity, 3, 3]`` / ``[capacity, 3]`` record buffers
            (see :func:`record_capacity`).
          step: number of valid record entries (== the view index being
            synthesised).
          target_R / target_T: pose of the view to synthesise.
          K: ``[3, 3]`` intrinsics.
          key: per-view PRNG key.
          params: optional parameter pytree overriding the constructor
            default (same treedef/shapes — no recompile).
        Returns:
          ``[B, H, W, 3]`` device array (not fetched; callers block).
        """
        p = self.params if params is None else params
        return self._run(p, jnp.asarray(record_imgs),
                         jnp.asarray(record_R), jnp.asarray(record_T),
                         jnp.asarray(step), jnp.asarray(target_R),
                         jnp.asarray(target_T), jnp.asarray(K), key)

    def step_many(self, record_imgs, record_R, record_T, steps, target_R,
                  target_T, K, keys, *, params=None):
        """One view step for N objects in ONE batched program.

        Everything gains a leading object axis; ``steps`` is ``[N]`` —
        per-object record lengths, so co-batched objects may sit at
        different autoregressive depths (the serving engine's continuous
        batching relies on this).  ``keys`` is ``[N]`` stacked PRNG keys.
        Returns ``[N, B, H, W, 3]`` (device array).
        """
        p = self.params if params is None else params
        return self._run_many(
            p, jnp.asarray(record_imgs), jnp.asarray(record_R),
            jnp.asarray(record_T), jnp.asarray(steps),
            jnp.asarray(target_R), jnp.asarray(target_T),
            jnp.asarray(K), keys)

    # ------------------------------------------------------------------
    # Offline loops: thin host loops over the step API.
    # ------------------------------------------------------------------

    def synthesize(self, views: Dict[str, np.ndarray], rng: jax.Array,
                   out_dir: Optional[str] = None,
                   max_views: Optional[int] = None) -> np.ndarray:
        """Autoregressively synthesise every view of ``views`` (the dict
        produced by ``SRNDataset.all_views``) from view 0.

        Returns ``[n_views-1, B, H, W, 3]`` generated images (B = number of
        guidance weights).  When ``out_dir`` is given, saves
        ``{out_dir}/{step}/gt.png`` and ``{out_dir}/{step}/{i}.png`` per
        view — the reference's output layout (``sampling.py:179-182``).
        """
        imgs, R, T, K = (views["imgs"], views["R"], views["T"],
                         jnp.asarray(views["K"]))
        n_views = imgs.shape[0] if max_views is None else min(
            imgs.shape[0], max_views)
        B = self.w.shape[0]
        H, W = imgs.shape[1:3]

        # Fixed-size record buffer; entry 0 is the GT first view repeated
        # across the guidance batch (reference sampling.py:160-162).
        capacity = record_capacity(n_views) if n_views > 1 else 1
        record_imgs = np.zeros((capacity, B, H, W, 3), np.float32)
        record_R = np.zeros((capacity, 3, 3), np.float32)
        record_T = np.zeros((capacity, 3), np.float32)
        record_imgs[0] = imgs[0][None]
        record_R[0], record_T[0] = R[0], T[0]

        outs = []
        for step in range(1, n_views):
            rng, k = jax.random.split(rng)
            out = self.step(record_imgs, record_R, record_T, step,
                            R[step], T[step], K, k)
            out = np.asarray(jax.block_until_ready(out))
            record_imgs[step] = out
            record_R[step], record_T[step] = R[step], T[step]
            outs.append(out)

            if out_dir is not None:
                save_image(os.path.join(out_dir, str(step), "gt.png"),
                           imgs[step])
                for i in range(B):
                    save_image(
                        os.path.join(out_dir, str(step), f"{i}.png"), out[i])
        return np.stack(outs) if outs else np.zeros((0, B, H, W, 3))

    def synthesize_many(self, views_list: Sequence[Dict[str, np.ndarray]],
                        rngs: Sequence[jax.Array],
                        max_views: Optional[int] = None) -> np.ndarray:
        """Autoregressively synthesise N objects' views in ONE batched
        program (objects are independent — the reference scores them
        strictly sequentially, ``sampling.py:169-184``; here the object
        axis becomes an extra batch dim on every model call).

        ``rngs`` holds one key per object.  Given the same per-object key,
        the per-object rng stream is identical to a sequential
        ``synthesize(views, key)`` call, so results match the sequential
        path to float tolerance (XLA may tile the larger batch
        differently, so bitwise equality is not guaranteed).

        Every object contributes ``n_views = min(min_i views_i,
        max_views)`` views — batch objects with equal view counts to avoid
        truncation.  Returns ``[N, n_views-1, B, H, W, 3]``.
        """
        N = len(views_list)
        assert N == len(rngs)
        n_views = min(v["imgs"].shape[0] for v in views_list)
        if max_views is not None:
            n_views = min(n_views, max_views)
        B = self.w.shape[0]
        H, W = views_list[0]["imgs"].shape[1:3]

        capacity = record_capacity(n_views) if n_views > 1 else 1
        record_imgs = np.zeros((N, capacity, B, H, W, 3), np.float32)
        record_R = np.zeros((N, capacity, 3, 3), np.float32)
        record_T = np.zeros((N, capacity, 3), np.float32)
        Rs = np.stack([np.asarray(v["R"][:n_views], np.float32)
                       for v in views_list])
        Ts = np.stack([np.asarray(v["T"][:n_views], np.float32)
                       for v in views_list])
        Ks = np.stack([np.asarray(v["K"], np.float32) for v in views_list])
        for i, v in enumerate(views_list):
            record_imgs[i, 0] = v["imgs"][0][None]
        record_R[:, 0], record_T[:, 0] = Rs[:, 0], Ts[:, 0]

        keys = jnp.stack([jnp.asarray(k) for k in rngs])
        outs = []
        for step in range(1, n_views):
            split = jax.vmap(jax.random.split)(keys)     # [N, 2, key]
            keys, step_keys = split[:, 0], split[:, 1]
            out = self.step_many(
                record_imgs, record_R, record_T,
                np.full((N,), step, np.int32),
                Rs[:, step], Ts[:, step], Ks, step_keys)
            out = np.asarray(jax.block_until_ready(out))  # [N, B, H, W, 3]
            record_imgs[:, step] = out
            record_R[:, step], record_T[:, step] = Rs[:, step], Ts[:, step]
            outs.append(out)
        return (np.stack(outs, axis=1) if outs
                else np.zeros((N, 0, B, H, W, 3)))
