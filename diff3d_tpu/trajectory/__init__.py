"""Camera trajectories: path generators for orbit-video serving.

The serving side (``TrajectoryRequest`` in ``serving/scheduler.py``,
``POST /trajectory`` in ``serving/server.py``) consumes these; the
evaluation side scores the resulting frame sequences with
``evaluation/consistency.py``.
"""

from diff3d_tpu.trajectory.paths import (PATH_KINDS, keyframe_path,
                                         look_at, orbit_path,
                                         path_from_spec, spiral_path,
                                         trajectory_views)

__all__ = ["PATH_KINDS", "look_at", "orbit_path", "spiral_path",
           "keyframe_path", "path_from_spec", "trajectory_views"]
