"""Camera-path generators for trajectory (orbit-video) requests.

Every generator returns ``(R, T)`` with ``R [n, 3, 3]`` world-from-camera
rotations and ``T [n, 3]`` camera positions — the exact convention of
``geometry/rays.py::pinhole_rays`` (OpenCV axes: +z forward, +y down;
ray origin = ``T``, ray direction = ``R @ K^-1 [u, v, 1]``) and of
``data/synthetic.py::_look_at``, so a generated path slots straight into
an ``all_views``-style dict next to any SRN-like intrinsics ``K``.

Everything here is host-side float32 numpy: paths are a few hundred
3x3 matrices at most, computed once per request — they never enter a
traced context, so there is nothing for the compiler (or graftlint's
transfer rules) to see.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

__all__ = ["look_at", "orbit_path", "spiral_path", "keyframe_path",
           "path_from_spec", "trajectory_views", "PATH_KINDS"]

#: Path kinds the JSON spec grammar accepts (serving POST /trajectory).
PATH_KINDS = ("orbit", "spiral", "keyframes")


def look_at(eye, target=(0.0, 0.0, 0.0), up=(0.0, 0.0, 1.0)) -> np.ndarray:
    """World-from-camera rotation for a camera at ``eye`` looking at
    ``target`` (OpenCV convention: +z forward, +y down).

    Columns are ``[right, down, forward]``: ``forward`` points at the
    target, ``right = forward x up`` (so "up" in the image is world
    ``up``), ``down`` completes the right-handed frame — det is +1 by
    construction.  When the view direction is within ~8 degrees of
    ``up`` the fallback up-vector (0, 1, 0) keeps the cross products
    non-degenerate (same fallback as ``data/synthetic.py::_look_at``).
    """
    eye = np.asarray(eye, np.float64)
    target = np.asarray(target, np.float64)
    fwd = target - eye
    norm = np.linalg.norm(fwd)
    if norm < 1e-9:
        raise ValueError(f"look_at: eye {eye} coincides with target")
    fwd = fwd / norm
    up = np.asarray(up, np.float64)
    up = up / np.linalg.norm(up)
    if abs(fwd @ up) > 0.99:
        up = np.array([0.0, 1.0, 0.0])
    right = np.cross(fwd, up)
    right /= np.linalg.norm(right)
    down = np.cross(fwd, right)
    return np.stack([right, down, fwd], axis=1).astype(np.float32)


def _poses_from_eyes(eyes: np.ndarray,
                     targets: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    R = np.stack([look_at(e, t) for e, t in zip(eyes, targets)])
    return R.astype(np.float32), eyes.astype(np.float32)


def orbit_path(n_frames: int, radius: float = 2.0,
               elevation_deg: float = 20.0,
               target=(0.0, 0.0, 0.0),
               azimuth0_deg: float = 0.0,
               full_turns: float = 1.0) -> Tuple[np.ndarray, np.ndarray]:
    """Circular orbit around ``target`` at constant radius/elevation.

    ``n_frames`` azimuths are spaced evenly over ``full_turns`` turns
    WITHOUT the duplicated endpoint, so a one-turn orbit is seamless as
    a looping video: the (virtual) frame ``n_frames`` coincides with
    frame 0 — the closure property the pose-math tests pin.
    """
    if n_frames < 1:
        raise ValueError(f"n_frames={n_frames} must be >= 1")
    if radius <= 0:
        raise ValueError(f"radius={radius} must be > 0")
    target = np.asarray(target, np.float64)
    az = (np.deg2rad(azimuth0_deg)
          + 2.0 * np.pi * full_turns * np.arange(n_frames) / n_frames)
    el = np.deg2rad(elevation_deg) * np.ones(n_frames)
    eyes = target + radius * np.stack(
        [np.cos(az) * np.cos(el), np.sin(az) * np.cos(el), np.sin(el)],
        axis=-1)
    return _poses_from_eyes(eyes, np.broadcast_to(target, eyes.shape))


def spiral_path(n_frames: int, radius: float = 2.0,
                elevation_start_deg: float = -10.0,
                elevation_end_deg: float = 45.0,
                target=(0.0, 0.0, 0.0),
                azimuth0_deg: float = 0.0,
                full_turns: float = 1.0) -> Tuple[np.ndarray, np.ndarray]:
    """Orbit whose elevation sweeps linearly start -> end across the
    path — the classic turntable-with-rise qualitative shot."""
    if n_frames < 1:
        raise ValueError(f"n_frames={n_frames} must be >= 1")
    if radius <= 0:
        raise ValueError(f"radius={radius} must be > 0")
    target = np.asarray(target, np.float64)
    az = (np.deg2rad(azimuth0_deg)
          + 2.0 * np.pi * full_turns * np.arange(n_frames) / n_frames)
    frac = (np.arange(n_frames) / max(1, n_frames - 1)
            if n_frames > 1 else np.zeros(1))
    el = np.deg2rad(elevation_start_deg
                    + (elevation_end_deg - elevation_start_deg) * frac)
    # Clamp away from the poles so look_at never degenerates.
    el = np.clip(el, np.deg2rad(-80.0), np.deg2rad(80.0))
    eyes = target + radius * np.stack(
        [np.cos(az) * np.cos(el), np.sin(az) * np.cos(el), np.sin(el)],
        axis=-1)
    return _poses_from_eyes(eyes, np.broadcast_to(target, eyes.shape))


def keyframe_path(keyframes: Sequence, n_frames: int,
                  targets: Optional[Sequence] = None
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Piecewise-linear look-at path through camera-position keyframes.

    ``keyframes`` is ``[k, 3]`` camera positions (k >= 2); ``targets``
    is ``[k, 3]`` per-keyframe look-at targets (default: origin for
    all).  Positions and targets interpolate linearly on a uniform
    parameter; each interpolated pose is re-orthonormalised through
    :func:`look_at`, so the output is exactly SO(3) even though the
    interpolation itself is Euclidean.
    """
    eyes_k = np.asarray(keyframes, np.float64)
    if eyes_k.ndim != 2 or eyes_k.shape[-1] != 3 or eyes_k.shape[0] < 2:
        raise ValueError(
            f"keyframes must be [k>=2, 3], got {eyes_k.shape}")
    if targets is None:
        tgts_k = np.zeros_like(eyes_k)
    else:
        tgts_k = np.asarray(targets, np.float64)
        if tgts_k.shape != eyes_k.shape:
            raise ValueError(
                f"targets shape {tgts_k.shape} != keyframes "
                f"{eyes_k.shape}")
    if n_frames < 1:
        raise ValueError(f"n_frames={n_frames} must be >= 1")
    if np.any(np.linalg.norm(eyes_k - tgts_k, axis=-1) < 1e-6):
        raise ValueError("a keyframe eye coincides with its target")
    u = (np.arange(n_frames) / max(1, n_frames - 1)
         if n_frames > 1 else np.zeros(1)) * (eyes_k.shape[0] - 1)
    i0 = np.minimum(u.astype(np.int64), eyes_k.shape[0] - 2)
    w = (u - i0)[:, None]
    eyes = (1.0 - w) * eyes_k[i0] + w * eyes_k[i0 + 1]
    tgts = (1.0 - w) * tgts_k[i0] + w * tgts_k[i0 + 1]
    return _poses_from_eyes(eyes, tgts)


def path_from_spec(spec: dict) -> Tuple[np.ndarray, np.ndarray]:
    """Build a path from a JSON-shaped spec (the serving grammar).

    ``{"kind": "orbit"|"spiral"|"keyframes", "frames": N, ...}`` — the
    remaining keys are the keyword arguments of the matching generator
    (``radius``, ``elevation_deg``, ``target``, ``azimuth0_deg``,
    ``full_turns``, ``elevation_start_deg``/``elevation_end_deg``,
    ``keyframes``/``targets``).  Unknown kinds and unknown keys raise
    ``ValueError`` so a typo'd request is a 400, not a silent default.
    """
    if not isinstance(spec, dict):
        raise ValueError(f"path spec must be an object, got {type(spec)}")
    spec = dict(spec)
    kind = spec.pop("kind", None)
    if kind not in PATH_KINDS:
        raise ValueError(
            f"path kind {kind!r} not in {PATH_KINDS}")
    frames = spec.pop("frames", None)
    if frames is None:
        raise ValueError("path spec must carry 'frames'")
    frames = int(frames)
    fns = {"orbit": orbit_path, "spiral": spiral_path,
           "keyframes": keyframe_path}
    fn = fns[kind]
    if kind == "keyframes":
        keyframes = spec.pop("keyframes", None)
        if keyframes is None:
            raise ValueError("keyframes path spec must carry 'keyframes'")
        kwargs = {"targets": spec.pop("targets", None)}
        args = (keyframes, frames)
    else:
        kwargs, args = {}, (frames,)
    allowed = {"orbit": {"radius", "elevation_deg", "target",
                         "azimuth0_deg", "full_turns"},
               "spiral": {"radius", "elevation_start_deg",
                          "elevation_end_deg", "target", "azimuth0_deg",
                          "full_turns"},
               "keyframes": set()}[kind]
    unknown = set(spec) - allowed
    if unknown:
        raise ValueError(
            f"unknown {kind} path keys {sorted(unknown)} "
            f"(allowed: {sorted(allowed)})")
    kwargs.update(spec)
    return fn(*args, **kwargs)


def trajectory_views(cond_img: np.ndarray, cond_R: np.ndarray,
                     cond_T: np.ndarray, K: np.ndarray,
                     path_R: np.ndarray, path_T: np.ndarray) -> dict:
    """Assemble the ``all_views``-style dict for a trajectory request:
    view 0 is the conditioning view (its image is the only one
    consumed), views 1.. are the path poses to synthesise.  The
    returned dict plugs straight into
    :class:`~diff3d_tpu.serving.scheduler.TrajectoryRequest` or
    ``Sampler.synthesize``."""
    cond_img = np.asarray(cond_img, np.float32)
    if cond_img.ndim == 3:
        cond_img = cond_img[None]
    if cond_img.ndim != 4 or cond_img.shape[-1] != 3:
        raise ValueError(
            f"cond_img must be [H, W, 3] or [1, H, W, 3], got "
            f"{cond_img.shape}")
    R = np.concatenate([np.asarray(cond_R, np.float32)[None],
                        np.asarray(path_R, np.float32)], axis=0)
    T = np.concatenate([np.asarray(cond_T, np.float32)[None],
                        np.asarray(path_T, np.float32)], axis=0)
    return {"imgs": cond_img[:1], "R": R, "T": T,
            "K": np.asarray(K, np.float32)}
