"""diff3d_tpu — TPU-native (JAX/XLA/Flax/Pallas) framework with the
capabilities of ``halixness/distributed-3d-diffusion-pytorch``: 3DiM-style
pose-conditional X-UNet diffusion for novel view synthesis on SRN
Cars/Chairs, with mesh-parallel training and stochastic-conditioning
autoregressive sampling."""

__version__ = "0.1.0"

from diff3d_tpu.config import (Config, DataConfig, DiffusionConfig,
                               MeshConfig, ModelConfig, TrainConfig,
                               srn64_config, srn128_config, test_config)

__all__ = [
    "Config", "DataConfig", "DiffusionConfig", "MeshConfig", "ModelConfig",
    "TrainConfig", "srn64_config", "srn128_config", "test_config",
]
