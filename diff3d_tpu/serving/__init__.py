"""Batched novel-view inference service.

Turns the offline :class:`diff3d_tpu.sampling.Sampler` into a long-running
service: a bounded scheduler microbatches concurrent requests into
fixed-shape device batches (bucketed by image size and record capacity), a
device-executor engine drives the object-batched per-view scan and admits
new requests *between* views (continuous batching at view granularity —
3DiM's 256-step-per-view sampler makes per-request latency batch-bound,
not step-bound), and a stdlib HTTP frontend exposes submit/poll, health
and metrics endpoints.  Above the single engine, the fleet router
(``serving/router.py`` + ``serving/fleet.py``) runs N replicas behind one
front door with session affinity (device-resident records never migrate),
typed fleet backpressure, blue/green params rollout and schedule-aware
placement.  The cross-process fleet (``serving/transport.py`` +
``serving/worker.py``) puts the same replica surface behind a socket:
workers pin replicas to disjoint device slices, the router fronts them
through :class:`RemoteReplica` with zero placement changes, and
HBM-budgeted admission rejects at the door with a typed
:class:`ReplicaOverBudget`.
"""

from diff3d_tpu.serving.cache import (ParamsRegistry, ProgramCache,
                                      ResultCache)
from diff3d_tpu.serving.engine import (Engine, EngineStopTimeout,
                                       HEALTH_DEGRADED, HEALTH_DRAINING,
                                       HEALTH_OK)
from diff3d_tpu.serving.fleet import HEALTH_DEAD, Replica, build_fleet
from diff3d_tpu.serving.metrics import MetricsRegistry
from diff3d_tpu.serving.router import FleetService, Router
from diff3d_tpu.serving.scheduler import (Bucket, EngineDraining,
                                          EngineOverloaded, EngineStepError,
                                          EngineStopped, FleetOverloaded,
                                          QueueFullError, ReplicaDraining,
                                          ReplicaOverBudget,
                                          RequestCancelled, RequestTimeout,
                                          Scheduler, SessionLost,
                                          TrajectoryRequest,
                                          UnsupportedSchedule, ViewRequest)
from diff3d_tpu.serving.server import (ServingService, build_request,
                                       build_trajectory_request,
                                       make_http_server)
from diff3d_tpu.serving.transport import (FrameGarbage, FrameTooLarge,
                                          FrameTruncated, RemoteReplica,
                                          TransportError)
from diff3d_tpu.serving.worker import (HbmAdmission, Worker, boot_worker,
                                       configure_compile_cache)

__all__ = [
    "Bucket", "Engine", "EngineDraining", "EngineOverloaded",
    "EngineStepError", "EngineStopTimeout", "EngineStopped",
    "FleetOverloaded", "FleetService", "FrameGarbage", "FrameTooLarge",
    "FrameTruncated", "HEALTH_DEAD", "HEALTH_DEGRADED",
    "HEALTH_DRAINING", "HEALTH_OK", "HbmAdmission", "MetricsRegistry",
    "ParamsRegistry", "ProgramCache", "QueueFullError", "RemoteReplica",
    "Replica", "ReplicaDraining", "ReplicaOverBudget", "RequestCancelled",
    "RequestTimeout", "ResultCache", "Router", "Scheduler",
    "ServingService", "SessionLost", "TransportError",
    "TrajectoryRequest", "UnsupportedSchedule", "ViewRequest",
    "Worker", "boot_worker", "build_fleet", "build_request",
    "build_trajectory_request", "configure_compile_cache",
    "make_http_server",
]
