"""Device-executor engine: continuous batching at view granularity.

One thread owns the chip.  Its loop is:

    admit pending requests (same bucket) into free lanes
      -> run ONE view's reverse diffusion for every active request
         (one ``Sampler.step_many`` launch; 256 fused steps inside)
      -> write each lane's view back into its request's record buffer,
         resolve finished requests, free their lanes
      -> repeat

Because admission happens *between* view steps, a freshly submitted
1-view request rides along with an in-flight 20-view job at the very next
view boundary instead of waiting behind it — iteration-level (Orca-style)
scheduling where the iteration is a whole fixed-length diffusion scan, the
natural preemption point of 3DiM's sampler (a scan cannot be split without
changing the compiled program).

Each request keeps the exact RNG stream of the offline path: a per-request
``PRNGKey(seed)`` split once per view (``sampling/runtime.py
synthesize``), so a served result is bit-identical to
``Sampler.synthesize`` with the same seed on the same backend.

Batch shapes are quantised: the active set is padded to the next power of
two lanes (<= ``ServingConfig.max_batch``) by repeating a live lane, so
each bucket owns a logarithmic number of compiled programs.  When the
sampler rides a mesh, lane counts are additionally rounded up to a
multiple of its ``lane_multiple`` (the mesh's data-axis size) — a sharded
program cannot split a non-divisible object axis, so without the rounding
an odd admission count would recompile (or crash) instead of padding.
Padding lanes burn real FLOPs — the occupancy/padding histograms exist
precisely to make that waste visible.

The engine keeps each request's record buffer on the HOST and re-stages
the active set every view step (unlike the offline ``synthesize`` loops,
which thread a device-resident donated carry): continuous batching
re-forms the lane set at every view boundary, so per-slot host buffers are
what let a fresh request join mid-flight without reshuffling device
memory.  The cost of that choice is measured, not hidden — the
``serving_host_{upload,fetch}_bytes_total`` counters track exactly how
many bytes cross the host boundary per step.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

import jax
import numpy as np

from diff3d_tpu.config import ServingConfig
from diff3d_tpu.serving.cache import (ParamsRegistry, ProgramCache,
                                      ResultCache)
from diff3d_tpu.serving.metrics import MetricsRegistry
from diff3d_tpu.serving.scheduler import (RequestCancelled, RequestTimeout,
                                          Scheduler, ViewRequest)
from diff3d_tpu.utils.profiling import StepTimer

log = logging.getLogger(__name__)


def lane_count(n: int, max_batch: int, multiple: int = 1) -> int:
    """Launch lanes for ``n`` live requests: smallest power of two >= n,
    rounded up to ``multiple`` (the sampler's mesh quantum — a sharded
    object axis must divide by the data-axis size), clamped to
    ``max_batch`` (itself pre-rounded by the engine when ``multiple`` >
    1)."""
    if not n:
        return 0
    lanes = 1 << (n - 1).bit_length()
    lanes = -(-lanes // multiple) * multiple
    return min(lanes, max_batch)


class _Slot:
    """Engine-side state of one admitted request."""

    def __init__(self, req: ViewRequest, guidance_B: int):
        self.req = req
        cap = req.bucket.capacity
        H, W = req.bucket.H, req.bucket.W
        self.record_imgs = np.zeros((cap, guidance_B, H, W, 3), np.float32)
        self.record_R = np.zeros((cap, 3, 3), np.float32)
        self.record_T = np.zeros((cap, 3), np.float32)
        self.record_imgs[0] = req.imgs0[None]
        # Device-resident record contract: ALL poses pre-filled — entry
        # ``step`` doubles as the target pose of the view being
        # synthesised (the stochastic-conditioning draw only reads
        # entries < step, so future poses never leak into sampling).
        self.record_R[:req.n_views] = req.R[:req.n_views]
        self.record_T[:req.n_views] = req.T[:req.n_views]
        self.step = 1                       # next view index to synthesise
        # Per-request PRNG carry; the per-view key split happens INSIDE
        # the compiled step (sample_view), preserving the offline loop's
        # exact stream.
        self.rng = np.asarray(jax.random.PRNGKey(req.seed))
        self.outs: List[np.ndarray] = []


class Engine:
    """Single consumer of the :class:`Scheduler`; owner of device work."""

    def __init__(self, sampler, scheduler: Scheduler,
                 metrics: MetricsRegistry, cfg: ServingConfig,
                 params_registry: Optional[ParamsRegistry] = None,
                 result_cache: Optional[ResultCache] = None,
                 program_cache: Optional[ProgramCache] = None):
        self.sampler = sampler
        self.scheduler = scheduler
        self.metrics = metrics
        self.cfg = cfg
        self.registry = params_registry or ParamsRegistry(sampler.params)
        self.result_cache = result_cache or ResultCache(
            cfg.result_cache_entries, metrics)
        self.programs = program_cache or ProgramCache(sampler, metrics)
        self.guidance_B = int(sampler.w.shape[0])
        # Mesh quantum: every launched lane count must divide by the
        # sampler's data-axis size, including the admission ceiling.
        self.lane_multiple = int(getattr(sampler, "lane_multiple", 1) or 1)
        self.max_batch = (-(-cfg.max_batch // self.lane_multiple)
                          * self.lane_multiple)
        if self.max_batch != cfg.max_batch:
            log.warning(
                "serving max_batch rounded %d -> %d (mesh data-axis "
                "size %d)", cfg.max_batch, self.max_batch,
                self.lane_multiple)
        self.step_timer = StepTimer(window=512)

        m = metrics
        self._submitted = m.counter("serving_requests_total",
                                    "requests accepted for scheduling")
        self._completed = m.counter("serving_requests_completed_total",
                                    "requests finished successfully")
        self._failed = m.counter("serving_requests_failed_total",
                                 "requests resolved with an error")
        self._views_done = m.counter("serving_views_completed_total",
                                     "novel views synthesised")
        self._active_g = m.gauge("serving_active_requests",
                                 "requests currently holding a lane")
        self._occupancy = m.histogram(
            "serving_batch_occupancy",
            "live requests per launched view-step batch")
        self._padding = m.histogram(
            "serving_batch_padding_fraction",
            "fraction of launched lanes that were padding")
        self._ttfv = m.histogram(
            "serving_time_to_first_view_seconds",
            "submit -> first synthesised view")
        self._view_lat = m.histogram("serving_view_step_seconds",
                                     "wall time of one view-step batch")
        self._e2e = m.histogram("serving_e2e_latency_seconds",
                                "submit -> full result")
        self._queue_wait = m.histogram("serving_queue_wait_seconds",
                                       "submit -> admission to a lane")
        self._upload_bytes = m.counter(
            "serving_host_upload_bytes_total",
            "host->device bytes staged for view-step batches")
        self._fetch_bytes = m.counter(
            "serving_host_fetch_bytes_total",
            "device->host bytes fetched from view-step batches")

        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- client surface --------------------------------------------------

    def submit(self, req: ViewRequest) -> ViewRequest:
        """Schedule a request (or answer it from the result cache)."""
        version, _ = self.registry.current()
        key = req.content_key(version)
        hit = self.result_cache.get(key)
        if hit is not None:
            req.cached = True
            req.submit_time = req.done_time = time.monotonic()
            req._resolve(hit)
            return req
        self._submitted.inc()
        return self.scheduler.submit(req)

    def start(self) -> "Engine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="diff3d-serving-engine",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self.scheduler.close(reject_pending=True)
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def snapshot_extra(self) -> dict:
        """Engine-level details merged into the metrics snapshot."""
        return {
            "engine": {
                "alive": self.alive,
                "params_version": self.registry.version,
                "lane_multiple": self.lane_multiple,
                "max_batch": self.max_batch,
                "num_devices": jax.device_count(),
                "step_timer": self.step_timer.summary(),
                "program_cache": self.programs.stats(),
                "result_cache_entries": len(self.result_cache),
            }
        }

    # -- executor loop ---------------------------------------------------

    def _loop(self) -> None:
        active: List[_Slot] = []
        try:
            while not self._stop.is_set():
                active = self._admit(active)
                if not active:
                    continue
                try:
                    self._run_view_step(active)
                except Exception as e:   # resolve, don't kill the server
                    log.exception("view step failed")
                    for slot in active:
                        self._failed.inc()
                        slot.req._reject(e)
                    active = []
                    continue
                active = self._retire(active)
        finally:
            for slot in active:
                slot.req._reject(RuntimeError("engine stopped"))
            self._active_g.set(0)

    def _admit(self, active: List[_Slot]) -> List[_Slot]:
        free = self.max_batch - len(active)
        if active:
            got = self.scheduler.acquire(active[0].req.bucket, free,
                                         block=False) if free > 0 else []
        else:
            got = self.scheduler.acquire(None, self.max_batch,
                                         block=True, poll_s=0.2)
        now = time.monotonic()
        for req in got:
            self._queue_wait.observe(now - req.submit_time)
            active.append(_Slot(req, self.guidance_B))
        if got or not active:
            self._active_g.set(len(active))
        return active

    def _run_view_step(self, active: List[_Slot]) -> None:
        n = len(active)
        lanes = lane_count(n, self.max_batch, self.lane_multiple)
        pad = lanes - n
        # Pad by repeating lane 0 (live data: zero-filled lanes would
        # still run the full scan, and denormals/NaN paths can be slower
        # than real numbers).  Padded outputs are discarded.
        idx = list(range(n)) + [0] * pad
        record_imgs = np.stack([active[i].record_imgs for i in idx])
        record_R = np.stack([active[i].record_R for i in idx])
        record_T = np.stack([active[i].record_T for i in idx])
        steps = np.asarray([active[i].step for i in idx], np.int32)
        Ks = np.stack([active[i].req.K for i in idx])
        # Per-lane PRNG carries — the per-view split happens inside the
        # compiled step, so the stream is identical to the offline
        # synthesize loop's.
        rngs = np.stack([active[i].rng for i in idx])
        self._upload_bytes.inc(record_imgs.nbytes + record_R.nbytes
                               + record_T.nbytes + steps.nbytes
                               + Ks.nbytes + rngs.nbytes)

        version, params = self.registry.current()
        bucket = active[0].req.bucket
        t0 = time.monotonic()
        out, _, _, new_rngs = self.programs.step_many(
            bucket, lanes, record_imgs, record_R, record_T, steps, Ks,
            rngs, params=params)
        out = np.asarray(jax.block_until_ready(out))
        new_rngs = np.asarray(new_rngs)
        dt = time.monotonic() - t0
        self._fetch_bytes.inc(out.nbytes + new_rngs.nbytes)
        self.step_timer.tick()
        self._view_lat.observe(dt)
        self._occupancy.observe(n)
        self._padding.observe(pad / lanes if lanes else 0.0)
        self._views_done.inc(n)

        now = time.monotonic()
        for i, slot in enumerate(active):
            view = out[i]
            slot.record_imgs[slot.step] = view
            slot.rng = new_rngs[i]
            slot.outs.append(view)
            if slot.req.first_view_time is None:
                slot.req.first_view_time = now
                self._ttfv.observe(now - slot.req.submit_time)
            slot.step += 1
        # One params version per launched batch; remember it for the
        # result-cache key of requests that finish this step.
        self._last_version = version

    def _retire(self, active: List[_Slot]) -> List[_Slot]:
        still: List[_Slot] = []
        now = time.monotonic()
        for slot in active:
            req = slot.req
            if req.cancelled:
                self._failed.inc()
                req._reject(RequestCancelled(f"{req.id}: cancelled"))
            elif req.expired(now):
                self._failed.inc()
                req._reject(RequestTimeout(
                    f"{req.id}: deadline exceeded mid-run at view "
                    f"{slot.step - 1}/{req.n_views - 1}"))
            elif slot.step >= req.n_views:
                result = np.stack(slot.outs)
                version = getattr(self, "_last_version",
                                  self.registry.version)
                self.result_cache.put(req.content_key(version), result)
                self._completed.inc()
                self._e2e.observe(now - req.submit_time)
                req._resolve(result)
            else:
                still.append(slot)
        if len(still) != len(active):
            self._active_g.set(len(still))
        return still
