"""Device-executor engine: continuous batching at view granularity.

One thread owns the chip.  Its loop is:

    admit pending requests (same bucket) into free lanes
      -> run ONE view's reverse diffusion for every active request
         (one ``Sampler.step_many`` launch; 256 fused steps inside)
      -> write each lane's view back into its request's record buffer,
         resolve finished requests, free their lanes
      -> repeat

Because admission happens *between* view steps, a freshly submitted
1-view request rides along with an in-flight 20-view job at the very next
view boundary instead of waiting behind it — iteration-level (Orca-style)
scheduling where the iteration is a whole fixed-length diffusion scan, the
natural preemption point of 3DiM's sampler (a scan cannot be split without
changing the compiled program).

Each request keeps the exact RNG stream of the offline path: a per-request
``PRNGKey(seed)`` split once per view (``sampling/runtime.py
synthesize``), so a served result is bit-identical to
``Sampler.synthesize`` with the same seed on the same backend.

Batch shapes are quantised: the active set is padded to the next power of
two lanes (<= ``ServingConfig.max_batch``) by repeating a live lane, so
each bucket owns a logarithmic number of compiled programs.  When the
sampler rides a mesh, lane counts are additionally rounded up to a
multiple of its ``lane_multiple`` (the mesh's data-axis size) — a sharded
program cannot split a non-divisible object axis, so without the rounding
an odd admission count would recompile (or crash) instead of padding.
Padding lanes burn real FLOPs — the occupancy/padding histograms exist
precisely to make that waste visible.

The engine keeps each request's record buffer on the HOST and re-stages
the active set every view step (unlike the offline ``synthesize`` loops,
which thread a device-resident donated carry): continuous batching
re-forms the lane set at every view boundary, so per-slot host buffers are
what let a fresh request join mid-flight without reshuffling device
memory.  The cost of that choice is measured, not hidden — the
``serving_host_{upload,fetch}_bytes_total`` counters track exactly how
many bytes cross the host boundary per step.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

import jax
import numpy as np

from diff3d_tpu.config import ServingConfig
from diff3d_tpu.runtime.retry import (RetryPolicy,
                                      is_transient_backend_error)
from diff3d_tpu.serving.cache import (ParamsRegistry, ProgramCache,
                                      ResultCache)
from diff3d_tpu.serving.metrics import MetricsRegistry
from diff3d_tpu.serving.scheduler import (EngineDraining, EngineOverloaded,
                                          EngineStepError, EngineStopped,
                                          RequestCancelled, RequestTimeout,
                                          Scheduler, UnsupportedSchedule,
                                          ViewRequest)
from diff3d_tpu.utils.profiling import StepTimer

log = logging.getLogger(__name__)

#: Engine health states (DESIGN.md §7).  ``ok`` -> full capacity;
#: ``degraded`` -> halved batch ceiling, queue soft limit, shed
#: lower-priority buckets, Retry-After on rejected admissions; returns
#: to ``ok`` after ``degraded_recovery_steps`` consecutive clean steps.
#: ``draining`` -> no new admissions, existing work runs to completion.
HEALTH_OK = "ok"
HEALTH_DEGRADED = "degraded"
HEALTH_DRAINING = "draining"
_HEALTH_GAUGE = {HEALTH_OK: 0, HEALTH_DEGRADED: 1, HEALTH_DRAINING: 2}


class EngineStopTimeout(RuntimeError):
    """``Engine.stop(timeout)`` could not join the worker thread — it is
    leaked (most likely wedged in a device call).  Operator-facing and
    NOT retryable: the process needs external attention."""


def lane_count(n: int, max_batch: int, multiple: int = 1) -> int:
    """Launch lanes for ``n`` live requests: smallest power of two >= n,
    rounded up to ``multiple`` (the sampler's mesh quantum — a sharded
    object axis must divide by the data-axis size), clamped to
    ``max_batch`` (itself pre-rounded by the engine when ``multiple`` >
    1)."""
    if not n:
        return 0
    lanes = 1 << (n - 1).bit_length()
    lanes = -(-lanes // multiple) * multiple
    return min(lanes, max_batch)


class _Slot:
    """Engine-side state of one admitted request."""

    def __init__(self, req: ViewRequest, guidance_B: int):
        self.req = req
        cap = req.bucket.capacity
        H, W = req.bucket.H, req.bucket.W
        self.record_imgs = np.zeros((cap, guidance_B, H, W, 3), np.float32)
        self.record_R = np.zeros((cap, 3, 3), np.float32)
        self.record_T = np.zeros((cap, 3), np.float32)
        self.record_imgs[0] = req.imgs0[None]
        # Device-resident record contract: ALL poses pre-filled — entry
        # ``step`` doubles as the target pose of the view being
        # synthesised (the stochastic-conditioning draw only reads
        # entries < step, so future poses never leak into sampling).
        self.record_R[:req.n_views] = req.R[:req.n_views]
        self.record_T[:req.n_views] = req.T[:req.n_views]
        self.step = 1                       # next view index to synthesise
        # Per-request PRNG carry; the per-view key split happens INSIDE
        # the compiled step (sample_view), preserving the offline loop's
        # exact stream.  A cascade phase child carries an explicit key
        # (its split of the parent stream) instead of PRNGKey(seed).
        key = getattr(req, "rng_key", None)
        self.rng = np.asarray(jax.random.PRNGKey(req.seed)
                              if key is None else key)
        # Refine-phase children carry the [n_views-1, B, H, W, 3]
        # upsampled drafts their truncated scans renoise from.
        self.drafts = getattr(req, "drafts", None)
        self.outs: List[np.ndarray] = []


class Engine:
    """Single consumer of the :class:`Scheduler`; owner of device work."""

    def __init__(self, sampler, scheduler: Scheduler,
                 metrics: MetricsRegistry, cfg: ServingConfig,
                 params_registry: Optional[ParamsRegistry] = None,
                 result_cache: Optional[ResultCache] = None,
                 program_cache: Optional[ProgramCache] = None,
                 extra_samplers: Optional[dict] = None,
                 cascade=None):
        self.sampler = sampler
        self.scheduler = scheduler
        self.metrics = metrics
        self.cfg = cfg
        # Schedule registry: the replica serves exactly these
        # (sampler_kind, steps) pairs — one Sampler each, all sharing the
        # default sampler's params.  Requests naming any other schedule
        # are rejected at submit with UnsupportedSchedule; programs are
        # never compiled on client demand.
        self.default_schedule = (getattr(sampler, "sampler_kind", None),
                                 getattr(sampler, "steps", None))
        self.samplers = {self.default_schedule: sampler}
        for key, extra in (extra_samplers or {}).items():
            kind, steps = key
            self.samplers[(kind, None if steps is None
                           else int(steps))] = extra
            if (getattr(extra, "lane_multiple", 1)
                    != getattr(sampler, "lane_multiple", 1)):
                raise ValueError(
                    f"extra sampler {key}: lane_multiple differs from the "
                    "default sampler's — all schedules must share a mesh")
        self.registry = params_registry or ParamsRegistry(sampler.params)
        self.result_cache = result_cache or ResultCache(
            cfg.result_cache_entries, metrics)
        self.programs = program_cache or ProgramCache(
            self.samplers if len(self.samplers) > 1 else sampler, metrics)
        # Cascade serving (DESIGN.md §20): a CascadeSampler contributes
        # the two phase programs — requests reach them only through
        # phase-tagged buckets, never through the (kind, steps) schedule
        # registry, so plain clients cannot address them.
        self.cascade = cascade
        if cascade is not None:
            from diff3d_tpu.convert.progressive import (
                adapt_params_resolution)

            dr = cascade.plan.draft.resolution
            for phase, s, adapt in (
                    ("draft", cascade.draft,
                     lambda p, _dr=dr: adapt_params_resolution(
                         p, (_dr, _dr))),
                    ("refine", cascade.refine, None)):
                if (getattr(s, "lane_multiple", 1)
                        != getattr(sampler, "lane_multiple", 1)):
                    raise ValueError(
                        f"cascade {phase} sampler: lane_multiple differs "
                        "from the default sampler's — all programs must "
                        "share a mesh")
                self.programs.register_phase(phase, s, adapt=adapt)
        self.guidance_B = int(sampler.w.shape[0])
        # Mesh quantum: every launched lane count must divide by the
        # sampler's data-axis size, including the admission ceiling.
        self.lane_multiple = int(getattr(sampler, "lane_multiple", 1) or 1)
        self.max_batch = (-(-cfg.max_batch // self.lane_multiple)
                          * self.lane_multiple)
        if self.max_batch != cfg.max_batch:
            log.warning(
                "serving max_batch rounded %d -> %d (mesh data-axis "
                "size %d)", cfg.max_batch, self.max_batch,
                self.lane_multiple)
        self.step_timer = StepTimer(window=512)

        m = metrics
        self._submitted = m.counter("serving_requests_total",
                                    "requests accepted for scheduling")
        self._completed = m.counter("serving_requests_completed_total",
                                    "requests finished successfully")
        self._failed = m.counter("serving_requests_failed_total",
                                 "requests resolved with an error")
        self._views_done = m.counter("serving_views_completed_total",
                                     "novel views synthesised")
        self._active_g = m.gauge("serving_active_requests",
                                 "requests currently holding a lane")
        self._occupancy = m.histogram(
            "serving_batch_occupancy",
            "live requests per launched view-step batch")
        self._padding = m.histogram(
            "serving_batch_padding_fraction",
            "fraction of launched lanes that were padding")
        self._ttfv = m.histogram(
            "serving_time_to_first_view_seconds",
            "submit -> first synthesised view")
        self._view_lat = m.histogram("serving_view_step_seconds",
                                     "wall time of one view-step batch")
        self._e2e = m.histogram("serving_e2e_latency_seconds",
                                "submit -> full result")
        self._queue_wait = m.histogram("serving_queue_wait_seconds",
                                       "submit -> admission to a lane")
        self._upload_bytes = m.counter(
            "serving_host_upload_bytes_total",
            "host->device bytes staged for view-step batches")
        self._fetch_bytes = m.counter(
            "serving_host_fetch_bytes_total",
            "device->host bytes fetched from view-step batches")
        self._step_faults = m.counter(
            "serving_engine_step_faults_total",
            "view-step dispatches that failed after retries")
        self._watchdog_trips = m.counter(
            "serving_engine_watchdog_trips_total",
            "stuck view steps detected by the watchdog")
        self._restarts_ctr = m.counter(
            "serving_engine_restarts_total",
            "engine loop threads respawned after dying")
        self._stop_timeouts = m.counter(
            "serving_engine_stop_timeout_total",
            "stop() calls that leaked the worker thread")
        self._sched_rejects = m.counter(
            "serving_unsupported_schedule_total",
            "submissions naming a (sampler_kind, steps) with no "
            "compiled bucket")
        self._traj_requests = m.counter(
            "serving_trajectory_requests_total",
            "trajectory (camera-path) requests accepted for scheduling")
        self._traj_frames = m.counter(
            "serving_trajectory_frames_total",
            "trajectory frames committed to records")
        self._traj_active_g = m.gauge(
            "serving_active_trajectories",
            "trajectory requests admitted but not yet resolved")
        self._cascade_requests = m.counter(
            "serving_cascade_requests_total",
            "cascade (progressive-preview) requests accepted")
        self._cascade_frames = m.counter(
            "serving_cascade_frames_total",
            "cascade phase frames committed (draft + refine)")
        self._health_g = m.gauge(
            "serving_engine_health",
            "engine health (0=ok, 1=degraded, 2=draining)")

        self._thread: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        self._stop = threading.Event()

        # -- fault-tolerance state (DESIGN.md §7) ------------------------
        # Transient-fault retry around each view-step dispatch.  Inputs
        # are freshly stacked host buffers, so a re-dispatch is safe and
        # bit-exact; real compile/shape errors are classified
        # non-retryable and surface immediately.
        self.step_policy = RetryPolicy(
            max_attempts=max(1, cfg.step_retry_attempts),
            base_delay_s=cfg.step_retry_backoff_s,
            max_delay_s=max(cfg.step_retry_backoff_s * 8, 1e-9),
            classify=is_transient_backend_error)
        self._health = HEALTH_OK  # guarded-by: self._health_lock
        self._health_lock = threading.Lock()
        # Clean steps since the last fault.
        self._ok_streak = 0  # guarded-by: self._health_lock
        self._restarts = 0
        # Admitted-but-unresolved requests, so the watchdog thread can
        # fail them with typed retryable errors when the loop wedges.
        # ViewRequest._reject is idempotent under the request's own
        # lock, so watchdog and loop racing on the same request is safe.
        self._inflight: dict = {}  # guarded-by: self._inflight_lock
        self._inflight_lock = threading.Lock()
        # Monotonic deadline of the dispatch currently on device (None
        # when no dispatch is running); read by the watchdog.
        self._step_deadline: Optional[float] = None

    # -- client surface --------------------------------------------------

    def supported_schedules(self) -> List[str]:
        """Sorted ``"kind:steps"`` strings this replica can serve."""
        return sorted(f"{k[0]}:{k[1]}" for k in self.samplers)

    def supports_schedule(self, sampler_kind: Optional[str] = None,
                          steps: Optional[int] = None) -> bool:
        """Would :meth:`submit` accept this ``(sampler_kind, steps)``?
        ``None`` fields resolve to the replica default, mirroring submit
        — the router's schedule-aware placement asks this before
        choosing a replica."""
        kind = (sampler_kind if sampler_kind is not None
                else self.default_schedule[0])
        steps = steps if steps is not None else self.default_schedule[1]
        return (kind, None if steps is None else int(steps)) in self.samplers

    def submit(self, req: ViewRequest) -> ViewRequest:
        """Schedule a request (or answer it from the result cache).

        The request's schedule is resolved here — ``None`` fields take
        the replica default; a ``(sampler_kind, steps)`` outside the
        schedule registry raises :class:`UnsupportedSchedule` (typed
        retryable, carrying the supported list) instead of minting a new
        compiled program variant on demand.
        """
        kind = (req.sampler_kind if req.sampler_kind is not None
                else self.default_schedule[0])
        steps = (req.steps if req.steps is not None
                 else self.default_schedule[1])
        if (kind, steps) not in self.samplers:
            self._sched_rejects.inc()
            raise UnsupportedSchedule(
                f"{req.id}: schedule {kind}:{steps} has no compiled "
                f"bucket on this replica (supported: "
                f"{', '.join(self.supported_schedules())})",
                supported=self.supported_schedules(),
                retry_after_s=self.cfg.retry_after_s)
        if kind is not None and steps is not None:
            req.resolve_schedule(kind, steps)
        version, _ = self.registry.current()
        key = req.content_key(version)
        hit = self.result_cache.get(key)
        if hit is not None:
            req.cached = True
            req.submit_time = req.done_time = time.monotonic()
            req._resolve(hit)
            return req
        self._submitted.inc()
        if req.is_trajectory:
            self._traj_requests.inc()
        return self.scheduler.submit(req)

    def supports_cascade(self, plan_spec: Optional[str] = None) -> bool:
        """Would :meth:`submit_cascade` accept a request?  With a plan
        spec, the replica must serve exactly that plan (cascade programs
        are compiled at boot, never on client demand)."""
        if self.cascade is None:
            return False
        return (plan_spec is None
                or plan_spec == self.cascade.plan.spec())

    def submit_cascade(self, req) -> "ViewRequest":
        """Schedule a :class:`~diff3d_tpu.cascade.CascadeRequest`.

        The parent never queues; its draft child is submitted now under
        the ``(draft_resolution, "draft")`` bucket, and when every draft
        view has resolved the refine child — carrying the upsampled
        drafts — is chained in under ``(H, "refine")`` (the chaining
        callback runs on the engine loop thread at the draft's retire).
        The parent resolves with the refine child's result; any child
        failure rejects the parent.
        """
        if self.cascade is None:
            raise UnsupportedSchedule(
                f"{req.id}: this replica serves no cascade plan",
                supported=self.supported_schedules(),
                retry_after_s=self.cfg.retry_after_s)
        if req.plan.spec() != self.cascade.plan.spec():
            raise UnsupportedSchedule(
                f"{req.id}: cascade plan {req.plan.spec()} does not "
                f"match the replica's {self.cascade.plan.spec()}",
                supported=[self.cascade.plan.spec()],
                retry_after_s=self.cfg.retry_after_s)

        def chain_refine(draft_result: np.ndarray) -> None:
            # Runs on the engine loop thread inside the draft child's
            # _resolve; a submit failure propagates back into the
            # child's resolve hook, which rejects the parent.
            self.scheduler.submit(req.make_refine_child(draft_result))

        draft = req.make_draft_child(chain_refine)
        self._submitted.inc()
        self._cascade_requests.inc()
        req.submit_time = time.monotonic()
        try:
            self.scheduler.submit(draft)
        except BaseException as e:
            req._reject(e)
            raise
        return req

    def start(self) -> "Engine":
        if self._thread is not None:
            raise RuntimeError("engine already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="diff3d-serving-engine",
                                        daemon=True)
        self._thread.start()
        if self.cfg.watchdog_timeout_s > 0 and self._watchdog is None:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="diff3d-serving-watchdog", daemon=True)
            self._watchdog.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Stop the engine, joining the worker within ``timeout``.

        A worker that fails to exit (wedged in a device call) is a
        LEAKED thread: the ``serving_engine_stop_timeout_total`` counter
        is bumped and :class:`EngineStopTimeout` is raised so the
        condition is impossible to miss — the old behavior of silently
        returning left operators believing the replica had shut down.
        """
        self._stop.set()
        self.scheduler.close(reject_pending=True)
        thread, self._thread = self._thread, None
        watchdog, self._watchdog = self._watchdog, None
        if watchdog is not None:
            watchdog.join(timeout=5.0)
        if thread is not None:
            thread.join(timeout)
            if thread.is_alive():
                self._stop_timeouts.inc()
                self._reject_inflight(EngineStopped(
                    "engine stopped with the worker thread wedged"))
                raise EngineStopTimeout(
                    f"engine worker {thread.name!r} did not exit within "
                    f"{timeout}s — thread leaked (likely wedged in a "
                    "device call)")

    def drain(self, timeout: Optional[float] = 30.0,
              poll_s: float = 0.05) -> bool:
        """Graceful rollout/shutdown: stop admitting, finish everything.

        Health moves to ``draining`` and new submissions are rejected
        with :class:`EngineDraining` (clients resubmit elsewhere, after
        ``retry_after_s``).  Blocks until the queue and all in-flight
        work are resolved, up to ``timeout`` (None = wait forever).
        Returns True once empty; the caller then calls :meth:`stop`.
        """
        self._set_health(HEALTH_DRAINING)
        self.scheduler.freeze(lambda: EngineDraining(
            "replica draining for shutdown/rollout: retry elsewhere",
            retry_after_s=self.cfg.retry_after_s))
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while self.scheduler.depth() or self._inflight_count():
            if not self.alive:
                break            # nothing will make progress; report below
            if deadline is not None and time.monotonic() > deadline:
                log.warning(
                    "drain timed out with %d queued / %d in flight",
                    self.scheduler.depth(), self._inflight_count())
                return False
            time.sleep(poll_s)
        drained = not (self.scheduler.depth() or self._inflight_count())
        log.info("drain complete" if drained else "drain incomplete")
        return drained

    def resume(self) -> None:
        """Re-admit after :meth:`drain` (the blue/green rollout path):
        lift the drain freeze and any degraded soft limit, and return
        health to ``ok``.  In-flight state is untouched — drain already
        emptied it."""
        self.scheduler.unfreeze()
        self.scheduler.clear_soft_limit()
        with self._health_lock:
            self._ok_streak = 0
        self._set_health(HEALTH_OK)

    def kill(self, exc: BaseException) -> None:
        """Hard, non-blocking stop simulating replica death (chaos /
        fleet-failover path).  Unlike :meth:`stop` there is no drain and
        no join: the stop flag is set, queued requests are rejected by
        the scheduler close, and in-flight requests resolve with ``exc``
        (a typed retryable error) immediately — the loop and watchdog
        threads exit at their next check.  Safe to call from any thread,
        including the engine loop itself (a ``kill`` fault spec fires
        mid-dispatch)."""
        self._stop.set()
        self.scheduler.close(reject_pending=True)
        n = self._reject_inflight(exc)
        log.warning("engine killed (%s); rejected %d in-flight requests",
                    exc, n)

    @property
    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def health(self) -> str:
        with self._health_lock:
            return self._health

    def snapshot_extra(self, include_memory: bool = False) -> dict:
        """Engine-level details merged into the metrics snapshot.

        ``include_memory`` opts into the per-program memory blocks in the
        program-cache stats; the first request per program compiles, so
        only the explicit ``/stats`` route pays for it — hot paths
        (``/metrics``, health polls) stay compile-free."""
        return {
            "engine": {
                "alive": self.alive,
                "health": self.health,
                "restarts": self._restarts,
                "params_version": self.registry.version,
                "lane_multiple": self.lane_multiple,
                "max_batch": self.max_batch,
                "effective_max_batch": self._effective_max_batch(),
                "num_devices": jax.device_count(),
                "step_timer": self.step_timer.summary(),
                "program_cache": self.programs.stats(
                    include_memory=include_memory),
                "result_cache_entries": len(self.result_cache),
                "default_schedule": (
                    f"{self.default_schedule[0]}:{self.default_schedule[1]}"),
                "supported_schedules": self.supported_schedules(),
                "trajectories": self.trajectory_progress(),
            }
        }

    # -- health machinery ------------------------------------------------

    def _set_health(self, state: str) -> None:
        with self._health_lock:
            if self._health == state:
                return
            log.warning("engine health: %s -> %s", self._health, state)
            self._health = state
            self._health_g.set(_HEALTH_GAUGE[state])

    def _effective_max_batch(self) -> int:
        """Batch ceiling under the current health: degraded mode halves
        it (rounded up to the mesh quantum) to cut blast radius while
        the fault source is live."""
        with self._health_lock:
            degraded = self._health == HEALTH_DEGRADED
        if not degraded:
            return self.max_batch
        half = max(1, self.max_batch // 2)
        half = -(-half // self.lane_multiple) * self.lane_multiple
        return min(half, self.max_batch)

    def _note_fault(self, reason: str) -> None:
        """A step failed or stuck: degrade (unless draining) and shed."""
        self._step_faults.inc()
        with self._health_lock:
            self._ok_streak = 0
            draining = self._health == HEALTH_DRAINING
            was_ok = self._health == HEALTH_OK
        if draining or not was_ok:
            return
        self._set_health(HEALTH_DEGRADED)
        shed = self.scheduler.shed(
            lambda req: EngineOverloaded(
                f"{req.id}: shed while replica degrades ({reason}); "
                "retry later",
                retry_after_s=self.cfg.retry_after_s))
        self.scheduler.set_soft_limit(
            max(1, self.scheduler.max_queue // 4),
            lambda: EngineOverloaded(
                "replica degraded: admission reduced; retry later",
                retry_after_s=self.cfg.retry_after_s))
        log.warning("engine degraded (%s); shed %d queued requests",
                    reason, shed)

    def _note_step_ok(self) -> None:
        with self._health_lock:
            degraded = self._health == HEALTH_DEGRADED
            if degraded:
                self._ok_streak += 1
                recovered = (self._ok_streak
                             >= self.cfg.degraded_recovery_steps)
            else:
                recovered = False
        if recovered:
            self.scheduler.clear_soft_limit()
            self._set_health(HEALTH_OK)
            log.info("engine recovered: %d consecutive clean steps",
                     self.cfg.degraded_recovery_steps)

    # -- in-flight registry (shared with the watchdog) -------------------

    def _register(self, req: ViewRequest) -> None:
        with self._inflight_lock:
            self._inflight[req.id] = req
            self._traj_active_g.set(sum(
                1 for r in self._inflight.values() if r.is_trajectory))

    def _unregister(self, req: ViewRequest) -> None:
        with self._inflight_lock:
            self._inflight.pop(req.id, None)
            self._traj_active_g.set(sum(
                1 for r in self._inflight.values() if r.is_trajectory))

    def _inflight_count(self) -> int:
        with self._inflight_lock:
            return len(self._inflight)

    def inflight(self) -> int:
        """Admitted-but-unresolved requests (public: the fleet router's
        least-loaded placement reads queue depth + this)."""
        return self._inflight_count()

    def trajectory_progress(self) -> List[dict]:
        """Per-trajectory progress of admitted-but-unresolved trajectory
        requests, for ``/metrics`` (engine block) and the per-replica
        ``/fleet`` snapshot.  frames_done reads each request's own
        monotonic frame buffer — no engine state is touched, so this is
        safe from any thread."""
        with self._inflight_lock:
            trajs = [r for r in self._inflight.values() if r.is_trajectory]
        return [{
            "id": r.id,
            "session_id": r.session_id,
            "frames_done": r.frames_done(),
            "n_frames": r.n_frames,
        } for r in trajs]

    def _reject_inflight(self, exc: BaseException) -> int:
        with self._inflight_lock:
            reqs, self._inflight = list(self._inflight.values()), {}
        for req in reqs:
            self._failed.inc()
            req._reject(exc)
        return len(reqs)

    # -- watchdog --------------------------------------------------------

    def _watchdog_loop(self) -> None:
        """Detect a stuck dispatch or a dead loop thread and keep the
        replica's contract: every admitted request resolves, with a
        typed retryable error if nothing better is possible."""
        poll = max(0.05, min(0.25, self.cfg.watchdog_timeout_s / 4.0))
        while not self._stop.wait(poll):
            deadline = self._step_deadline
            if deadline is not None and time.monotonic() > deadline:
                # The dispatch has been on device longer than the step
                # budget.  Clear the deadline first so one stuck step
                # trips once, not every poll.
                self._step_deadline = None
                self._watchdog_trips.inc()
                n = self._reject_inflight(EngineStepError(
                    f"view step stuck > {self.cfg.watchdog_timeout_s}s "
                    "(watchdog); retry later",
                    retry_after_s=self.cfg.retry_after_s))
                log.error("watchdog: stuck view step; failed %d "
                          "in-flight requests", n)
                self._note_fault("stuck view step")
            thread = self._thread
            if (thread is not None and not thread.is_alive()
                    and not self._stop.is_set()):
                n = self._reject_inflight(EngineStepError(
                    "engine loop died; retry later",
                    retry_after_s=self.cfg.retry_after_s))
                self._note_fault("engine loop died")
                if self._restarts < self.cfg.engine_max_restarts:
                    self._restarts += 1
                    self._restarts_ctr.inc()
                    log.error(
                        "watchdog: engine loop died (%d in flight); "
                        "respawning (restart %d/%d)", n, self._restarts,
                        self.cfg.engine_max_restarts)
                    self._thread = threading.Thread(
                        target=self._loop, name="diff3d-serving-engine",
                        daemon=True)
                    self._thread.start()
                else:
                    log.critical(
                        "watchdog: engine loop died and the restart "
                        "budget (%d) is exhausted; failing fast",
                        self.cfg.engine_max_restarts)
                    self.scheduler.freeze(lambda: EngineStopped(
                        "engine loop dead (restart budget exhausted)"))
                    return           # nothing left to watch

    # -- executor loop ---------------------------------------------------

    def _loop(self) -> None:
        active: List[_Slot] = []
        try:
            while not self._stop.is_set():
                active = self._admit(active)
                if not active:
                    continue
                try:
                    self._run_view_step(active)
                except Exception as e:   # resolve, don't kill the server
                    log.exception("view step failed (after retries)")
                    self._note_fault(str(e).splitlines()[0][:120]
                                     if str(e) else type(e).__name__)
                    for slot in active:
                        self._failed.inc()
                        self._unregister(slot.req)
                        slot.req._reject(EngineStepError(
                            f"{slot.req.id}: view step failed ({e}); "
                            "retry later",
                            retry_after_s=self.cfg.retry_after_s))
                    active = []
                    self._active_g.set(0)
                    continue
                self._note_step_ok()
                active = self._retire(active)
        finally:
            for slot in active:
                self._unregister(slot.req)
                slot.req._reject(EngineStopped(
                    f"{slot.req.id}: engine stopped"))
            self._active_g.set(0)

    def _admit(self, active: List[_Slot]) -> List[_Slot]:
        # Drop slots whose request was resolved out from under the loop
        # (watchdog rejection, client cancel racing completion).
        done = [s for s in active if s.req.done()]
        if done:
            for slot in done:
                self._unregister(slot.req)
            active = [s for s in active if not s.req.done()]
        limit = self._effective_max_batch()
        free = limit - len(active)
        if active:
            got = self.scheduler.acquire(active[0].req.bucket, free,
                                         block=False) if free > 0 else []
        else:
            got = self.scheduler.acquire(None, limit,
                                         block=True, poll_s=0.2)
        now = time.monotonic()
        for req in got:
            self._queue_wait.observe(now - req.submit_time)
            self._register(req)
            active.append(_Slot(req, self.guidance_B))
        if got or done or not active:
            self._active_g.set(len(active))
        return active

    def _run_view_step(self, active: List[_Slot]) -> None:
        n = len(active)
        lanes = lane_count(n, self.max_batch, self.lane_multiple)
        pad = lanes - n
        # Pad by repeating lane 0 (live data: zero-filled lanes would
        # still run the full scan, and denormals/NaN paths can be slower
        # than real numbers).  Padded outputs are discarded.
        idx = list(range(n)) + [0] * pad
        record_imgs = np.stack([active[i].record_imgs for i in idx])
        record_R = np.stack([active[i].record_R for i in idx])
        record_T = np.stack([active[i].record_T for i in idx])
        steps = np.asarray([active[i].step for i in idx], np.int32)
        Ks = np.stack([active[i].req.K for i in idx])
        # Per-lane PRNG carries — the per-view split happens inside the
        # compiled step, so the stream is identical to the offline
        # synthesize loop's.
        rngs = np.stack([active[i].rng for i in idx])
        self._upload_bytes.inc(record_imgs.nbytes + record_R.nbytes
                               + record_T.nbytes + steps.nbytes
                               + Ks.nbytes + rngs.nbytes)

        version, params = self.registry.current()
        bucket = active[0].req.bucket
        # Refine-phase batches add the per-lane draft operand: lane i's
        # scan renoises the draft of the view it is about to synthesise
        # (slot.step is 1-based; drafts index 0 is view 1).
        drafts = None
        if bucket.phase == "refine":
            drafts = np.stack([active[i].drafts[active[i].step - 1]
                               for i in idx])
            self._upload_bytes.inc(drafts.nbytes)
        t0 = time.monotonic()

        def _dispatch():
            # Arm the watchdog per attempt: a retry gets a fresh step
            # budget, and the deadline is cleared even on failure so the
            # backoff sleep can't be mistaken for a stuck device.
            if self.cfg.watchdog_timeout_s > 0:
                self._step_deadline = (time.monotonic()
                                       + self.cfg.watchdog_timeout_s)
            try:
                r = self.programs.step_many(
                    bucket, lanes, record_imgs, record_R, record_T,
                    steps, Ks, rngs, params=params, drafts=drafts)
                return (np.asarray(jax.block_until_ready(r[0])),
                        np.asarray(r[3]))
            finally:
                self._step_deadline = None

        out, new_rngs = self.step_policy.call(
            _dispatch, describe=f"view step {bucket}")
        dt = time.monotonic() - t0
        self._fetch_bytes.inc(out.nbytes + new_rngs.nbytes)
        self.step_timer.tick()
        self._view_lat.observe(dt)
        self._occupancy.observe(n)
        self._padding.observe(pad / lanes if lanes else 0.0)
        self._views_done.inc(n)

        now = time.monotonic()
        for i, slot in enumerate(active):
            view = out[i]
            slot.record_imgs[slot.step] = view
            slot.rng = new_rngs[i]
            slot.outs.append(view)
            if slot.req.first_view_time is None:
                slot.req.first_view_time = now
                self._ttfv.observe(now - slot.req.submit_time)
            # Per-view commit hook: streams the frame to a trajectory
            # client the moment it lands in the record (no-op for plain
            # view requests).  Called before the step advances so the
            # frame index is the view just synthesised.
            slot.req._commit_frame(slot.step, view)
            if slot.req.is_trajectory:
                self._traj_frames.inc()
            if bucket.phase is not None:
                self._cascade_frames.inc()
            slot.step += 1
        # One params version per launched batch; remember it for the
        # result-cache key of requests that finish this step.
        self._last_version = version

    def _retire(self, active: List[_Slot]) -> List[_Slot]:
        still: List[_Slot] = []
        now = time.monotonic()
        for slot in active:
            req = slot.req
            if req.done():            # resolved elsewhere (watchdog/cancel)
                self._unregister(req)
                continue
            if req.cancelled:
                self._failed.inc()
                req._reject(RequestCancelled(f"{req.id}: cancelled"))
            elif req.expired(now):
                self._failed.inc()
                req._reject(RequestTimeout(
                    f"{req.id}: deadline exceeded mid-run at view "
                    f"{slot.step - 1}/{req.n_views - 1}"))
            elif slot.step >= req.n_views:
                result = np.stack(slot.outs)
                version = getattr(self, "_last_version",
                                  self.registry.version)
                self.result_cache.put(req.content_key(version), result)
                self._completed.inc()
                self._e2e.observe(now - req.submit_time)
                req._resolve(result)
            else:
                still.append(slot)
                continue
            self._unregister(req)     # resolved or rejected above
        if len(still) != len(active):
            self._active_g.set(len(still))
        return still
