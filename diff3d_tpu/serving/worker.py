"""Worker process: one replica behind the socket transport.

The far end of ``serving/transport.py``: a :class:`Worker` wraps one
:class:`~diff3d_tpu.serving.fleet.Replica` (touching ONLY the replica
duck-type surface, so tests can wrap scripted fakes) and serves the
framed RPC protocol — submit / poll / state / drain / resume / kill /
swap_params / snapshot / depth / supports / session ledger — plus an
optional HTTP front door (the single-replica surface: /healthz,
/metrics, /stats, /synthesize) for direct inspection of a worker.

Three things live here beyond plumbing (DESIGN.md §19):

**HBM-budgeted admission.**  The worker loads its programs' peak-HBM
manifests (the ``runs/memcheck/`` pins, ``memcheck --update``'s output)
at boot and rejects *at the door* — before any device work, before the
request even reaches the replica — when admitting a request would push
the slice past its budget::

    resident_record_bytes + request_record_bytes + program_peak_bytes
        > hbm_budget_bytes   ->  ReplicaOverBudget (503 + Retry-After)

``resident_record_bytes`` counts the device-resident record buffers of
every request still in flight on this worker (capacity × H × W × 3
float32 each — the autoregressive record the session conditions on);
``program_peak_bytes`` is the manifest pin for the request's compiled
program.  Budget, resident and headroom surface on the ``state`` RPC,
``health()`` and ``GET /stats`` so the router and operators see the
same arithmetic that rejected the request.

**Persistent compile cache.**  :func:`configure_compile_cache` points
``jax_compilation_cache_dir`` at a shared directory before the first
trace, so replica scale-out and blue/green worker restarts reuse each
other's XLA compilations instead of paying a cold compile per process.

**Replica×mesh-slice placement.**  :func:`boot_worker` builds the
replica's :class:`~diff3d_tpu.parallel.mesh.MeshEnv` over an explicit
*device subset* (``jax.devices()[lo:hi]``), so N workers on one host
pin to disjoint slices instead of sharing one default device set —
the CPU tests split the 8-virtual-device mesh 2×4.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
from http.server import ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from diff3d_tpu.analysis import membudgets
from diff3d_tpu.config import Config
from diff3d_tpu.serving.scheduler import (ReplicaOverBudget, RequestTimeout,
                                          ViewRequest)
from diff3d_tpu.serving.transport import (DEFAULT_MAX_FRAME_BYTES,
                                          FrameGarbage, FrameTooLarge,
                                          FrameTruncated, TransportError,
                                          encode_error, recv_frame,
                                          request_from_wire, send_frame)

log = logging.getLogger(__name__)

#: Programs whose manifests a worker preloads: the serving step
#: programs per sampler kind (the scan that renders views) plus the
#: warmup trace and the two cascade phase programs (DESIGN.md §20).
#: ``step_many`` is the ancestral sampler's program; other kinds append
#: their name (matching memcheck's registry).
SERVING_PROGRAMS = ("step_many", "step_many_ddim", "serving_warmup",
                    "step_many_cascade_draft", "step_many_cascade_refine")


def program_for_schedule(sampler_kind: Optional[str],
                         phase: Optional[str] = None) -> str:
    """memcheck program name for a request's (resolved) sampler kind.
    A cascade phase child maps to its phase program regardless of kind
    — the phase, not the schedule, names the compiled scan."""
    if phase is not None:
        return f"step_many_cascade_{phase}"
    if sampler_kind in (None, "ancestral"):
        return "step_many"
    return f"step_many_{sampler_kind}"


def configure_compile_cache(cache_dir: str) -> str:
    """Point jax's persistent compilation cache at ``cache_dir`` (must
    run before the first trace).  Every worker sharing the directory
    reuses each other's XLA compilations — replica scale-out and
    blue/green restarts skip the cold compile."""
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # Serving programs are exactly the long-compile artifacts the cache
    # exists for; cache everything, however small.
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


class HbmAdmission:
    """The admission gate: budget arithmetic over resident records.

    Tracks the record bytes of every in-flight request (reserved at
    admission, released when the request resolves) and the per-program
    peak pins from the memcheck manifests.  ``budget_bytes <= 0``
    disables the gate (the default for tests that only exercise the
    transport).
    """

    def __init__(self, budget_bytes: int = 0,
                 manifest_dir: str = membudgets.DEFAULT_MANIFEST_DIR,
                 replica_name: str = "?",
                 retry_after_s: float = 5.0):
        self.budget_bytes = int(budget_bytes)
        self.replica_name = replica_name
        self.retry_after_s = float(retry_after_s)
        self._lock = threading.Lock()
        self._reserved: Dict[str, int] = {}  # guarded-by: self._lock
        self._rejects = 0  # guarded-by: self._lock
        self._warned_unpinned: set = set()  # guarded-by: self._lock
        self.program_peaks: Dict[str, int] = {}
        self._load_manifests(manifest_dir)

    def _load_manifests(self, manifest_dir: str) -> None:
        for program in SERVING_PROGRAMS:
            path = membudgets.manifest_path(program, manifest_dir)
            if not os.path.exists(path):
                continue
            try:
                manifest = membudgets.load_manifest(path)
            except (ValueError, json.JSONDecodeError) as e:
                log.warning("hbm admission: unreadable manifest %s: %s",
                            path, e)
                continue
            self.program_peaks[program] = manifest.budgets.peak_bytes

    @staticmethod
    def record_bytes(req: ViewRequest) -> int:
        """Device-resident record footprint of one admitted request:
        the float32 record buffer the autoregressive sampler conditions
        on (capacity × H × W × 3 lanes of 4 bytes)."""
        b = req.bucket
        return b.capacity * b.H * b.W * 3 * 4

    def program_peak(self, sampler_kind: Optional[str],
                     phase: Optional[str] = None) -> int:
        """Manifest pin for the request's program; a kind with no
        committed manifest is charged the largest known pin (admission
        must stay conservative for unpinned programs, not free) — and
        warns once per program name, so an unpinned cascade phase
        riding the fallback is visible, not silent."""
        program = program_for_schedule(sampler_kind, phase)
        peak = self.program_peaks.get(program)
        if peak is not None:
            return peak
        fallback = max(self.program_peaks.values(), default=0)
        with self._lock:
            warn = program not in self._warned_unpinned
            if warn:
                self._warned_unpinned.add(program)
        if warn:
            log.warning(
                "hbm admission: program %r has no committed memcheck "
                "manifest pin — charging the largest known pin "
                "(%d bytes); run `python -m diff3d_tpu.analysis.memcheck "
                "--update` to pin it", program, fallback)
        return fallback

    def admit(self, req: ViewRequest,
              default_kind: Optional[str] = None) -> None:
        """Reserve the request's footprint or raise
        :class:`ReplicaOverBudget` — atomic under the gate's lock, so
        two concurrent submits can never both squeeze under the line.

        Cascade work is charged its actual phase pin: a phase child
        carries ``bucket.phase``, and a cascade parent (whose children
        have not been derived yet) is charged the refine pin — the
        full-resolution phase, i.e. the cascade's own peak — instead of
        the cross-program largest-pin fallback."""
        if self.budget_bytes <= 0:
            return
        kind = req.sampler_kind if req.sampler_kind is not None \
            else default_kind
        phase = getattr(req.bucket, "phase", None) \
            if req.bucket is not None else None
        if phase is None and getattr(req, "is_cascade", False):
            phase = "refine"
        need = self.record_bytes(req)
        peak = self.program_peak(kind, phase=phase)
        with self._lock:
            resident = sum(self._reserved.values())
            if resident + need + peak > self.budget_bytes:
                self._rejects += 1
                raise ReplicaOverBudget(
                    f"{req.id}: admitting {need} record bytes would "
                    f"exceed the slice HBM budget: resident {resident} "
                    f"+ record {need} + program peak {peak} > budget "
                    f"{self.budget_bytes}",
                    replica=self.replica_name,
                    retry_after_s=self.retry_after_s,
                    budget_bytes=self.budget_bytes,
                    resident_bytes=resident,
                    program_peak_bytes=peak)
            self._reserved[req.id] = need

    def release(self, request_id: str) -> None:
        with self._lock:
            self._reserved.pop(request_id, None)

    def snapshot(self) -> dict:
        """The /stats + state-RPC block: the exact arithmetic admission
        runs, so a rejected client can see why."""
        with self._lock:
            resident = sum(self._reserved.values())
            rejects = self._rejects
        return {
            "budget_bytes": self.budget_bytes,
            "resident_bytes": resident,
            "headroom_bytes": (self.budget_bytes - resident
                               if self.budget_bytes > 0 else None),
            "program_peaks": dict(self.program_peaks),
            "rejects": rejects,
            "enabled": self.budget_bytes > 0,
        }


class Worker:
    """Socket server exposing one replica over the framed protocol.

    One accept loop, one handler thread per connection (RemoteReplica
    holds two long-lived connections — control + poller — and dials
    ephemeral ones for lifecycle calls).  Handler threads do pure host
    work; device calls stay on the replica's engine thread, so ``state``
    probes answer while a multi-minute job is on the chip.
    """

    def __init__(self, replica, cfg: Config, *,
                 host: str = "127.0.0.1", port: int = 0,
                 admission: Optional[HbmAdmission] = None,
                 default_sampler_kind: Optional[str] = None):
        self.replica = replica
        self.cfg = cfg
        self.host = host
        self._requested_port = int(port)
        self.admission = admission or HbmAdmission(
            0, replica_name=replica.name)
        self._default_kind = default_sampler_kind
        self.max_frame_bytes = int(getattr(
            cfg.serving, "max_frame_bytes", DEFAULT_MAX_FRAME_BYTES))
        self._lock = threading.Lock()
        self._requests: Dict[str, ViewRequest] = {}  # guarded-by: self._lock
        self._conns: List[socket.socket] = []  # guarded-by: self._lock
        self._stopping = False  # guarded-by: self._lock
        self._sock: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        # Worker-side metrics: reuse the replica's registry when it has
        # one (Replica does) so /metrics shows engine + admission in one
        # exposition; scripted fakes get a private registry.
        metrics = getattr(replica, "metrics", None)
        if metrics is None:
            from diff3d_tpu.serving.metrics import MetricsRegistry
            metrics = MetricsRegistry()
        self.metrics = metrics
        self._rejects_ctr = metrics.counter(
            "worker_admission_rejects_hbm_total",
            "requests rejected at the door by the HBM admission gate")
        self._resident_gauge = metrics.gauge(
            "worker_hbm_resident_bytes",
            "record bytes of in-flight requests counted by admission")
        self._headroom_gauge = metrics.gauge(
            "worker_hbm_headroom_bytes",
            "bytes left under the slice HBM budget (0 when disabled)")

    # -- lifecycle -------------------------------------------------------

    def start(self, http_port: Optional[int] = None) -> "Worker":
        self.replica.start()
        self._sock = socket.create_server((self.host, self._requested_port))
        self._sock.listen(32)
        self._accept_thread = threading.Thread(
            target=self._accept_loop,
            name=f"diff3d-worker-{self.replica.name}", daemon=True)
        self._accept_thread.start()
        if http_port is not None:
            from diff3d_tpu.serving.server import make_http_server
            self._httpd = make_http_server(self, self.host, http_port)
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name=f"diff3d-worker-http-{self.replica.name}", daemon=True)
            self._http_thread.start()
        log.info("worker %s: serving on %s:%d", self.replica.name,
                 self.host, self.port)
        return self

    @property
    def port(self) -> int:
        if self._sock is None:
            return self._requested_port
        return self._sock.getsockname()[1]

    @property
    def http_port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def stop(self, timeout: float = 10.0) -> None:
        """Close the listener and every open connection, then stop the
        replica.  Clients see the close as FrameTruncated and their
        heartbeat marks this worker dead — the abrupt shape a SIGKILL
        would have, which is exactly what the chaos tests rely on."""
        with self._lock:
            if self._stopping:
                return
            self._stopping = True
            conns = list(self._conns)
        if self._sock is not None:
            # shutdown() before close(): close() alone leaves a thread
            # blocked in accept() pinned until the join timeout.
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._accept_thread is not None:
            self._accept_thread.join(timeout)
        self.replica.stop(timeout=timeout)

    # -- accept / dispatch ----------------------------------------------

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, addr = self._sock.accept()
            except OSError:
                return      # listener closed: shutting down
            with self._lock:
                if self._stopping:
                    conn.close()
                    return
                self._conns.append(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn, addr),
                name=f"diff3d-worker-conn-{addr[1]}", daemon=True).start()

    def _serve_conn(self, conn: socket.socket, addr) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            while True:
                try:
                    frame = recv_frame(conn, self.max_frame_bytes)
                except (FrameTooLarge, FrameGarbage) as e:
                    # Protocol violation: tell the peer (typed), then
                    # drop the connection — the stream offset is lost.
                    self._reply_error(conn, e)
                    return
                except (FrameTruncated, OSError):
                    return
                if frame is None:
                    return      # clean EOF
                op = str(frame.get("op", ""))
                args = frame.get("args") or {}
                try:
                    value = self._dispatch(op, args)
                except Exception as e:   # typed errors cross the wire
                    self._reply_error(conn, e)
                    continue
                try:
                    send_frame(conn, {"ok": True, "value": value},
                               self.max_frame_bytes)
                except (TransportError, OSError):
                    return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _reply_error(self, conn: socket.socket, exc: BaseException) -> None:
        try:
            send_frame(conn, {"ok": False, "error": encode_error(exc)},
                       self.max_frame_bytes)
        except (TransportError, OSError):
            pass

    def _dispatch(self, op: str, args: dict) -> Any:
        if op == "ping":
            return "pong"
        if op == "state":
            return self._state()
        if op == "submit":
            return self._op_submit(args)
        if op == "poll":
            return self._op_poll(args)
        if op == "depth":
            return self.replica.depth()
        if op == "supports":
            return bool(self.replica.supports(
                args.get("sampler_kind"), args.get("steps")))
        if op == "session_records":
            return self.replica.session_records()
        if op == "session_count":
            return self.replica.session_count(args.get("session_id"))
        if op == "snapshot":
            snap = dict(self.replica.snapshot())
            snap["hbm"] = self.admission.snapshot()
            return snap
        if op == "drain":
            return bool(self.replica.drain(timeout=args.get("timeout")))
        if op == "resume":
            self.replica.resume()
            return True
        if op == "kill":
            self.replica.kill(str(args.get("reason", "killed")))
            return True
        if op == "swap_params":
            return self._op_swap(args)
        raise ValueError(f"unknown op {op!r}")

    # -- op implementations ----------------------------------------------

    def _state(self) -> dict:
        """The heartbeat payload: everything the RemoteReplica caches."""
        hbm = self.admission.snapshot()
        self._resident_gauge.set(hbm["resident_bytes"])
        self._headroom_gauge.set(hbm["headroom_bytes"] or 0)
        return {
            "name": self.replica.name,
            "health": self.replica.health,
            "depth": self.replica.depth(),
            "params_version": self.replica.params_version,
            "supported_schedules": self.replica.supported_schedules(),
            "session_records": self.replica.session_records(),
            "hbm": hbm,
        }

    def _op_submit(self, args: dict) -> dict:
        req = request_from_wire(args)
        # Admission BEFORE the replica sees the request: a rejected
        # request does no device work and leaves no ledger trace.
        try:
            self.admission.admit(req, default_kind=self._default_kind)
        except ReplicaOverBudget:
            self._rejects_ctr.inc()
            raise
        try:
            self.replica.submit(req)
        except BaseException:
            self.admission.release(req.id)
            raise
        with self._lock:
            self._requests[req.id] = req
        return {"id": req.id, "accepted": True}

    def _op_poll(self, args: dict) -> dict:
        """One poll turn for a submitted request: block up to ``wait_s``
        for progress, then report status + any frames past ``from``.
        Terminal polls release the admission reservation and drop the
        request from the table (the client owns the result now)."""
        rid = str(args.get("id", ""))
        start = max(0, int(args.get("from", 0)))
        wait_s = min(5.0, max(0.0, float(args.get("wait_s", 0.2))))
        with self._lock:
            req = self._requests.get(rid)
        if req is None:
            return {"id": rid, "status": "unknown"}
        out: Dict[str, Any] = {"id": rid, "status": "pending"}
        if req.is_trajectory:
            try:
                frames = req.wait_frames(start, timeout=wait_s)
            except BaseException:
                frames = req.frames_since(start)
            if frames:
                out["frames"] = [np.asarray(f) for f in frames]
        else:
            try:
                req.result(timeout=wait_s)
            except RequestTimeout:
                if not req.done():
                    return out      # genuinely still running
            except BaseException:
                pass                # terminal failure: classified below
        if not req.done():
            return out
        self._forget(rid)
        err = req.error
        if err is not None:
            out["status"] = "failed"
            out["error"] = encode_error(err)
            return out
        out["status"] = "done"
        out["cached"] = bool(req.cached)
        out["result"] = np.asarray(req.result(timeout=0))
        return out

    def _forget(self, rid: str) -> None:
        self.admission.release(rid)
        with self._lock:
            self._requests.pop(rid, None)

    def _op_swap(self, args: dict) -> str:
        """Rebuild the params pytree from wire leaves against the
        replica's own treedef (the registry's shape guard still runs),
        then swap — the blue/green rollout step, cross-process."""
        import jax

        leaves = args.get("leaves")
        if leaves is None:
            raise ValueError("swap_params needs 'leaves'")
        current = getattr(self.replica, "registry", None)
        if current is None:
            # Scripted fakes have no registry: pass leaves through.
            return str(self.replica.swap_params(leaves,
                                                args.get("version")))
        _, params = current.current()
        treedef = jax.tree.structure(params)
        params_new = jax.tree.unflatten(
            treedef, [np.asarray(leaf) for leaf in leaves])
        return str(self.replica.swap_params(params_new,
                                            args.get("version")))

    # -- ServingService duck-type (optional HTTP front door) -------------

    def submit(self, payload: dict) -> ViewRequest:
        from diff3d_tpu.serving.server import build_request
        req = build_request(payload, self.cfg)
        return self._admit_and_submit(req)

    def submit_trajectory(self, payload: dict) -> ViewRequest:
        from diff3d_tpu.serving.server import build_trajectory_request
        req = build_trajectory_request(payload, self.cfg)
        return self._admit_and_submit(req)

    def _admit_and_submit(self, req: ViewRequest) -> ViewRequest:
        try:
            self.admission.admit(req, default_kind=self._default_kind)
        except ReplicaOverBudget:
            self._rejects_ctr.inc()
            raise
        try:
            self.replica.submit(req)
        except BaseException:
            self.admission.release(req.id)
            raise
        with self._lock:
            self._requests[req.id] = req
        return req

    def get_request(self, request_id: str) -> Optional[ViewRequest]:
        with self._lock:
            return self._requests.get(request_id)

    def result_payload(self, req: ViewRequest) -> dict:
        from diff3d_tpu.serving.server import result_payload
        return result_payload(req)

    def health(self) -> dict:
        return {
            "status": self.replica.health,
            "replica": self.replica.name,
            "queue_depth": self.replica.depth(),
            "params_version": self.replica.params_version,
            "supported_schedules": self.replica.supported_schedules(),
            "hbm": self.admission.snapshot(),
        }

    def metrics_snapshot(self, include_memory: bool = False) -> dict:
        extra = {"hbm": self.admission.snapshot(),
                 "replica": self.replica.snapshot()}
        return self.metrics.snapshot(extra=extra)


def device_slice(spec: str) -> List[int]:
    """Parse a ``--devices`` slice: ``"0-3"`` (inclusive range) or
    ``"0,1,2"`` (explicit list) into device indices."""
    spec = spec.strip()
    if "-" in spec and "," not in spec:
        lo, hi = spec.split("-", 1)
        idx = list(range(int(lo), int(hi) + 1))
    else:
        idx = [int(p) for p in spec.split(",") if p.strip()]
    if not idx:
        raise ValueError(f"--devices {spec!r}: empty device slice")
    if len(set(idx)) != len(idx):
        raise ValueError(f"--devices {spec!r}: duplicate device index")
    return idx


def boot_worker(cfg: Config, *, name: str, devices: List[int],
                sampler_kind: str = "ancestral", steps: Optional[int] = None,
                extra_schedules: Optional[List[Tuple[str, int]]] = None,
                params=None, params_version: str = "v0",
                host: str = "127.0.0.1", port: int = 0,
                hbm_budget_bytes: int = 0,
                memcheck_dir: str = membudgets.DEFAULT_MANIFEST_DIR,
                compile_cache: Optional[str] = None,
                scan_chunks: int = 1) -> Worker:
    """Build a worker: mesh over the device slice, model + samplers,
    replica, admission gate, socket server.  ``params=None`` draws
    random init params (the test/dev path)."""
    if compile_cache:
        configure_compile_cache(compile_cache)
    import jax

    from diff3d_tpu.models import XUNet
    from diff3d_tpu.parallel.mesh import make_mesh
    from diff3d_tpu.sampling import Sampler
    from diff3d_tpu.serving.fleet import Replica
    from diff3d_tpu.train.trainer import init_params

    all_devices = jax.devices()
    bad = [i for i in devices if i >= len(all_devices)]
    if bad:
        raise ValueError(
            f"device indices {bad} out of range: backend has "
            f"{len(all_devices)} devices")
    slice_devices = [all_devices[i] for i in devices]
    mesh_env = make_mesh(cfg.mesh, devices=slice_devices)

    model = XUNet(cfg.model)
    if params is None:
        params = init_params(model, cfg, jax.random.PRNGKey(0))
    default_steps = steps if steps is not None else cfg.diffusion.timesteps
    sampler = Sampler(model, params, cfg, scan_chunks=scan_chunks,
                      mesh=mesh_env, sampler_kind=sampler_kind,
                      steps=default_steps)
    extra = {}
    for kind, n_steps in (extra_schedules or []):
        if (kind, n_steps) == (sampler_kind, default_steps):
            continue
        extra[(kind, n_steps)] = Sampler(
            model, params, cfg, scan_chunks=scan_chunks, mesh=mesh_env,
            sampler_kind=kind, steps=n_steps)

    replica = Replica(name, sampler, cfg, extra_samplers=extra or None,
                      params_version=params_version)
    admission = HbmAdmission(
        hbm_budget_bytes, manifest_dir=memcheck_dir, replica_name=name,
        retry_after_s=cfg.serving.retry_after_s)
    return Worker(replica, cfg, host=host, port=port, admission=admission,
                  default_sampler_kind=sampler_kind)
