"""Request queue + microbatcher for the inference service.

Requests are grouped into **shape buckets** ``(H, W, record capacity)`` —
the tuple that determines the compiled program for a view step (the batch
lane count is handled by the engine's power-of-two padding).  Capacity
comes from :func:`diff3d_tpu.sampling.record_capacity`, so a served
request lands on exactly the program shape the offline sampler would
compile for the same view count.

Scheduling policy (Orca-style iteration-level scheduling, adapted to
fixed-length diffusion scans):
  * the engine asks for work *between view steps*, so a long 20-view job
    never blocks a 1-view job for more than one view's worth of compute;
  * an idle engine blocks until a request arrives, then waits at most
    ``max_wait`` (measured from the oldest pending request's submit time)
    for co-batchable requests before launching underfull;
  * the queue is **bounded**: submissions beyond ``max_queue`` raise
    :class:`QueueFullError` immediately (explicit backpressure, HTTP 429),
    and every request carries a deadline after which it is resolved with
    :class:`RequestTimeout` instead of silently rotting in the queue.
"""

from __future__ import annotations

import hashlib
import itertools
import threading
import time
from collections import OrderedDict, deque
from typing import Callable, Deque, Dict, List, NamedTuple, Optional

import numpy as np

from diff3d_tpu.diffusion import SAMPLER_KINDS
from diff3d_tpu.runtime.retry import RetryableError
from diff3d_tpu.sampling import record_capacity


class Bucket(NamedTuple):
    """Shape key of a compiled view-step program (minus the lane count).

    ``steps`` / ``sampler`` extend the key to the *schedule* of the
    compiled scan: a 16-step DDIM program and a 256-step ancestral one
    differ in trip count and update rule, so they can never share a
    compilation.  ``None`` (the defaults, kept for positional
    compatibility) means "the engine's default schedule" — the engine
    resolves them to concrete values at submit time, before any request
    reaches the scheduler or the program cache.

    ``phase`` extends the key to the cascade's ``(resolution, phase)``
    space (DESIGN.md §20): ``"draft"`` runs the low-resolution student
    schedule, ``"refine"`` the truncated high-resolution one (its
    program takes an extra drafts operand, so it can never share a
    compilation with a plain view step even at equal shapes).  ``None``
    — every non-cascade request — keeps the tuple positionally
    backward compatible.  The resolution half of the cascade key is
    already carried by ``H``/``W``.
    """

    H: int
    W: int
    capacity: int
    steps: Optional[int] = None
    sampler: Optional[str] = None
    phase: Optional[str] = None


class QueueFullError(RuntimeError):
    """Bounded queue is full — request rejected at submit time."""


class RequestTimeout(RuntimeError):
    """Request deadline expired before (or while) running."""


class RequestCancelled(RuntimeError):
    """Request was cancelled by the client before completion."""


# Typed retryable rejections (see diff3d_tpu/runtime/retry.py): the
# request did not fail on its own merits — the *replica* faulted, shed,
# or is going away — so the client (or a future multi-replica router)
# should retry it elsewhere or after `retry_after_s`.

class EngineStepError(RetryableError):
    """A view step failed or stuck; in-flight requests were resolved
    with this instead of hanging their futures."""


class EngineOverloaded(RetryableError):
    """Degraded-mode admission control: shed or rejected to protect the
    replica while it recovers."""


class EngineDraining(RetryableError):
    """Replica is draining for shutdown/rollout; resubmit elsewhere."""


class EngineStopped(RetryableError):
    """Replica stopped before the request could run."""


class UnsupportedSchedule(RetryableError):
    """The request's ``(sampler_kind, steps)`` has no compiled program on
    this replica.  Compiling on demand would let clients mint unbounded
    program-cache variants, so the request is rejected with the replica's
    ``supported`` schedules (a list of ``"kind:steps"`` strings) — a
    router can resubmit to a replica that serves the schedule."""

    def __init__(self, msg: str, *,
                 supported: Optional[List[str]] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg, retry_after_s=retry_after_s)
        self.supported = list(supported or [])


# Fleet-level typed rejections (serving/router.py).  Same taxonomy, one
# level up: the *fleet*, not a single replica, could not place the
# request right now.

class FleetOverloaded(RetryableError):
    """No eligible replica can admit the request: every replica that
    serves the schedule is full, degraded past its soft limit, draining,
    or dead.  Purely a capacity signal — retry the same request after
    ``retry_after_s``."""


class ReplicaDraining(RetryableError):
    """The session's owning replica is draining (blue/green rollout).
    The device-resident record stays where it is — the session must NOT
    be restarted elsewhere; retry the same session after
    ``retry_after_s`` and it will land on the re-admitted replica."""

    def __init__(self, msg: str, *, replica: Optional[str] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg, retry_after_s=retry_after_s)
        self.replica = replica


class SessionLost(RetryableError):
    """The session's owning replica is gone (killed/dead), and the
    device-resident record died with it.  ``replica`` names the lost
    owner.  Retryable in the *session* sense: the client restarts the
    session from its committed views — a bare resubmit of view N would
    condition on state that no longer exists anywhere."""

    def __init__(self, msg: str, *, replica: Optional[str] = None,
                 retry_after_s: Optional[float] = None):
        super().__init__(msg, retry_after_s=retry_after_s)
        self.replica = replica


class ReplicaOverBudget(RetryableError):
    """HBM-budgeted admission control (serving/worker.py): admitting
    this request would push the replica's device slice past its HBM
    budget — resident session-record bytes plus the compiled program's
    peak (the ``runs/memcheck/`` manifest pin) exceed
    ``hbm_budget_bytes``.  Rejected *at the door*, before any device
    work; purely a capacity signal, so retry after ``retry_after_s``
    (or place the request on a replica with headroom)."""

    def __init__(self, msg: str, *, replica: Optional[str] = None,
                 retry_after_s: Optional[float] = None,
                 budget_bytes: int = 0, resident_bytes: int = 0,
                 program_peak_bytes: int = 0):
        super().__init__(msg, retry_after_s=retry_after_s)
        self.replica = replica
        self.budget_bytes = int(budget_bytes)
        self.resident_bytes = int(resident_bytes)
        self.program_peak_bytes = int(program_peak_bytes)

    @property
    def headroom_bytes(self) -> int:
        """Bytes left under the budget before this request's footprint
        (negative means resident state alone is already over)."""
        return self.budget_bytes - self.resident_bytes


_req_ids = itertools.count()


class ViewRequest:
    """One novel-view synthesis job: autoregressively generate views
    ``1..n_views-1`` of an object from its view-0 image and the target
    poses, with the per-request RNG stream of
    ``Sampler.synthesize(views, PRNGKey(seed))`` (same seed => bit-equal
    result on the same backend).

    ``views`` is the ``all_views``-style dict: ``imgs [>=1, H, W, 3]``
    (only view 0 is consumed), ``R [n, 3, 3]``, ``T [n, 3]``,
    ``K [3, 3]``.

    ``sampler_kind`` / ``steps`` select the reverse-process schedule;
    ``None`` means "replica default" and is resolved by the engine at
    submit time (:meth:`resolve_schedule`) — a request never queues with
    an unresolved schedule.

    ``session_id`` names the object session this request extends (router
    affinity key, DESIGN.md §14): all requests carrying the same
    session_id must run on the replica holding the session's
    device-resident record.  ``None`` = sessionless (any replica).  The
    id does not enter :meth:`content_key` — identical inputs produce
    identical results whichever session asked.
    """

    def __init__(self, views: dict, seed: int = 0,
                 n_views: Optional[int] = None,
                 timeout_s: Optional[float] = None,
                 request_id: Optional[str] = None,
                 sampler_kind: Optional[str] = None,
                 steps: Optional[int] = None,
                 session_id: Optional[str] = None):
        imgs = np.asarray(views["imgs"], np.float32)
        R = np.asarray(views["R"], np.float32)
        T = np.asarray(views["T"], np.float32)
        K = np.asarray(views["K"], np.float32)
        if imgs.ndim != 4 or imgs.shape[-1] != 3:
            raise ValueError(f"imgs must be [n, H, W, 3], got {imgs.shape}")
        if R.ndim != 3 or R.shape[-2:] != (3, 3):
            raise ValueError(f"R must be [n, 3, 3], got {R.shape}")
        if T.ndim != 2 or T.shape[-1] != 3:
            raise ValueError(f"T must be [n, 3], got {T.shape}")
        if K.shape != (3, 3):
            raise ValueError(f"K must be [3, 3], got {K.shape}")
        if R.shape[0] != T.shape[0]:
            raise ValueError(
                f"R/T view counts differ: {R.shape[0]} vs {T.shape[0]}")
        avail = R.shape[0]
        self.n_views = avail if n_views is None else min(int(n_views),
                                                         avail)
        if self.n_views < 2:
            raise ValueError(
                f"n_views={self.n_views}: need >= 2 (view 0 conditions, "
                "views 1.. are synthesised)")
        self.imgs0 = imgs[0]
        self.R = R[:self.n_views]
        self.T = T[:self.n_views]
        self.K = K
        self.seed = int(seed)
        self.timeout_s = timeout_s
        if sampler_kind is not None and sampler_kind not in SAMPLER_KINDS:
            raise ValueError(
                f"sampler_kind={sampler_kind!r} not in {SAMPLER_KINDS}")
        if steps is not None:
            steps = int(steps)
            if steps < 1:
                raise ValueError(f"steps={steps} must be >= 1")
        self.sampler_kind = sampler_kind
        self.steps = steps
        self.session_id = None if session_id is None else str(session_id)
        H, W = imgs.shape[1:3]
        self._HW = (H, W)
        self.bucket = Bucket(H, W, record_capacity(self.n_views),
                             steps, sampler_kind)
        self.id = request_id or f"req-{next(_req_ids)}"

        self.submit_time: Optional[float] = None
        self.deadline: Optional[float] = None
        self.first_view_time: Optional[float] = None
        self.done_time: Optional[float] = None
        self.cached = False

        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result: Optional[np.ndarray] = None  # guarded-by: self._lock
        self._error: Optional[BaseException] = None  # guarded-by: self._lock
        self._cancelled = False  # guarded-by: self._lock

    # -- result plumbing ------------------------------------------------

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        # Read-after-done: _resolve/_reject write under _lock and then
        # Event.set; callers look only after done(), so the Event
        # publish gives the happens-before the lock normally would.
        return self._error  # lockcheck: disable=LC302(happens-before via _event.set)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block for the result ``[n_views-1, B, H, W, 3]``; raises the
        request's error (:class:`RequestTimeout`, ...) if it failed."""
        if not self._event.wait(timeout):
            raise RequestTimeout(
                f"{self.id}: no result within {timeout}s")
        # Event.wait returned True, so the writes in _resolve/_reject
        # happen-before these reads — no lock needed.
        err = self._error  # lockcheck: disable=LC302(happens-before via _event.wait)
        if err is not None:
            raise err
        return self._result  # lockcheck: disable=LC302(happens-before via _event.wait)

    def _resolve(self, result: np.ndarray) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._result = result
            self.done_time = time.monotonic()
            self._event.set()

    def _reject(self, exc: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._error = exc
            self.done_time = time.monotonic()
            self._event.set()

    def cancel(self) -> bool:
        """Best-effort cancel; returns False once the request finished.
        A request already admitted to the engine finishes its in-flight
        view step, then is dropped before the next one."""
        with self._lock:
            if self._event.is_set():
                return False
            self._cancelled = True
        return True

    @property
    def cancelled(self) -> bool:
        # Monotonic flag: a stale False only delays the drop to the
        # scheduler's next sweep.
        return self._cancelled  # lockcheck: disable=LC302(racy read of monotonic flag is benign)

    def expired(self, now: Optional[float] = None) -> bool:
        if self.deadline is None:
            return False
        return (time.monotonic() if now is None else now) > self.deadline

    def resolve_schedule(self, sampler_kind: str, steps: int) -> None:
        """Fill in replica defaults and rebuild the bucket with a fully
        concrete schedule.  Called by the engine at submit time, before
        the request can reach the scheduler, result cache, or program
        cache — so every queued request's bucket names the exact compiled
        program that will serve it."""
        self.sampler_kind = str(sampler_kind)
        self.steps = int(steps)
        H, W = self._HW
        self.bucket = Bucket(H, W, record_capacity(self.n_views),
                             self.steps, self.sampler_kind,
                             self.bucket.phase)

    def content_key(self, params_version: str, extra: str = "") -> str:
        """Content hash for the result cache: identical inputs + seed +
        schedule + params version => identical output (the sampler is
        deterministic given the key), so replays can skip the chip
        entirely."""
        h = hashlib.sha256()
        for a in (self.imgs0, self.R, self.T, self.K):
            h.update(np.ascontiguousarray(a).tobytes())
        h.update(f"|{self.seed}|{self.n_views}|{self.sampler_kind}"
                 f"|{self.steps}|{params_version}|{extra}".encode())
        return h.hexdigest()

    # -- per-view commit hook (trajectory streaming) ---------------------

    def _commit_frame(self, view_index: int, frame: np.ndarray) -> None:
        """Engine hook, called once per synthesised view right after the
        view step that produced it.  No-op for plain view requests —
        :class:`TrajectoryRequest` overrides it to stream frames to the
        client before the request resolves."""

    @property
    def is_trajectory(self) -> bool:
        return False


class TrajectoryRequest(ViewRequest):
    """A camera-path rendering job: one request = render every pose of a
    trajectory, streaming frames to the client *as they commit* to the
    record instead of only resolving at the end.

    Same device contract as :class:`ViewRequest` — views 1..n_views-1
    synthesised autoregressively from the view-0 conditioning image,
    identical RNG stream, same Bucket space (so trajectory chunks from
    different objects co-batch with each other and with plain view
    requests through the shared compiled scan).  What it adds is a
    monotonic frame buffer with its own condition variable: the engine
    calls :meth:`_commit_frame` after each view step, and HTTP handler
    threads block in :meth:`wait_frames` to stream them out (incremental
    poll with ``?from=K``, or chunked NDJSON).

    ``frame k`` (0-based) is synthesised view ``k + 1`` — the
    conditioning view is never echoed back.  Frames arrive strictly in
    commit order; on a result-cache hit (or any resolve that skipped
    the engine) the buffer is backfilled from the full result so the
    streaming surface behaves identically.
    """

    def __init__(self, views: dict, **kwargs):
        super().__init__(views, **kwargs)
        self._frames_lock = threading.Lock()
        self._frames_cv = threading.Condition(self._frames_lock)
        # Committed frames, strictly in order; index k = view k+1.
        self._frames: List[np.ndarray] = []  # guarded-by: self._frames_lock

    @property
    def is_trajectory(self) -> bool:
        return True

    @property
    def n_frames(self) -> int:
        """Frames this trajectory renders (poses past the conditioning
        view)."""
        return self.n_views - 1

    def _commit_frame(self, view_index: int, frame: np.ndarray) -> None:
        with self._frames_cv:
            # The engine commits views in order; anything else would
            # break the autoregressive record, so drop out-of-order
            # duplicates (watchdog rejection racing a late commit).
            if view_index != len(self._frames) + 1:
                return
            self._frames.append(frame)
            self._frames_cv.notify_all()

    def frames_done(self) -> int:
        with self._frames_lock:
            return len(self._frames)

    def frames_since(self, start: int = 0) -> List[np.ndarray]:
        """Committed frames ``start..`` (non-blocking snapshot)."""
        with self._frames_lock:
            return list(self._frames[max(0, int(start)):])

    def wait_frames(self, start: int,
                    timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block until at least one frame past ``start`` is committed
        (or the request resolves), then return frames ``start..``.
        Returns ``[]`` only on timeout or when the request finished with
        ``start`` >= the final frame count; a failed request raises its
        error once every committed frame has been consumed — frames
        that did commit are always deliverable."""
        start = max(0, int(start))
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._frames_cv:
            while len(self._frames) <= start and not self._event.is_set():
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                self._frames_cv.wait(remaining)
            got = list(self._frames[start:])
        if not got and self._event.is_set():
            err = self.error
            if err is not None:
                raise err
        return got

    # Resolution overrides: backfill the frame buffer on resolve (the
    # result-cache path never runs the engine, so nothing committed) and
    # wake streaming waiters on both resolve and reject — a client
    # blocked in wait_frames must observe terminal states promptly.

    def _resolve(self, result: np.ndarray) -> None:
        super()._resolve(result)
        with self._frames_cv:
            for k in range(len(self._frames), result.shape[0]):
                self._frames.append(result[k])
            self._frames_cv.notify_all()

    def _reject(self, exc: BaseException) -> None:
        super()._reject(exc)
        with self._frames_cv:
            self._frames_cv.notify_all()


class Scheduler:
    """Bounded, bucketed FIFO with deadline sweeping.

    The engine is the single consumer; producers are HTTP handler
    threads calling :meth:`submit`.
    """

    def __init__(self, max_queue: int = 64, max_wait_s: float = 0.05,
                 default_timeout_s: float = 300.0, metrics=None):
        self.max_queue = max_queue
        self.max_wait_s = max_wait_s
        self.default_timeout_s = default_timeout_s
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._pending: "OrderedDict[Bucket, Deque[ViewRequest]]" = (
            OrderedDict())  # guarded-by: self._lock
        self._closed = False  # guarded-by: self._lock
        # Fault-tolerance admission policy (set by the engine): when
        # frozen, every submission is rejected with the factory's typed
        # error (drain mode / dead engine); a soft limit rejects
        # submissions beyond a reduced depth while degraded.
        self._frozen: Optional[Callable[[], BaseException]] = (
            None)  # guarded-by: self._lock
        self._soft_limit: Optional[int] = None  # guarded-by: self._lock
        self._soft_exc: Optional[Callable[[], BaseException]] = (
            None)  # guarded-by: self._lock
        m = metrics
        self._depth_gauge = m.gauge(
            "serving_queue_depth",
            "requests waiting for admission") if m else None
        self._timeouts = m.counter(
            "serving_requests_timeout_total",
            "requests expired before completion") if m else None
        self._rejects = m.counter(
            "serving_requests_rejected_total",
            "submissions rejected by the bounded queue") if m else None
        self._shed = m.counter(
            "serving_requests_shed_total",
            "pending requests shed by degraded/drain admission control"
        ) if m else None

    # -- producer side --------------------------------------------------

    def submit(self, req: ViewRequest) -> ViewRequest:
        # Admission decisions happen under the lock; the rejection
        # *callbacks* run after it is released — an exc_factory that
        # re-enters the scheduler (depth(), another submit) must not
        # find this thread still holding _lock (LC306).
        reject: Optional[Callable[[], BaseException]] = None
        with self._lock:
            if self._closed:
                raise RuntimeError("scheduler is closed")
            if self._frozen is not None:
                if self._rejects:
                    self._rejects.inc()
                reject = self._frozen
            elif (self._soft_limit is not None
                    and self._depth_locked() >= self._soft_limit):
                if self._rejects:
                    self._rejects.inc()
                reject = self._soft_exc if self._soft_exc is not None \
                    else lambda: EngineOverloaded(
                        "replica degraded: queue soft limit reached")
            elif self._depth_locked() >= self.max_queue:
                if self._rejects:
                    self._rejects.inc()
                raise QueueFullError(
                    f"queue full ({self.max_queue} pending): retry later")
            else:
                now = time.monotonic()
                req.submit_time = now
                timeout = (self.default_timeout_s if req.timeout_s is None
                           else req.timeout_s)
                req.deadline = now + timeout
                self._pending.setdefault(req.bucket, deque()).append(req)
                self._update_depth()
                self._nonempty.notify_all()
        if reject is not None:
            raise reject()
        return req

    # -- consumer (engine) side -----------------------------------------

    def acquire(self, bucket: Optional[Bucket], max_n: int,
                block: bool = True,
                poll_s: float = 0.2) -> List[ViewRequest]:
        """Take up to ``max_n`` runnable requests.

        ``bucket`` given (engine already has active work of that shape):
        non-blocking grab of co-batchable requests — continuous batching
        admits them at the next view boundary.

        ``bucket`` None (engine idle): block until any request is pending
        (up to ``poll_s``, so the engine can re-check shutdown), pick the
        bucket of the *oldest* pending request, then hold until that
        request has aged ``max_wait_s`` (the microbatch flush deadline)
        or ``max_n`` co-batchable requests are available.
        """
        with self._lock:
            self._sweep_locked()
            if bucket is not None:
                got = self._take_locked(bucket, max_n)
                self._update_depth()
                return got
            if not block:
                b = self._oldest_bucket_locked()
                got = self._take_locked(b, max_n) if b else []
                self._update_depth()
                return got

            deadline = time.monotonic() + poll_s
            while not self._closed:
                self._sweep_locked()
                b = self._oldest_bucket_locked()
                if b is not None:
                    head = self._pending[b][0]
                    flush_at = head.submit_time + self.max_wait_s
                    while (len(self._pending.get(b) or ()) < max_n
                           and time.monotonic() < flush_at
                           and not self._closed):
                        self._nonempty.wait(
                            max(0.0, flush_at - time.monotonic()))
                        self._sweep_locked()
                        # The head may have expired during the wait; fall
                        # back to whatever is oldest now.
                        nb = self._oldest_bucket_locked()
                        if nb is None:
                            break
                        if nb != b:
                            b = nb
                            flush_at = (self._pending[b][0].submit_time
                                        + self.max_wait_s)
                    got = self._take_locked(b, max_n)
                    if got:
                        self._update_depth()
                        return got
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._nonempty.wait(remaining)
            self._update_depth()
            return []

    def depth(self) -> int:
        with self._lock:
            return self._depth_locked()

    # -- fault-tolerance admission control (engine side) -----------------

    def freeze(self, exc_factory: Callable[[], BaseException]) -> None:
        """Reject all new submissions with ``exc_factory()`` (drain mode,
        dead engine).  Pending/in-flight work keeps running."""
        with self._lock:
            self._frozen = exc_factory

    def unfreeze(self) -> None:
        with self._lock:
            self._frozen = None

    def set_soft_limit(self, limit: int,
                       exc_factory: Optional[Callable[[], BaseException]]
                       = None) -> None:
        """Degraded-mode admission: reject submissions once the queue
        holds ``limit`` requests (below ``max_queue``)."""
        with self._lock:
            self._soft_limit = max(1, int(limit))
            self._soft_exc = exc_factory

    def clear_soft_limit(self) -> None:
        with self._lock:
            self._soft_limit = None
            self._soft_exc = None

    def shed(self, exc_factory: Callable[[ViewRequest], BaseException],
             keep_oldest: bool = True) -> int:
        """Reject pending requests to cut load on a degraded replica.

        Priority is age: the bucket holding the *oldest* pending request
        (the next one the engine would serve) is kept; every other
        bucket's requests are resolved with ``exc_factory(req)`` — a
        typed retryable error, so clients know to go elsewhere.  Returns
        the number shed.
        """
        victims: List[ViewRequest] = []
        with self._lock:
            keep = self._oldest_bucket_locked() if keep_oldest else None
            for b in list(self._pending):
                if b == keep:
                    continue
                victims.extend(self._pending.pop(b))
            self._update_depth()
        # Resolve outside the lock: exc_factory is caller code (LC306),
        # and _reject takes each request's own lock — no reason to hold
        # the scheduler lock across either.
        for req in victims:
            req._reject(exc_factory(req))
            if self._shed:
                self._shed.inc()
        return len(victims)

    def close(self, reject_pending: bool = True) -> None:
        """Stop accepting work; optionally reject everything queued."""
        with self._lock:
            self._closed = True
            if reject_pending:
                for q in self._pending.values():
                    for req in q:
                        req._reject(EngineStopped(
                            f"{req.id}: server shutting down"))
                self._pending.clear()
            self._update_depth()
            self._nonempty.notify_all()

    # -- internals (lock held) ------------------------------------------

    def _depth_locked(self) -> int:  # guarded-by: self._lock
        return sum(len(q) for q in self._pending.values())

    def _update_depth(self) -> None:  # guarded-by: self._lock
        if self._depth_gauge:
            self._depth_gauge.set(self._depth_locked())

    def _sweep_locked(self) -> None:  # guarded-by: self._lock
        """Resolve expired / drop cancelled requests in place."""
        now = time.monotonic()
        for b in list(self._pending):
            q = self._pending[b]
            kept: Deque[ViewRequest] = deque()
            for req in q:
                if req.cancelled:
                    req._reject(RequestCancelled(f"{req.id}: cancelled"))
                elif req.expired(now):
                    if self._timeouts:
                        self._timeouts.inc()
                    req._reject(RequestTimeout(
                        f"{req.id}: deadline exceeded after "
                        f"{now - req.submit_time:.2f}s in queue"))
                else:
                    kept.append(req)
            if kept:
                self._pending[b] = kept
            else:
                del self._pending[b]

    def _oldest_bucket_locked(self) -> Optional[Bucket]:  # guarded-by: self._lock
        best, best_t = None, None
        for b, q in self._pending.items():
            if q and (best_t is None or q[0].submit_time < best_t):
                best, best_t = b, q[0].submit_time
        return best

    def _take_locked(self, bucket: Optional[Bucket],  # guarded-by: self._lock
                     max_n: int) -> List[ViewRequest]:
        if bucket is None or bucket not in self._pending or max_n <= 0:
            return []
        q = self._pending[bucket]
        got = []
        while q and len(got) < max_n:
            got.append(q.popleft())
        if not q:
            del self._pending[bucket]
        return got
