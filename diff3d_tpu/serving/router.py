"""Fleet router: one front door over N in-process engine replicas.

3DiM's sampler is autoregressive — view N of an object conditions on
the views already committed to that object's device-resident record
(DESIGN.md §6b), so a session is pinned to the hardware holding its
state.  The router therefore moves *requests to state*, never state to
requests (DESIGN.md §14):

* **Session affinity** — a request carrying ``session_id`` pins to an
  owning replica on its first view (rendezvous hash over the replicas
  eligible for its schedule — stable under fleet churn: adding or
  losing an unrelated replica never remaps an existing session) and
  every later view routes to the recorded owner.  Records never
  migrate.  Sessionless requests go to the least-loaded healthy
  replica and may fail over.
* **Admission control & backpressure** — per-replica queue depth and
  health (``ok|degraded|draining|dead``) feed typed rejections
  composing the RetryableError taxonomy:
  :class:`~diff3d_tpu.serving.scheduler.FleetOverloaded` (capacity,
  retry same request), :class:`~diff3d_tpu.serving.scheduler.ReplicaDraining`
  (owner mid-rollout, retry same session) and
  :class:`~diff3d_tpu.serving.scheduler.SessionLost` (owner dead,
  record gone — restart the session), each carrying ``retry_after_s``.
* **Blue/green rollout** — :meth:`Router.rollout` drains one replica
  at a time, hot-swaps params through the existing
  ``serving/cache.py`` registry path, re-admits, repeats.  In-flight
  requests finish on the old params before their replica swaps; a
  drain that times out resumes WITHOUT swapping (reported, never
  dropped).
* **Schedule-aware placement** — replicas declare supported
  ``(sampler_kind, steps)`` schedules (the PR 4 registry); the router
  places each request on a replica that compiled its schedule or
  rejects with :class:`~diff3d_tpu.serving.scheduler.UnsupportedSchedule`
  carrying the fleet-wide supported union.

The router holds no device state and compiles nothing: it composes
already-compiled engines, so shardcheck/memcheck manifests live with
the programs (sampling/serving), not here.  Its lock covers only the
session table and rollout flag — every replica call (submit, drain,
health probes) happens with the lock released, so a slow device step
can never serialize routing (see ``# guarded-by:`` annotations;
lockcheck static rules + the runtime lock-order witness run over this
module in tier 1).
"""

from __future__ import annotations

import hashlib
import logging
import re
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from diff3d_tpu.config import Config
from diff3d_tpu.serving.fleet import HEALTH_DEAD, Replica, build_fleet
from diff3d_tpu.serving.engine import (HEALTH_DEGRADED, HEALTH_DRAINING,
                                       HEALTH_OK)
from diff3d_tpu.serving.metrics import MetricsRegistry
from diff3d_tpu.serving.scheduler import (EngineDraining, EngineOverloaded,
                                          FleetOverloaded, QueueFullError,
                                          ReplicaDraining, ReplicaOverBudget,
                                          SessionLost, UnsupportedSchedule,
                                          ViewRequest)
from diff3d_tpu.serving.server import (build_cascade_request, build_request,
                                       build_trajectory_request,
                                       make_http_server, remember_request,
                                       result_payload)

log = logging.getLogger(__name__)

_ROUTABLE = (HEALTH_OK, HEALTH_DEGRADED)


def _metric_suffix(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_]", "_", name)


def _sched_str(kind: Optional[str], steps: Optional[int]) -> str:
    return f"{'default' if kind is None else kind}:" \
           f"{'default' if steps is None else steps}"


class Router:
    """Routing core: session table + placement + rollout state machine.

    Thread contract: ``submit`` runs on many HTTP handler threads
    concurrently; ``rollout`` on an operator thread; replica health
    changes on engine/watchdog threads.  ``self._lock`` guards only the
    session table, the replica map and the rollout flag — never held
    across a replica call.
    """

    def __init__(self, replicas: List[Replica],
                 metrics: Optional[MetricsRegistry] = None,
                 retry_after_s: float = 5.0):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.metrics = metrics or MetricsRegistry()
        self.retry_after_s = float(retry_after_s)
        self._lock = threading.Lock()
        self._replicas: "OrderedDict[str, Replica]" = (
            OrderedDict())  # guarded-by: self._lock
        for rep in replicas:
            if rep.name in self._replicas:
                raise ValueError(f"duplicate replica name {rep.name!r}")
            self._replicas[rep.name] = rep
        # Affinity table: session_id -> owning replica name.  Entries
        # are removed only when the owner dies (SessionLost tells the
        # client) or the session's replica is removed from the fleet.
        self._sessions: Dict[str, str] = {}  # guarded-by: self._lock
        self._rollout_active = False  # guarded-by: self._lock

        m = self.metrics
        self._requests_ctr = m.counter(
            "router_requests_total", "requests entering the router")
        self._rejected_ctr = m.counter(
            "router_rejected_total",
            "requests rejected by the router (typed retryable)")
        self._failover_ctr = m.counter(
            "router_failover_total",
            "sessionless/new-session requests placed away from their "
            "first-preference replica (attempt failed or a replica is "
            "dead)")
        self._sessions_lost_ctr = m.counter(
            "router_sessions_lost_total",
            "sticky sessions orphaned by a dead replica")
        self._rollouts_ctr = m.counter(
            "router_rollouts_total", "blue/green rollouts started")
        self._sessions_g = m.gauge(
            "router_sessions_active", "sessions in the affinity table")
        # Cross-process fleet supervision (serving/transport.py): these
        # exist (at 0) even on an all-in-process fleet, so dashboards
        # can alert on them before the first remote replica joins.
        self._remote_connected_g = m.gauge(
            "fleet_remote_connected",
            "remote replicas with a live transport connection")
        self._hb_timeouts_ctr = m.counter(
            "fleet_heartbeat_timeouts_total",
            "remote replicas marked dead by heartbeat timeout")
        self._admission_rejects_ctr = m.counter(
            'fleet_admission_rejects_total{reason="hbm"}',
            "requests rejected by worker HBM-budgeted admission")
        # Per-replica last-seen counter values for delta folding (worker
        # counters are cumulative; ours must only ever inc).
        self._remote_seen_lock = threading.Lock()
        self._remote_seen: Dict[str, Dict[str, int]] = (
            {})  # guarded-by: self._remote_seen_lock

    # -- fleet membership -------------------------------------------------

    def replica_list(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    def replica(self, name: str) -> Optional[Replica]:
        with self._lock:
            return self._replicas.get(name)

    def add_replica(self, replica: Replica) -> None:
        """Fleet churn: admit a new replica.  Existing sessions keep
        their owners (the affinity table, not the hash, is the source
        of truth); only new sessions can land on the newcomer."""
        with self._lock:
            if replica.name in self._replicas:
                raise ValueError(
                    f"replica {replica.name!r} already in the fleet")
            self._replicas[replica.name] = replica

    def remove_replica(self, name: str) -> Optional[Replica]:
        """Fleet churn: forget a replica (caller owns stopping it).
        Its sticky sessions stay in the table and surface
        :class:`SessionLost` on their next request — silent record loss
        is never an option."""
        with self._lock:
            return self._replicas.pop(name, None)

    # -- placement --------------------------------------------------------

    @staticmethod
    def rendezvous_order(session_id: str,
                         replicas: List[Replica]) -> List[Replica]:
        """Highest-random-weight ranking of ``replicas`` for a session:
        each (session, replica) pair hashes independently, so removing
        one replica only remaps the sessions it owned — every other
        session's argmax is untouched.  That minimal-disruption
        property is exactly the affinity-under-churn contract."""
        def weight(rep: Replica) -> str:
            return hashlib.sha256(
                f"{session_id}|{rep.name}".encode()).hexdigest()
        return sorted(replicas, key=weight, reverse=True)

    def _routable(self, kind: Optional[str],
                  steps: Optional[int]) -> List[Replica]:
        return [r for r in self.replica_list()
                if r.health in _ROUTABLE and r.supports(kind, steps)]

    def _reject(self, exc: BaseException) -> BaseException:
        self._rejected_ctr.inc()
        return exc

    @staticmethod
    def _rep_submit(rep: Replica, req: ViewRequest) -> ViewRequest:
        """One dispatch point for both request shapes: a cascade parent
        goes through the replica's cascade surface (which derives and
        chains the phase children), everything else through the plain
        submit path."""
        if getattr(req, "is_cascade", False):
            return rep.submit_cascade(req)
        return rep.submit(req)

    # -- request path -----------------------------------------------------

    def submit(self, req: ViewRequest) -> ViewRequest:
        """Route + submit one request.  Raises typed retryable errors
        (FleetOverloaded / ReplicaDraining / SessionLost /
        UnsupportedSchedule) instead of queueing anywhere the record
        contract would not be honoured."""
        self._requests_ctr.inc()
        sid = req.session_id
        if sid is not None:
            with self._lock:
                owner = self._sessions.get(sid)
            if owner is not None:
                return self._submit_sticky(req, sid, owner)
        return self._submit_placed(req, sid)

    def _submit_sticky(self, req: ViewRequest, sid: str,
                       owner: str) -> ViewRequest:
        rep = self.replica(owner)
        if rep is None or rep.health == HEALTH_DEAD:
            with self._lock:
                if self._sessions.get(sid) == owner:
                    del self._sessions[sid]
                    self._sessions_g.set(len(self._sessions))
            self._sessions_lost_ctr.inc()
            raise self._reject(SessionLost(
                f"{req.id}: session {sid}: owning replica {owner} is "
                "gone and its device-resident record is lost — restart "
                "the session from its committed views",
                replica=owner, retry_after_s=self.retry_after_s))
        if rep.health == HEALTH_DRAINING:
            raise self._reject(ReplicaDraining(
                f"{req.id}: session {sid}: owning replica {owner} is "
                "draining for rollout; the record stays there — retry "
                f"the same session after {self.retry_after_s:g}s",
                replica=owner, retry_after_s=self.retry_after_s))
        try:
            return self._rep_submit(rep, req)
        except (QueueFullError, EngineOverloaded) as e:
            # Sticky requests cannot fail over — the record is here.
            raise self._reject(FleetOverloaded(
                f"{req.id}: session {sid}: owning replica {owner} is at "
                f"capacity; retry after {self.retry_after_s:g}s",
                retry_after_s=self.retry_after_s)) from e
        except EngineDraining as e:
            # Health flipped to draining between the check and the
            # submit; same contract as the pre-check.
            raise self._reject(ReplicaDraining(
                f"{req.id}: session {sid}: owning replica {owner} "
                "started draining; retry the same session",
                replica=owner, retry_after_s=self.retry_after_s)) from e
        except ReplicaOverBudget:
            # The owner's HBM admission gate fired.  Sticky requests
            # cannot fail over (the record is here), but unlike a dead
            # owner the record is intact — the typed rejection carries
            # the budget arithmetic and a Retry-After.
            self._rejected_ctr.inc()
            raise
        except UnsupportedSchedule:
            self._rejected_ctr.inc()
            raise
        except RuntimeError as e:
            if rep.health == HEALTH_DEAD:
                # Killed between the health check and the submit.
                with self._lock:
                    if self._sessions.get(sid) == owner:
                        del self._sessions[sid]
                        self._sessions_g.set(len(self._sessions))
                self._sessions_lost_ctr.inc()
                raise self._reject(SessionLost(
                    f"{req.id}: session {sid}: owning replica {owner} "
                    "died mid-submit; its record is lost — restart the "
                    "session", replica=owner,
                    retry_after_s=self.retry_after_s)) from e
            raise

    def _submit_placed(self, req: ViewRequest,
                       sid: Optional[str]) -> ViewRequest:
        kind, steps = req.sampler_kind, req.steps
        cands = self._routable(kind, steps)
        if getattr(req, "is_cascade", False):
            spec = req.plan.spec()
            cands = [r for r in cands if r.supports_cascade(spec)]
            if not cands:
                raise self._reject(UnsupportedSchedule(
                    f"{req.id}: no live replica serves cascade plan "
                    f"{spec} (boot replicas with --cascade)",
                    retry_after_s=self.retry_after_s))
        if not cands:
            raise self._reject(self._no_candidates_exc(req, kind, steps))
        dead = [r.name for r in self.replica_list()
                if r.health == HEALTH_DEAD]
        if sid is not None:
            return self._place_session(req, sid, cands, bool(dead))
        # Sessionless: least-loaded first, fail over down the order.
        order = sorted(cands, key=lambda r: (r.depth(), r.name))
        last: Optional[BaseException] = None
        for i, rep in enumerate(order):
            try:
                got = self._rep_submit(rep, req)
            except (QueueFullError, EngineOverloaded, EngineDraining,
                    ReplicaOverBudget) as e:
                # ReplicaOverBudget: this replica's slice is out of HBM
                # headroom, but another may admit — keep failing over.
                last = e
                continue
            if i > 0 or dead:
                self._failover_ctr.inc()
            return got
        raise self._reject(FleetOverloaded(
            f"{req.id}: all {len(order)} eligible replicas rejected the "
            f"request ({len(dead)} dead); retry after "
            f"{self.retry_after_s:g}s",
            retry_after_s=self.retry_after_s)) from last

    def _place_session(self, req: ViewRequest, sid: str,
                       cands: List[Replica],
                       any_dead: bool) -> ViewRequest:
        """First view of a session: claim the rendezvous owner in the
        affinity table BEFORE submitting, so a concurrent same-session
        request sees the claim and goes sticky instead of racing to a
        second replica."""
        chosen = self.rendezvous_order(sid, cands)[0]
        with self._lock:
            owner = self._sessions.setdefault(sid, chosen.name)
            self._sessions_g.set(len(self._sessions))
        if owner != chosen.name:
            # Lost the first-view race; the established claim wins.
            return self._submit_sticky(req, sid, owner)
        try:
            got = self._rep_submit(chosen, req)
        except ReplicaOverBudget:
            # No record exists yet; release the claim exactly like the
            # capacity path, but re-raise the typed budget rejection
            # itself — the client (or an upstream balancer) should see
            # the HBM arithmetic, not a generic FleetOverloaded.
            with self._lock:
                release = (self._sessions.get(sid) == chosen.name
                           and chosen.session_count(sid) == 0)
                if release:
                    del self._sessions[sid]
                    self._sessions_g.set(len(self._sessions))
            self._rejected_ctr.inc()
            raise
        except (QueueFullError, EngineOverloaded, EngineDraining) as e:
            # No record exists yet; release the claim (unless a racing
            # request already landed one) and report capacity — a new
            # session does NOT fail over, so its retry re-hashes to the
            # same owner once capacity frees (stable placement beats
            # one-shot greed here).
            with self._lock:
                release = (self._sessions.get(sid) == chosen.name
                           and chosen.session_count(sid) == 0)
                if release:
                    del self._sessions[sid]
                    self._sessions_g.set(len(self._sessions))
            raise self._reject(FleetOverloaded(
                f"{req.id}: session {sid}: rendezvous owner "
                f"{chosen.name} cannot admit ({e}); retry after "
                f"{self.retry_after_s:g}s",
                retry_after_s=self.retry_after_s)) from e
        if any_dead:
            self._failover_ctr.inc()
        return got

    def _no_candidates_exc(self, req: ViewRequest, kind: Optional[str],
                           steps: Optional[int]) -> BaseException:
        reps = self.replica_list()
        supporters = [r for r in reps if r.health != HEALTH_DEAD
                      and r.supports(kind, steps)]
        if not supporters:
            supported = sorted({s for r in reps
                                if r.health != HEALTH_DEAD
                                for s in r.supported_schedules()})
            return UnsupportedSchedule(
                f"{req.id}: no live replica serves schedule "
                f"{_sched_str(kind, steps)} (fleet supports: "
                f"{', '.join(supported) or 'nothing — fleet dead'})",
                supported=supported, retry_after_s=self.retry_after_s)
        if all(r.health == HEALTH_DRAINING for r in supporters):
            return ReplicaDraining(
                f"{req.id}: every replica serving "
                f"{_sched_str(kind, steps)} is draining for rollout; "
                f"retry after {self.retry_after_s:g}s",
                retry_after_s=self.retry_after_s)
        return FleetOverloaded(
            f"{req.id}: no healthy replica for schedule "
            f"{_sched_str(kind, steps)}; retry after "
            f"{self.retry_after_s:g}s",
            retry_after_s=self.retry_after_s)

    # -- blue/green rollout ----------------------------------------------

    def rollout(self, params, version: Optional[str] = None,
                drain_timeout_s: float = 60.0) -> dict:
        """Blue/green params rollout: for each live replica in turn,
        drain (in-flight work finishes on the old params) -> hot-swap
        through its ParamsRegistry -> resume.  At every instant N-1
        replicas serve, so the fleet never goes dark; a drain timeout
        resumes the replica un-swapped and marks the rollout failed
        rather than dropping its in-flight requests.  Single-flight:
        concurrent rollouts are rejected."""
        with self._lock:
            if self._rollout_active:
                raise RuntimeError("rollout already in progress")
            self._rollout_active = True
        self._rollouts_ctr.inc()
        steps_log: List[dict] = []
        ok = True
        try:
            for rep in self.replica_list():
                if rep.health == HEALTH_DEAD:
                    steps_log.append({"replica": rep.name,
                                      "status": "skipped-dead"})
                    continue
                log.info("rollout: draining replica %s", rep.name)
                if not rep.drain(timeout=drain_timeout_s):
                    rep.resume()
                    steps_log.append({"replica": rep.name,
                                      "status": "drain-timeout"})
                    ok = False
                    continue
                new_version = rep.swap_params(params, version)
                rep.resume()
                log.info("rollout: replica %s -> params %s", rep.name,
                         new_version)
                steps_log.append({"replica": rep.name,
                                  "status": "swapped",
                                  "params_version": new_version})
        finally:
            with self._lock:
                self._rollout_active = False
        return {"ok": ok, "steps": steps_log}

    # -- observability ----------------------------------------------------

    def refresh_gauges(self) -> None:
        """Update the per-replica depth gauges (lazy get-or-create, so
        churned-in replicas appear on their first refresh), and fold
        remote replicas' transport counters into the fleet metrics."""
        connected = 0
        deltas: List[tuple] = []
        for rep in self.replica_list():
            self.metrics.gauge(
                f"router_replica_depth_{_metric_suffix(rep.name)}",
                "queued + in-flight requests on this replica").set(
                    rep.depth())
            stats_fn = getattr(rep, "transport_stats", None)
            if stats_fn is None:
                continue        # in-process replica: no transport
            stats = stats_fn()
            if stats.get("connected"):
                connected += 1
            deltas.append((rep.name, stats))
        # Delta-fold cumulative worker counters into our inc-only
        # counters: compute deltas under the last-seen lock, inc after
        # release (Counter has its own lock; never nest them).
        pending: List[tuple] = []
        with self._remote_seen_lock:
            for name, stats in deltas:
                seen = self._remote_seen.setdefault(name, {})
                for key, ctr in (
                        ("heartbeat_timeouts", self._hb_timeouts_ctr),
                        ("admission_rejects_hbm",
                         self._admission_rejects_ctr)):
                    now = int(stats.get(key) or 0)
                    delta = now - seen.get(key, 0)
                    if delta > 0:
                        pending.append((ctr, delta))
                    seen[key] = max(now, seen.get(key, 0))
        for ctr, delta in pending:
            ctr.inc(delta)
        self._remote_connected_g.set(connected)

    def fleet_snapshot(self) -> dict:
        self.refresh_gauges()
        with self._lock:
            sessions = dict(self._sessions)
            rollout_active = self._rollout_active
        per_owner: Dict[str, int] = {}
        for owner in sessions.values():
            per_owner[owner] = per_owner.get(owner, 0) + 1
        return {
            "replicas": {r.name: r.snapshot()
                         for r in self.replica_list()},
            "sessions": {
                "active": len(sessions),
                "per_replica": per_owner,
            },
            "rollout_active": rollout_active,
        }


class FleetService:
    """HTTP-facing front door over a :class:`Router` — duck-types the
    single-replica :class:`~diff3d_tpu.serving.server.ServingService`
    surface (submit / get_request / result_payload / health /
    metrics_snapshot), so :func:`make_http_server` serves either, and
    adds ``GET /fleet`` plus the router counters to ``GET /metrics``.
    """

    def __init__(self, replicas: List[Replica], cfg: Config):
        cfg.serving.validate()
        self.cfg = cfg
        self.replicas = list(replicas)
        self._metrics = MetricsRegistry()
        self.router = Router(self.replicas, metrics=self._metrics,
                             retry_after_s=cfg.serving.retry_after_s)
        self._requests_lock = threading.Lock()
        self._requests: "OrderedDict[str, ViewRequest]" = (
            OrderedDict())  # guarded-by: self._requests_lock
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None

    @classmethod
    def build(cls, sampler, cfg: Config, n: Optional[int] = None,
              extra_samplers: Optional[dict] = None,
              per_replica_extra: Optional[Dict[int, dict]] = None,
              params_version: str = "v0", cascade=None) -> "FleetService":
        """One-call fleet: N replicas sharing ``sampler``'s jit cache
        (see :func:`~diff3d_tpu.serving.fleet.build_fleet`)."""
        return cls(build_fleet(sampler, cfg, n,
                               extra_samplers=extra_samplers,
                               per_replica_extra=per_replica_extra,
                               params_version=params_version,
                               cascade=cascade), cfg)

    # -- lifecycle -------------------------------------------------------

    def start(self, serve_http: bool = True) -> "FleetService":
        for rep in self.replicas:
            rep.start()
        if serve_http:
            self._httpd = make_http_server(self, self.cfg.serving.host,
                                           self.cfg.serving.port)
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="diff3d-fleet-http", daemon=True)
            self._http_thread.start()
        return self

    def stop(self, drain_s: float = 0.0) -> None:
        if drain_s > 0:
            for rep in self.replicas:
                if rep.health not in (HEALTH_DEAD,):
                    rep.drain(timeout=drain_s)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        for rep in self.replicas:
            rep.stop()

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    # -- request surface -------------------------------------------------

    def submit(self, payload: dict) -> ViewRequest:
        """Build, route and schedule a request from a JSON-shaped
        payload (``session_id`` keys the affinity contract)."""
        req = build_request(payload, self.cfg)
        self.router.submit(req)
        remember_request(self._requests, self._requests_lock, req,  # lockcheck: disable=LC302(reference passed; remember_request locks)
                         4 * self.cfg.serving.max_queue)
        return req

    def submit_trajectory(self, payload: dict) -> ViewRequest:
        """Build + route a camera-path rendering request.  A trajectory
        carrying ``session_id`` is the canonical sticky workload: every
        frame commits to the owning replica's device-resident record,
        and the zero-migration contract keeps it there."""
        req = build_trajectory_request(payload, self.cfg)
        self.router.submit(req)
        remember_request(self._requests, self._requests_lock, req,  # lockcheck: disable=LC302(reference passed; remember_request locks)
                         4 * self.cfg.serving.max_queue)
        return req

    def submit_cascade(self, payload: dict) -> ViewRequest:
        """Build + route a progressive-preview cascade.  The plan comes
        from the fleet (the first cascade-capable replica's — replicas
        built through :meth:`build` share one), never the payload; the
        router then places the parent on a cascade-capable replica,
        honouring session affinity exactly like a plain request."""
        plan = None
        for rep in self.replicas:
            casc = getattr(rep.engine, "cascade", None)
            if casc is not None:
                plan = casc.plan
                break
        if plan is None:
            raise UnsupportedSchedule(
                "no replica in this fleet serves a cascade plan "
                "(boot with --cascade)")
        req = build_cascade_request(payload, self.cfg, plan)
        self.router.submit(req)
        remember_request(self._requests, self._requests_lock, req,  # lockcheck: disable=LC302(reference passed; remember_request locks)
                         4 * self.cfg.serving.max_queue)
        return req

    def get_request(self, request_id: str) -> Optional[ViewRequest]:
        with self._requests_lock:
            return self._requests.get(request_id)

    def result_payload(self, req: ViewRequest) -> dict:
        return result_payload(req)

    def rollout(self, params, version: Optional[str] = None,
                drain_timeout_s: float = 60.0) -> dict:
        return self.router.rollout(params, version=version,
                                   drain_timeout_s=drain_timeout_s)

    # -- observability ----------------------------------------------------

    @property
    def metrics(self) -> MetricsRegistry:
        # Refresh per-replica depth gauges on the way out so the text
        # exposition (`GET /metrics`) is as current as the JSON path.
        self.router.refresh_gauges()
        return self._metrics

    def health(self) -> dict:
        reps = self.router.replica_list()
        healths = {r.name: r.health for r in reps}
        if any(h == HEALTH_OK for h in healths.values()):
            status = "ok"
        elif any(h in (HEALTH_DEGRADED, HEALTH_DRAINING)
                 for h in healths.values()):
            status = "degraded"
        else:
            status = "dead"
        return {
            "status": status,
            "fleet_size": len(reps),
            "replicas": healths,
            "queue_depth": sum(r.depth() for r in reps),
            "params_versions": {r.name: r.params_version for r in reps},
            "supported_schedules": sorted(
                {s for r in reps if r.health != HEALTH_DEAD
                 for s in r.supported_schedules()}),
            "cascade": sorted(
                {r.engine.cascade.plan.spec() for r in reps
                 if r.health != HEALTH_DEAD
                 and getattr(r.engine, "cascade", None) is not None}),
        }

    def metrics_snapshot(self, include_memory: bool = False) -> dict:
        self.router.refresh_gauges()
        return self._metrics.snapshot(
            extra={"fleet": self.fleet_snapshot()})

    def fleet_snapshot(self) -> dict:
        return self.router.fleet_snapshot()
