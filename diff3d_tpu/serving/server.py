"""Stdlib HTTP frontend for the inference service.

``ThreadingHTTPServer`` + ``BaseHTTPRequestHandler`` only — the service
has to run in the bare jax_graft container, so no web framework.  Handler
threads do pure host work (JSON <-> numpy, queue submit, event wait); the
single engine thread owns every device call, so ``GET /healthz`` and
``GET /metrics`` stay responsive while a multi-minute job is on the chip.

Surface:
  * ``POST /synthesize`` — submit a job.  Body: ``{"views": {"imgs",
    "R", "T", "K"}, "seed": 0, "n_views"?: int, "timeout_s"?: float,
    "block"?: bool, "sampler_kind"?: "ancestral"|"ddim",
    "steps"?: int}``.  ``block=true`` (default) waits for the result;
    ``block=false`` returns ``202 {"id"}`` for later polling.  A
    ``(sampler_kind, steps)`` pair the replica has no compiled bucket
    for is rejected ``503`` with the supported schedules.
  * ``POST /trajectory`` — render a camera path as one request.  Body:
    either ``{"views": {...}}`` with explicit poses (view 0 is the
    conditioning view) or ``{"cond": {"img", "R", "T", "K"}, "path":
    {"kind": "orbit"|"spiral"|"keyframes", "frames": N, ...}}`` (the
    ``diff3d_tpu/trajectory`` spec grammar), plus the /synthesize
    options and ``"stream"?: bool``.  Three response modes:
    ``stream=true`` streams chunked NDJSON — a header line, then one
    line per frame *as it commits to the record*, then a terminal
    status line; ``block=false`` returns ``202 {"id", "n_frames"}``
    for incremental polling; ``block=true`` (default) waits and
    returns all frames at once.
  * ``POST /cascade`` — progressive-preview synthesis (DESIGN.md §20):
    the same ``{"views": ...}`` payload at the served (refine)
    resolution; the replica's cascade plan decides both phase schedules
    (``sampler_kind``/``steps`` are rejected).  Response modes mirror
    /trajectory, but the streamed/polled unit is a *phase-tagged
    event*: draft frames arrive first (preview), each refined frame
    then replaces its draft at the same ``frame`` index.  ``503`` when
    the replica serves no cascade plan.
  * ``GET /result/<id>`` — poll a submitted job.  For trajectory
    requests ``?from=K`` returns frames ``K..`` committed so far plus
    progress (``200`` even while running) — the incremental-poll
    streaming surface.  For cascade requests ``?from=K`` walks the
    phase-tagged event buffer the same way (``next`` continues the
    cursor without gaps).
  * ``GET /healthz`` — liveness + engine/queue state (incl. supported
    schedules).
  * ``GET /metrics`` — text exposition; ``/metrics?format=json`` for the
    structured snapshot (per-trajectory progress under
    ``engine.trajectories``).
  * ``GET /stats`` — the structured snapshot (alias of
    ``/metrics?format=json``): per-bucket program-cache entries carry
    their step count and sampler kind.
  * ``GET /fleet`` — fleet topology + per-replica health/depth/sessions
    and trajectory progress (404 on a single-replica service; served
    when the front door is the router's
    :class:`~diff3d_tpu.serving.router.FleetService`).

Backpressure maps to status codes, never to silent queuing: a full queue
is ``429``, a request deadline is ``504``, a cancelled request ``409``,
malformed input ``400``.  A trajectory request hits the same bounded
queue as everything else — its typed rejection arrives before the
stream starts, as a plain JSON error response.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

# Only the (dependency-free) plan module at import time: cascade.request
# subclasses scheduler.ViewRequest, so importing it here would close an
# import cycle through the serving package __init__.
from diff3d_tpu.cascade.plan import CascadePlan
from diff3d_tpu.config import Config
from diff3d_tpu.runtime.retry import RetryableError
from diff3d_tpu.serving.cache import ParamsRegistry, ProgramCache, ResultCache
from diff3d_tpu.serving.engine import Engine
from diff3d_tpu.serving.metrics import MetricsRegistry
from diff3d_tpu.serving.scheduler import (QueueFullError, RequestCancelled,
                                          RequestTimeout, Scheduler,
                                          TrajectoryRequest,
                                          UnsupportedSchedule, ViewRequest)
from diff3d_tpu.trajectory import path_from_spec, trajectory_views

log = logging.getLogger(__name__)


def _error_status(exc: BaseException) -> int:
    if isinstance(exc, QueueFullError):
        return 429
    if isinstance(exc, RequestTimeout):
        return 504
    if isinstance(exc, RequestCancelled):
        return 409
    if isinstance(exc, RetryableError):
        # Typed retryable rejection (degraded/draining/step fault): the
        # replica, not the request, is the problem — 503 + Retry-After.
        return 503
    if isinstance(exc, (ValueError, KeyError, TypeError)):
        return 400
    return 500


def _retry_after(exc: BaseException) -> Optional[int]:
    after = getattr(exc, "retry_after_s", None)
    return max(1, int(round(after))) if after else None


def _request_kwargs(payload: dict, cfg: Config) -> dict:
    """The ViewRequest/TrajectoryRequest keyword options shared by both
    builders, with the ``n_views`` ceiling pre-checked."""
    n_views = payload.get("n_views")
    if n_views is not None:
        n_views = int(n_views)
        if n_views > cfg.serving.max_views:
            raise ValueError(
                f"n_views={n_views} exceeds the service ceiling "
                f"{cfg.serving.max_views}")
    steps = payload.get("steps")
    return dict(
        seed=int(payload.get("seed", 0)),
        n_views=n_views,
        timeout_s=payload.get("timeout_s"),
        sampler_kind=payload.get("sampler_kind"),
        steps=None if steps is None else int(steps),
        session_id=payload.get("session_id"))


def _check_against_model(req: ViewRequest, cfg: Config) -> ViewRequest:
    """Post-construction ceilings every front door enforces before any
    replica is chosen."""
    if req.n_views > cfg.serving.max_views:
        raise ValueError(
            f"request spans {req.n_views} views, service ceiling is "
            f"{cfg.serving.max_views} (pass n_views to truncate)")
    H, W = req.bucket.H, req.bucket.W
    if (H, W) != (cfg.model.H, cfg.model.W):
        raise ValueError(
            f"image size {H}x{W} does not match the served model "
            f"({cfg.model.H}x{cfg.model.W})")
    return req


def build_request(payload: dict, cfg: Config) -> ViewRequest:
    """Validate a JSON-shaped payload against the served model and build
    the :class:`ViewRequest`.  Shared by the single-replica
    :class:`ServingService` and the fleet router's front door — both
    enforce the same ceilings before any replica is chosen."""
    if "views" not in payload:
        raise ValueError("payload must carry a 'views' object with "
                         "imgs/R/T/K")
    req = ViewRequest(
        {k: np.asarray(v) for k, v in payload["views"].items()},
        **_request_kwargs(payload, cfg))
    return _check_against_model(req, cfg)


def build_trajectory_request(payload: dict,
                             cfg: Config) -> TrajectoryRequest:
    """Build a :class:`TrajectoryRequest` from a JSON-shaped payload.

    Two input shapes: ``{"views": {...}}`` with explicit poses (view 0
    conditions, views 1.. are the path), or ``{"cond": {"img", "R",
    "T", "K"}, "path": <spec>}`` where the spec is compiled through
    :func:`diff3d_tpu.trajectory.path_from_spec` — a path of N frames
    becomes an (N+1)-view request, so the frame budget is
    ``max_views - 1``.  Same ceilings as :func:`build_request`.
    """
    if "views" in payload:
        views = {k: np.asarray(v) for k, v in payload["views"].items()}
    else:
        cond, path = payload.get("cond"), payload.get("path")
        if cond is None or path is None:
            raise ValueError(
                "trajectory payload must carry either a 'views' object "
                "or 'cond' ({img, R, T, K}) + 'path' (spec)")
        missing = [k for k in ("img", "R", "T", "K") if k not in cond]
        if missing:
            raise ValueError(f"cond is missing {missing}")
        path_R, path_T = path_from_spec(path)
        views = trajectory_views(
            np.asarray(cond["img"], np.float32),
            np.asarray(cond["R"], np.float32),
            np.asarray(cond["T"], np.float32),
            np.asarray(cond["K"], np.float32), path_R, path_T)
    req = TrajectoryRequest(views, **_request_kwargs(payload, cfg))
    return _check_against_model(req, cfg)


def build_cascade_request(payload: dict, cfg: Config,
                          plan: CascadePlan) -> "ViewRequest":
    """Build a :class:`CascadeRequest` from a JSON-shaped payload.

    The payload is the plain /synthesize shape at the served (refine)
    resolution; the cascade *plan* owns both phase schedules, so a
    payload naming its own ``sampler_kind``/``steps`` is rejected —
    cascade programs are compiled at boot, never minted per request.
    """
    if "views" not in payload:
        raise ValueError("payload must carry a 'views' object with "
                         "imgs/R/T/K")
    from diff3d_tpu.cascade.request import CascadeRequest

    kw = _request_kwargs(payload, cfg)
    if kw.pop("sampler_kind") is not None or kw.pop("steps") is not None:
        raise ValueError(
            "cascade requests take their schedules from the replica's "
            "cascade plan — drop sampler_kind/steps from the payload")
    req = CascadeRequest(
        {k: np.asarray(v) for k, v in payload["views"].items()},
        plan, **kw)
    return _check_against_model(req, cfg)


def remember_request(requests: "OrderedDict[str, ViewRequest]",
                     lock: threading.Lock, req: ViewRequest,
                     cap: int) -> None:
    """Record an accepted request in a front door's id->request map,
    evicting the oldest *finished* entries past ``cap`` (shared by the
    single-replica service and the fleet front door)."""
    with lock:
        requests[req.id] = req
        while len(requests) > cap:
            oldest = next(iter(requests))
            if not requests[oldest].done():
                break
            del requests[oldest]


def result_payload(req: ViewRequest) -> dict:
    """The terminal JSON body of a finished request (raises the
    request's error if it failed).  Trajectory requests additionally
    report their frame count — ``views`` and the streamed frames are
    the same arrays in the same order."""
    out = req.result(timeout=0)
    body = {
        "id": req.id,
        "status": "done",
        "cached": req.cached,
        "n_views": req.n_views,
        "shape": list(out.shape),
        "views": out.tolist(),
    }
    if req.is_trajectory:
        body["n_frames"] = req.n_frames
        body["frames_committed"] = req.frames_done()
    return body


def trajectory_poll_payload(req: TrajectoryRequest, start: int) -> dict:
    """Incremental-poll body for ``GET /result/<id>?from=K``: frames
    ``K..`` committed so far, plus progress.  ``next`` is the ``from``
    value that continues the stream without gaps or repeats."""
    frames = req.frames_since(start)
    done = req.done()
    committed = req.frames_done()
    body = {
        "id": req.id,
        "status": "done" if done and req.error is None else (
            "failed" if done else "running"),
        "n_frames": req.n_frames,
        "frames_committed": committed,
        "from": start,
        "next": start + len(frames),
        "frames": [f.tolist() for f in frames],
    }
    if done and req.error is not None:
        body["error"] = str(req.error)
    return body


def _event_body(event: dict, seq: int) -> dict:
    """One phase-tagged frame event on the wire: ``frame`` is the
    0-based preview slot (view k -> frame k-1) a client renders draft
    events into and overwrites with the matching refine event."""
    return {
        "event": seq,
        "phase": event["phase"],
        "frame": event["view"] - 1,
        "view": event["frame"].tolist(),
    }


def cascade_poll_payload(req: "ViewRequest", start: int) -> dict:
    """Incremental-poll body for a cascade's ``GET /result/<id>?from=K``:
    phase-tagged events ``K..`` committed so far.  A finished cascade
    has ``2 * n_frames`` events — one draft and one refine per view —
    and ``next`` continues the cursor without gaps or repeats."""
    events = req.events_since(start)
    done = req.done()
    body = {
        "id": req.id,
        "status": "done" if done and req.error is None else (
            "failed" if done else "running"),
        "n_frames": req.n_frames,
        "n_events": req.n_events,
        "events_committed": req.events_done(),
        "from": start,
        "next": start + len(events),
        "events": [_event_body(e, start + i)
                   for i, e in enumerate(events)],
    }
    if done and req.error is not None:
        body["error"] = str(req.error)
    return body


class ServingService:
    """Wires scheduler + engine + caches + metrics around one Sampler.

    The HTTP layer is optional: tests and the serving bench drive
    :meth:`submit` in-process.
    """

    def __init__(self, sampler, cfg: Config, params_version: str = "v0",
                 extra_samplers: Optional[dict] = None, cascade=None):
        """``extra_samplers`` maps ``(sampler_kind, steps)`` to extra
        :class:`~diff3d_tpu.sampling.Sampler` instances (sharing the
        default sampler's params) — the additional schedules this
        replica serves beyond the default sampler's own.  ``cascade``
        is an optional :class:`~diff3d_tpu.cascade.CascadeSampler`
        enabling the progressive-preview surface (``POST /cascade``)."""
        cfg.serving.validate()
        self.cfg = cfg
        self.metrics = MetricsRegistry()
        self.scheduler = Scheduler(
            max_queue=cfg.serving.max_queue,
            max_wait_s=cfg.serving.max_wait_ms / 1e3,
            default_timeout_s=cfg.serving.default_timeout_s,
            metrics=self.metrics)
        self.registry = ParamsRegistry(sampler.params,
                                       version=params_version)
        samplers = {(getattr(sampler, "sampler_kind", None),
                     getattr(sampler, "steps", None)): sampler,
                    **(extra_samplers or {})}
        self.engine = Engine(
            sampler, self.scheduler, self.metrics, cfg.serving,
            params_registry=self.registry,
            result_cache=ResultCache(cfg.serving.result_cache_entries,
                                     self.metrics),
            program_cache=ProgramCache(
                samplers if len(samplers) > 1 else sampler, self.metrics),
            extra_samplers=extra_samplers, cascade=cascade)
        self._requests_lock = threading.Lock()
        self._requests: "OrderedDict[str, ViewRequest]" = OrderedDict()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None

    # -- lifecycle -------------------------------------------------------

    def start(self, serve_http: bool = True) -> "ServingService":
        self.engine.start()
        if serve_http:
            self._httpd = make_http_server(self, self.cfg.serving.host,
                                           self.cfg.serving.port)
            self._http_thread = threading.Thread(
                target=self._httpd.serve_forever,
                name="diff3d-serving-http", daemon=True)
            self._http_thread.start()
        return self

    def stop(self, drain_s: float = 0.0) -> None:
        """Shut the service down; ``drain_s > 0`` first drains the
        engine (no new admissions, in-flight work finishes) for up to
        that many seconds — the clean-rollout path."""
        if drain_s > 0 and self.engine.alive:
            self.engine.drain(timeout=drain_s)
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self.engine.stop()

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Stop admissions and wait for queued + in-flight work."""
        return self.engine.drain(timeout=timeout)

    @property
    def port(self) -> Optional[int]:
        """Bound port (useful with ``port=0`` for tests)."""
        return self._httpd.server_address[1] if self._httpd else None

    # -- request surface -------------------------------------------------

    def submit(self, payload: dict) -> ViewRequest:
        """Build + schedule a request from a JSON-shaped payload."""
        req = build_request(payload, self.cfg)
        self.engine.submit(req)
        remember_request(self._requests, self._requests_lock, req,
                         4 * self.cfg.serving.max_queue)
        return req

    def submit_trajectory(self, payload: dict) -> TrajectoryRequest:
        """Build + schedule a camera-path rendering request; frames
        stream through the request's commit buffer as the engine
        commits them (``POST /trajectory``)."""
        req = build_trajectory_request(payload, self.cfg)
        self.engine.submit(req)
        remember_request(self._requests, self._requests_lock, req,
                         4 * self.cfg.serving.max_queue)
        return req

    def submit_cascade(self, payload: dict) -> "ViewRequest":
        """Build + schedule a progressive-preview request against the
        replica's cascade plan (``POST /cascade``); phase-tagged frame
        events stream through the request's event buffer as each phase
        commits them."""
        if self.engine.cascade is None:
            raise UnsupportedSchedule(
                "this replica serves no cascade plan (boot with "
                "--cascade)",
                supported=self.engine.supported_schedules())
        req = build_cascade_request(payload, self.cfg,
                                    self.engine.cascade.plan)
        self.engine.submit_cascade(req)
        remember_request(self._requests, self._requests_lock, req,
                         4 * self.cfg.serving.max_queue)
        return req

    def get_request(self, request_id: str) -> Optional[ViewRequest]:
        with self._requests_lock:
            return self._requests.get(request_id)

    def result_payload(self, req: ViewRequest) -> dict:
        return result_payload(req)

    def health(self) -> dict:
        alive = self.engine.alive
        # Engine health states (ok|degraded|draining, DESIGN.md §7); a
        # dead engine thread reports degraded whatever the state says.
        status = self.engine.health if alive else "degraded"
        return {
            "status": status,
            "engine_alive": alive,
            "engine_health": self.engine.health,
            "engine_restarts": self.engine._restarts,
            "queue_depth": self.scheduler.depth(),
            "params_version": self.registry.version,
            "lane_multiple": self.engine.lane_multiple,
            "max_batch": self.engine.max_batch,
            "supported_schedules": self.engine.supported_schedules(),
            "cascade": (self.engine.cascade.plan.spec()
                        if self.engine.cascade is not None else None),
        }

    def metrics_snapshot(self, include_memory: bool = False) -> dict:
        return self.metrics.snapshot(
            extra=self.engine.snapshot_extra(include_memory=include_memory))


def make_http_server(service: ServingService, host: str,
                     port: int) -> ThreadingHTTPServer:
    """Build (without starting) the HTTP server bound to ``host:port``."""

    class Handler(BaseHTTPRequestHandler):
        server_version = "diff3d-serve/1.0"
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):   # route through logging, not
            log.debug("%s " + fmt, self.address_string(), *args)  # stderr

        def _send_json(self, status: int, obj: dict,
                       retry_after: Optional[int] = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(status)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            if retry_after is not None:
                self.send_header("Retry-After", str(retry_after))
            self.end_headers()
            self.wfile.write(body)

        def _send_text(self, status: int, text: str,
                       ctype: str = "text/plain; version=0.0.4") -> None:
            body = text.encode()
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            url = urlparse(self.path)
            if url.path == "/healthz":
                h = service.health()
                self._send_json(200 if h["status"] == "ok" else 503, h)
            elif url.path == "/metrics":
                if "format=json" in (url.query or ""):
                    self._send_json(200, service.metrics_snapshot())
                else:
                    self._send_text(200, service.metrics.exposition())
            elif url.path == "/stats":
                self._send_json(
                    200, service.metrics_snapshot(include_memory=True))
            elif url.path == "/fleet":
                # Served only by the fleet router's front door
                # (serving/router.py FleetService, duck-typed into this
                # handler); the single-replica service has no fleet.
                snap = getattr(service, "fleet_snapshot", None)
                if snap is None:
                    self._send_json(
                        404, {"error": "not a fleet front door"})
                else:
                    self._send_json(200, snap())
            elif url.path.startswith("/result/"):
                req = service.get_request(url.path[len("/result/"):])
                qs = parse_qs(url.query or "")
                cascade = getattr(req, "is_cascade", False)
                if req is None:
                    self._send_json(404, {"error": "unknown request id"})
                elif (req.is_trajectory or cascade) and "from" in qs:
                    # Incremental poll: committed frames/events are
                    # deliverable whether the request is still running,
                    # finished, or even failed mid-path (the body
                    # carries the error).
                    try:
                        start = int(qs["from"][0])
                    except ValueError:
                        self._send_json(
                            400, {"error": "from must be an integer"})
                        return
                    self._send_json(
                        200, cascade_poll_payload(req, start) if cascade
                        else trajectory_poll_payload(req, start))
                elif not req.done():
                    body = {"id": req.id, "status": "pending"}
                    if req.is_trajectory:
                        body["n_frames"] = req.n_frames
                        body["frames_committed"] = req.frames_done()
                    if cascade:
                        body["n_frames"] = req.n_frames
                        body["n_events"] = req.n_events
                        body["events_committed"] = req.events_done()
                    self._send_json(202, body)
                elif req.error is not None:
                    self._send_json(_error_status(req.error),
                                    {"id": req.id,
                                     "error": str(req.error)},
                                    retry_after=_retry_after(req.error))
                else:
                    self._send_json(200, service.result_payload(req))
            else:
                self._send_json(404, {"error": f"no route {url.path}"})

        # -- chunked NDJSON streaming (POST /trajectory stream=true) ----

        def _write_chunk(self, data: bytes) -> None:
            self.wfile.write(b"%x\r\n" % len(data))
            self.wfile.write(data)
            self.wfile.write(b"\r\n")

        def _stream_line(self, obj: dict) -> None:
            self._write_chunk(json.dumps(obj).encode() + b"\n")

        def _stream_trajectory(self, req: TrajectoryRequest,
                               wait: float) -> None:
            """Stream frames as they commit: HTTP/1.1 chunked transfer,
            one JSON line per event.  The handler thread blocks in
            ``wait_frames`` (never the engine); errors after the header
            has gone out are delivered as a terminal NDJSON line since
            the status line is already on the wire."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self._stream_line({"id": req.id, "status": "streaming",
                               "n_frames": req.n_frames,
                               "n_views": req.n_views})
            deadline = time.monotonic() + wait
            sent = 0
            while True:
                try:
                    frames = req.wait_frames(
                        sent, timeout=max(
                            0.05, min(1.0, deadline - time.monotonic())))
                except BaseException as e:
                    self._stream_line({"id": req.id, "status": "error",
                                       "frames_committed": sent,
                                       "http_status": _error_status(e),
                                       "error": str(e)})
                    break
                for f in frames:
                    self._stream_line({"frame": sent,
                                       "view": f.tolist()})
                    sent += 1
                if req.done() and sent >= req.frames_done():
                    err = req.error
                    if err is None:
                        self._stream_line({"id": req.id, "status": "done",
                                           "frames_committed": sent,
                                           "cached": req.cached})
                    else:
                        self._stream_line(
                            {"id": req.id, "status": "error",
                             "frames_committed": sent,
                             "http_status": _error_status(err),
                             "error": str(err)})
                    break
                if time.monotonic() > deadline:
                    req.cancel()
                    self._stream_line({"id": req.id, "status": "timeout",
                                       "frames_committed": sent})
                    break
            self._write_chunk(b"")   # terminal zero-length chunk

        def _stream_cascade(self, req, wait: float) -> None:
            """Progressive-preview streaming: the same chunked-NDJSON
            surface as ``_stream_trajectory``, but the unit is a
            phase-tagged event — draft frames arrive first, then the
            refine event for each frame index replaces it client-side."""
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            self._stream_line({"id": req.id, "status": "streaming",
                               "n_frames": req.n_frames,
                               "n_events": req.n_events,
                               "n_views": req.n_views})
            deadline = time.monotonic() + wait
            sent = 0
            while True:
                try:
                    events = req.wait_events(
                        sent, timeout=max(
                            0.05, min(1.0, deadline - time.monotonic())))
                except BaseException as e:
                    self._stream_line({"id": req.id, "status": "error",
                                       "events_committed": sent,
                                       "http_status": _error_status(e),
                                       "error": str(e)})
                    break
                for e in events:
                    self._stream_line(_event_body(e, sent))
                    sent += 1
                if req.done() and sent >= req.events_done():
                    err = req.error
                    if err is None:
                        self._stream_line({"id": req.id, "status": "done",
                                           "events_committed": sent,
                                           "cached": req.cached})
                    else:
                        self._stream_line(
                            {"id": req.id, "status": "error",
                             "events_committed": sent,
                             "http_status": _error_status(err),
                             "error": str(err)})
                    break
                if time.monotonic() > deadline:
                    req.cancel()
                    self._stream_line({"id": req.id, "status": "timeout",
                                       "events_committed": sent})
                    break
            self._write_chunk(b"")   # terminal zero-length chunk

        def do_POST(self):
            url = urlparse(self.path)
            if url.path not in ("/synthesize", "/trajectory", "/cascade"):
                self._send_json(404, {"error": f"no route {url.path}"})
                return
            trajectory = url.path == "/trajectory"
            cascade = url.path == "/cascade"
            try:
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                if trajectory:
                    req = service.submit_trajectory(payload)
                elif cascade:
                    submit = getattr(service, "submit_cascade", None)
                    if submit is None:
                        raise UnsupportedSchedule(
                            "this service has no cascade surface")
                    req = submit(payload)
                else:
                    req = service.submit(payload)
            except Exception as e:
                self._send_json(_error_status(e), {"error": str(e)},
                                retry_after=_retry_after(e))
                return
            wait = float(payload.get(
                "timeout_s", service.cfg.serving.default_timeout_s)) + 5.0
            if trajectory and payload.get("stream", False):
                self._stream_trajectory(req, wait)
                return
            if cascade and payload.get("stream", False):
                self._stream_cascade(req, wait)
                return
            if not payload.get("block", True):
                body = {"id": req.id, "status": "pending"}
                if trajectory:
                    body["n_frames"] = req.n_frames
                if cascade:
                    body["n_frames"] = req.n_frames
                    body["n_events"] = req.n_events
                self._send_json(202, body)
                return
            # Block the handler thread (not the engine) for the result.
            try:
                req.result(timeout=wait)
                self._send_json(200, service.result_payload(req))
            except Exception as e:
                self._send_json(_error_status(e),
                                {"id": req.id, "error": str(e)},
                                retry_after=_retry_after(e))

    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.daemon_threads = True
    return httpd
