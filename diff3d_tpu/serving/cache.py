"""Caches for the inference service: compiled programs, params, results.

Three independent layers, cheapest first:

  * :class:`ResultCache` — LRU over full request results keyed by content
    hash (inputs + seed + params version).  The sampler is deterministic
    given the key, so a replayed request costs a dict lookup instead of
    ``256 * (n_views-1)`` model calls.
  * :class:`ProgramCache` — the executable cache is jax's own jit cache
    (keyed by input shapes); this layer pins the *key space* to the
    engine's ``(bucket, lanes)`` grid, warms shapes ahead of traffic, and
    counts compiles vs. reuses so padding policy changes show up in
    ``/metrics`` instead of as mystery latency spikes.
  * :class:`ParamsRegistry` — hot checkpoint swap.  ``Sampler`` takes
    params as a jit *argument* (``sampling/runtime.py``), so installing a
    new same-shaped pytree changes zero compiled programs; the registry
    adds the atomicity (a view step runs entirely on one version) and the
    shape guard (a mismatched tree fails at swap time with a clear error,
    not mid-request with an XLA shape error).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np


class ParamsRegistry:
    """Versioned, atomically swappable parameter pytree."""

    def __init__(self, params, version: str = "v0"):
        self._lock = threading.Lock()
        self._params = params  # guarded-by: self._lock
        self._version = version  # guarded-by: self._lock
        # _template/_treedef are write-once in __init__; swap() only
        # compares against them, so they need no guard.
        self._template = [(l.shape, l.dtype)
                          for l in jax.tree.leaves(params)]
        self._treedef = jax.tree.structure(params)
        self.swaps = 0  # guarded-by: self._lock

    def current(self) -> Tuple[str, Any]:
        with self._lock:
            return self._version, self._params

    @property
    def version(self) -> str:
        with self._lock:
            return self._version

    def swap(self, params, version: Optional[str] = None) -> str:
        """Install new params; every *subsequent* view step uses them
        (in-flight steps finish on the old version).  Raises ``ValueError``
        on any structure/shape/dtype mismatch — the compiled programs are
        specialised to the template, so a mismatch would recompile at best
        and crash mid-request at worst."""
        treedef = jax.tree.structure(params)
        if treedef != self._treedef:
            raise ValueError(
                f"params tree structure mismatch: {treedef} != "
                f"{self._treedef}")
        got = [(l.shape, l.dtype) for l in jax.tree.leaves(params)]
        for i, (new, old) in enumerate(zip(got, self._template)):
            if new != old:
                raise ValueError(
                    f"params leaf {i} shape/dtype mismatch: {new} != {old}")
        with self._lock:
            self.swaps += 1
            self._version = version or f"v{self.swaps}"
            self._params = params
            return self._version


class ProgramCache:
    """Tracks the compiled view-step programs by ``(bucket, lanes)``.

    jax's jit cache holds the executables; first use of a new key is a
    trace+compile (timed and counted here), later uses are cache hits.

    ``sampler`` may be a single :class:`~diff3d_tpu.sampling.Sampler` or
    a dict ``{(sampler_kind, steps): Sampler}`` (the engine's schedule
    registry, all sharing one params pytree): a bucket whose
    ``steps``/``sampler`` fields are set routes to the matching sampler,
    so the schedule rides the SAME key space as the shapes — no
    on-demand sampler construction, no unbounded program variants.
    """

    def __init__(self, sampler, metrics=None):
        if isinstance(sampler, dict):
            if not sampler:
                raise ValueError("ProgramCache: empty sampler dict")
            self._samplers = dict(sampler)
            self._sampler = next(iter(sampler.values()))
        else:
            self._samplers = {
                (getattr(sampler, "sampler_kind", None),
                 getattr(sampler, "steps", None)): sampler}
            self._sampler = sampler
        # Cascade phase programs ride a SEPARATE registry keyed by the
        # bucket's phase tag: a refine program takes an extra drafts
        # operand, so it must never be reachable through the plain
        # (kind, steps) schedule space even at identical shapes.
        self._phase_samplers: Dict[str, object] = {}
        # Per-phase params adapters (draft: resolution-adapt the served
        # params) with an identity-memoized result, so a rollout's
        # swapped params are re-adapted exactly once, not per view step.
        self._phase_adapt: Dict[str, object] = {}
        self._phase_adapted: Dict[str, tuple] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()
        self._programs: Dict[tuple, dict] = {}  # guarded-by: self._lock
        m = metrics
        self._compiles = m.counter(
            "serving_program_compiles_total",
            "distinct (bucket, lanes) programs compiled") if m else None
        self._hits = m.counter(
            "serving_program_hits_total",
            "view steps served by an already-compiled program") if m \
            else None

    def register_phase(self, phase: str, sampler, adapt=None) -> None:
        """Attach a cascade phase sampler: buckets tagged ``phase``
        dispatch here instead of the schedule registry.  ``adapt``
        (optional) maps the engine's current served params to this
        phase's params — the draft phase resolution-adapts them; the
        refine phase serves them as-is."""
        if phase not in ("draft", "refine"):
            raise ValueError(f"phase={phase!r} not in ('draft', 'refine')")
        self._phase_samplers[phase] = sampler
        if adapt is not None:
            self._phase_adapt[phase] = adapt

    def _phase_params(self, phase: str, params):
        """The params a phase program should run with: the served params
        through the phase's adapter, memoized by identity (one adaption
        per swap, not per view step; the previous params generation is
        dropped from the memo when a new one arrives)."""
        adapt = self._phase_adapt.get(phase)
        if adapt is None or params is None:
            return params
        with self._lock:
            cached = self._phase_adapted.get(phase)
            if cached is not None and cached[0] is params:
                return cached[1]
        adapted = adapt(params)
        with self._lock:
            self._phase_adapted[phase] = (params, adapted)
        return adapted

    def _sampler_for(self, bucket):
        """The sampler serving ``bucket``'s schedule (default sampler for
        legacy 3-tuple buckets / unresolved schedules; the phase registry
        for cascade-tagged buckets)."""
        phase = getattr(bucket, "phase", None)
        if phase is not None:
            try:
                return self._phase_samplers[phase]
            except KeyError:
                raise KeyError(
                    f"no {phase!r} phase sampler (bucket {tuple(bucket)}); "
                    "the engine should have rejected this cascade at "
                    "submit time")
        kind = getattr(bucket, "sampler", None)
        steps = getattr(bucket, "steps", None)
        if kind is None and steps is None:
            return self._sampler
        key = (kind if kind is not None
               else getattr(self._sampler, "sampler_kind", None),
               steps if steps is not None
               else getattr(self._sampler, "steps", None))
        try:
            return self._samplers[key]
        except KeyError:
            raise KeyError(
                f"no sampler for schedule {key} (bucket {tuple(bucket)}); "
                "the engine should have rejected this at submit time")

    @staticmethod
    def _schedule_of(bucket) -> tuple:
        return (getattr(bucket, "sampler", None),
                getattr(bucket, "steps", None))

    def step_many(self, bucket, lanes: int, record_imgs, record_R,
                  record_T, steps, K, rngs, *, params=None, drafts=None):
        """Run one batched view step (device-resident signature: the pose
        buffers carry every view's pose, ``rngs`` are per-lane PRNG
        carries split inside).  ``drafts`` is the refine phase's
        ``[N, B, H, W, 3]`` upsampled-draft operand (None elsewhere).
        Returns the sampler's full ``(out, record_imgs, steps + 1,
        rngs)`` carry tuple."""
        sampler = self._sampler_for(bucket)
        phase = getattr(bucket, "phase", None)
        if phase is not None:
            params = self._phase_params(phase, params)
        key = (tuple(bucket), int(lanes))
        with self._lock:
            entry = self._programs.get(key)
            first = entry is None
            if first:
                entry = self._programs[key] = {
                    "compile_s": None, "uses": 0,
                    "steps": getattr(sampler, "steps", None),
                    "sampler": getattr(sampler, "sampler_kind", None),
                    # Bucket object kept so stats() can re-lower the
                    # program for its memory footprint (memory=None
                    # until first computed; guarded-by: self._lock).
                    "bucket": bucket, "memory": None}
            entry["uses"] += 1
        if first and self._compiles:
            self._compiles.inc()
        if not first and self._hits:
            self._hits.inc()
        t0 = time.monotonic()
        kw = {} if drafts is None else {"drafts": drafts}
        out = sampler.step_many(record_imgs, record_R, record_T,
                                steps, K, rngs, params=params, **kw)
        if first:
            out = jax.block_until_ready(out)
            with self._lock:
                self._programs[key]["compile_s"] = time.monotonic() - t0
        return out

    def warmup(self, bucket, lanes: int, guidance_B: int, *,
               params=None) -> float:
        """Compile the ``(bucket, lanes)`` program on zeros ahead of
        traffic; returns the wall seconds spent (0 if already cached)."""
        key = (tuple(bucket), int(lanes))
        with self._lock:
            if key in self._programs:
                return 0.0
        H, W, cap = tuple(bucket)[:3]
        N = int(lanes)
        drafts = (np.zeros((N, guidance_B, H, W, 3), np.float32)
                  if getattr(bucket, "phase", None) == "refine" else None)
        t0 = time.monotonic()
        out = self.step_many(
            bucket, lanes,
            np.zeros((N, cap, guidance_B, H, W, 3), np.float32),
            np.zeros((N, cap, 3, 3), np.float32),
            np.zeros((N, cap, 3), np.float32),
            np.ones((N,), np.int32),
            np.zeros((N, 3, 3), np.float32),
            np.stack([np.asarray(jax.random.PRNGKey(i)) for i in range(N)]),
            params=params, drafts=drafts)
        jax.block_until_ready(out)
        return time.monotonic() - t0

    def lower(self, bucket, lanes: int):
        """Lower the ``(bucket, lanes)`` view-step program on ABSTRACT
        args (no zeros staged, nothing executed) — the analysis hook
        shardcheck uses to audit a serving-warmup program's collectives.
        Routes through the same schedule dispatch as :meth:`step_many`,
        so the lowered program IS the one :meth:`warmup` would compile."""
        sampler = self._sampler_for(bucket)
        H, W, cap = tuple(bucket)[:3]
        return sampler.lower_step_many(int(lanes), int(cap),
                                       H=int(H), W=int(W))

    def _memory_of(self, key: tuple) -> Optional[dict]:
        """Per-program memory footprint (peak HBM estimate + argument
        bytes) from the compiled executable's memory analysis, computed
        at most once per program and cached in its entry.  The compile
        happens OUTSIDE the lock (jax's compilation cache makes
        re-compiling the already-warmed program cheap); best-effort —
        a backend without memory analysis yields None, never an error
        in the ``/stats`` path."""
        with self._lock:
            entry = self._programs.get(key)
            if entry is None or entry.get("memory") is not None:
                return entry.get("memory") if entry else None
            bucket = entry.get("bucket")
        if bucket is None:           # pre-existing entry shape (tests)
            return None
        try:
            from diff3d_tpu.analysis import mem as mem_lib

            compiled = self.lower(bucket, key[1]).compile()
            stats = mem_lib.compiled_memory_stats(compiled)
            memory = None
            if stats is not None:
                memory = {
                    "peak_bytes": (stats["argument_bytes"]
                                   + stats["output_bytes"]
                                   + stats["temp_bytes"]
                                   + stats["generated_code_bytes"]
                                   - stats["alias_bytes"]),
                    "argument_bytes": stats["argument_bytes"],
                    "temp_bytes": stats["temp_bytes"],
                }
        except Exception:
            memory = None
        if memory is not None:
            with self._lock:
                entry = self._programs.get(key)
                if entry is not None:
                    entry["memory"] = memory
        return memory

    def supported_schedules(self) -> list:
        """Sorted ``"kind:steps"`` strings of the routable samplers."""
        return sorted(
            f"{k[0]}:{k[1]}" for k in self._samplers)

    def stats(self, include_memory: bool = False) -> dict:
        default = (getattr(self._sampler, "sampler_kind", None),
                   getattr(self._sampler, "steps", None))

        def name(k):
            b, lanes = k
            s = f"H{b[0]}xW{b[1]}xcap{b[2]}"
            kind, steps = (b[4], b[3]) if len(b) >= 5 else (None, None)
            # Default-schedule buckets keep the legacy (schedule-free)
            # name — dashboards keyed on it stay longitudinal; only
            # non-default schedules grow a distinguishing segment.
            if ((kind is not None or steps is not None)
                    and (kind, steps) != default):
                s += (f"x{kind or 'default'}"
                      f"{steps if steps is not None else ''}")
            if len(b) >= 6 and b[5] is not None:
                s += f"x{b[5]}"      # cascade phase tag
            return s + f"xlanes{lanes}"

        with self._lock:
            keys = list(self._programs)
        # Fill per-program memory blocks before snapshotting (cached
        # after the first request per program; lock released —
        # _memory_of may compile).  Opt-in: the compile-free callers
        # (metrics snapshots, health) skip it, reporting whatever a
        # prior memory-including call already cached.
        if include_memory:
            memory = {k: self._memory_of(k) for k in keys}
        else:
            with self._lock:
                memory = {k: self._programs[k].get("memory")
                          for k in keys if k in self._programs}
        with self._lock:
            return {
                "programs": {
                    name(k): {
                        "uses": v["uses"],
                        "compile_s": v["compile_s"],
                        "steps": v.get("steps"),
                        "sampler": v.get("sampler"),
                        "peak_bytes": (memory.get(k) or {}).get(
                            "peak_bytes"),
                        "argument_bytes": (memory.get(k) or {}).get(
                            "argument_bytes"),
                    } for k, v in self._programs.items()
                },
                "num_programs": len(self._programs),
                "supported_schedules": self.supported_schedules(),
            }


class ResultCache:
    """Thread-safe LRU of completed request results.

    Keys come from :meth:`ViewRequest.content_key` (inputs + seed + params
    version); values are the ``[n_views-1, B, H, W, 3]`` output arrays.
    ``capacity=0`` disables caching entirely.
    """

    def __init__(self, capacity: int = 32, metrics=None):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, np.ndarray]" = (
            OrderedDict())  # guarded-by: self._lock
        m = metrics
        self._hit_ctr = m.counter(
            "serving_result_cache_hits_total",
            "requests answered from the result cache") if m else None

    def get(self, key: str) -> Optional[np.ndarray]:
        with self._lock:
            val = self._entries.get(key)
            if val is not None:
                self._entries.move_to_end(key)
                if self._hit_ctr:
                    self._hit_ctr.inc()
            return val

    def put(self, key: str, value: np.ndarray) -> None:
        if self.capacity <= 0:
            return
        with self._lock:
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
