"""Socket replica transport: length-prefixed JSON frames + RemoteReplica.

PR 12's fleet router multiplexes N replicas that all live in one Python
process — one OOM or segfault takes down every replica.  This module
puts the deliberately small :class:`~diff3d_tpu.serving.fleet.Replica`
surface (submit / health / depth / drain / resume / inflight / kill,
plus trajectory frame cursors) behind a socket so replicas become
separate *processes* pinned to disjoint device slices
(``serving/worker.py`` is the far end; ``cli/worker_cli.py`` boots it).

**Frame layout** (DESIGN.md §19): every message is one frame —

    +----------------+----------------------------------+
    | length: !I (4B)| body: UTF-8 JSON, `length` bytes |
    +----------------+----------------------------------+

Requests are ``{"op": str, "args": {...}}``; responses are
``{"ok": true, "value": ...}`` or ``{"ok": false, "error": {...}}``.
numpy arrays ride inside the JSON as ``{"__nd__": {dtype, shape,
b64}}`` — raw little-endian bytes, so a round-trip is *bit-exact* (the
fleet's bit-parity contract survives the wire).  Malformed input is a
typed error, never a hung socket: a declared length past the cap is
:class:`FrameTooLarge`, EOF mid-frame is :class:`FrameTruncated`,
a body that isn't a JSON object is :class:`FrameGarbage`, and every
socket op runs under a timeout (:class:`TransportError` on expiry).

**Error taxonomy over the wire**: the server encodes the typed
retryable taxonomy (scheduler.py) by class name + payload fields;
:func:`decode_error` rehydrates the same class client-side, so
``RemoteReplica.submit`` raises exactly what ``Replica.submit`` would
— the router's placement logic needs zero changes.

**RemoteReplica** duck-types :class:`~diff3d_tpu.serving.fleet.Replica`:
short reads (depth/supports/ledger) are live RPCs with a cached
fallback, results stream back on a dedicated poller connection (plain
requests resolve from the terminal poll; trajectory requests commit
frames through the same ``?from=K`` cursor semantics as the HTTP
surface), and a heartbeat thread supervises the connection — a worker
silent past ``heartbeat_timeout_s`` is marked ``dead`` (terminal, like
an in-process kill), its in-flight sticky requests are rejected with a
typed :class:`~diff3d_tpu.serving.scheduler.SessionLost` naming it,
and the router fails sessionless traffic over to the survivors.
"""

from __future__ import annotations

import base64
import json
import logging
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from diff3d_tpu.runtime.retry import RetryableError
from diff3d_tpu.serving.fleet import HEALTH_DEAD
from diff3d_tpu.serving.scheduler import (EngineDraining, EngineOverloaded,
                                          EngineStepError, EngineStopped,
                                          FleetOverloaded, QueueFullError,
                                          ReplicaDraining, ReplicaOverBudget,
                                          RequestCancelled, RequestTimeout,
                                          SessionLost, TrajectoryRequest,
                                          UnsupportedSchedule, ViewRequest)

log = logging.getLogger(__name__)

#: Frame-size ceiling.  A frame carries at most one request's views or
#: one result batch; base64 inflates arrays ~4/3, so this bounds a
#: result at ~¾ GiB of raw pixels — far past any served bucket.
DEFAULT_MAX_FRAME_BYTES = 1 << 30

_LEN = struct.Struct("!I")


# ---------------------------------------------------------------------------
# Typed transport faults (all retryable: the *connection*, not the
# request, is the problem — the caller resubmits or fails over).
# ---------------------------------------------------------------------------


class TransportError(RetryableError):
    """Socket-level fault talking to a worker: connect/read/write
    failure or timeout.  Retryable — the heartbeat decides whether the
    worker is dead or just slow."""


class FrameTooLarge(TransportError):
    """Declared frame length exceeds the negotiated cap — refuse to
    buffer it (a garbage header would otherwise demand gigabytes)."""


class FrameTruncated(TransportError):
    """Peer closed the connection mid-frame (after the length prefix
    promised more bytes)."""


class FrameGarbage(TransportError):
    """Frame body is not a JSON object — protocol violation."""


# ---------------------------------------------------------------------------
# Array / payload codec
# ---------------------------------------------------------------------------


def encode_payload(obj: Any) -> Any:
    """JSON-able deep copy of ``obj`` with ndarrays as bit-exact
    ``{"__nd__": ...}`` blocks (little-endian raw bytes + base64)."""
    if isinstance(obj, np.ndarray):
        a = np.ascontiguousarray(obj)
        if a.dtype.byteorder == ">":
            a = a.astype(a.dtype.newbyteorder("<"))
        return {"__nd__": {
            "dtype": a.dtype.str,
            "shape": list(a.shape),
            "b64": base64.b64encode(a.tobytes()).decode("ascii"),
        }}
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, (np.bool_,)):
        return bool(obj)
    if isinstance(obj, dict):
        return {k: encode_payload(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [encode_payload(v) for v in obj]
    return obj


def decode_payload(obj: Any) -> Any:
    """Inverse of :func:`encode_payload` (arrays come back bit-equal)."""
    if isinstance(obj, dict):
        nd = obj.get("__nd__")
        if nd is not None and set(obj) == {"__nd__"}:
            raw = base64.b64decode(nd["b64"])
            return np.frombuffer(raw, dtype=np.dtype(nd["dtype"])).reshape(
                nd["shape"]).copy()
        return {k: decode_payload(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [decode_payload(v) for v in obj]
    return obj


# ---------------------------------------------------------------------------
# Frame I/O
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, obj: Any,
               max_bytes: int = DEFAULT_MAX_FRAME_BYTES) -> None:
    body = json.dumps(encode_payload(obj)).encode()
    if len(body) > max_bytes:
        raise FrameTooLarge(
            f"outgoing frame {len(body)} bytes exceeds cap {max_bytes}")
    sock.sendall(_LEN.pack(len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """``n`` bytes or None on clean EOF at offset 0; EOF mid-read is a
    :class:`FrameTruncated`."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = sock.recv(min(1 << 20, n - got))
        if not chunk:
            if got == 0:
                return None
            raise FrameTruncated(
                f"peer closed mid-frame: wanted {n} bytes, got {got}")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket,
               max_bytes: int = DEFAULT_MAX_FRAME_BYTES) -> Optional[dict]:
    """One decoded frame, None on clean EOF.  Raises the typed frame
    faults; a socket timeout propagates as ``socket.timeout`` for the
    caller to classify (server: drop connection; client: TransportError).
    """
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > max_bytes:
        raise FrameTooLarge(
            f"declared frame length {length} exceeds cap {max_bytes}")
    body = _recv_exact(sock, length)
    if body is None:
        raise FrameTruncated("peer closed between header and body")
    try:
        obj = json.loads(body)
    except ValueError as e:
        raise FrameGarbage(f"frame body is not JSON: {e}") from e
    if not isinstance(obj, dict):
        raise FrameGarbage(
            f"frame body must be a JSON object, got {type(obj).__name__}")
    return decode_payload(obj)


# ---------------------------------------------------------------------------
# Error codec: typed taxonomy across the wire
# ---------------------------------------------------------------------------

#: Classes that cross the wire by name.  Anything else degrades to a
#: RuntimeError carrying the original type name in its message.
_WIRE_ERRORS = {cls.__name__: cls for cls in (
    QueueFullError, RequestTimeout, RequestCancelled, EngineStepError,
    EngineOverloaded, EngineDraining, EngineStopped, UnsupportedSchedule,
    FleetOverloaded, ReplicaDraining, SessionLost, ReplicaOverBudget,
    TransportError, FrameTooLarge, FrameTruncated, FrameGarbage,
    ValueError, KeyError, TypeError, RuntimeError,
)}

#: Extra constructor/attribute fields carried per class (beyond msg and
#: retry_after_s, which every RetryableError has).
_ERROR_FIELDS = ("replica", "supported", "budget_bytes", "resident_bytes",
                 "program_peak_bytes")


def encode_error(exc: BaseException) -> dict:
    d: Dict[str, Any] = {"type": type(exc).__name__, "msg": str(exc)}
    after = getattr(exc, "retry_after_s", None)
    if after is not None:
        d["retry_after_s"] = float(after)
    for f in _ERROR_FIELDS:
        v = getattr(exc, f, None)
        if v is not None:
            d[f] = v
    return d


def decode_error(d: dict) -> BaseException:
    name = d.get("type", "RuntimeError")
    msg = d.get("msg", "")
    cls = _WIRE_ERRORS.get(name)
    if cls is None:
        return RuntimeError(f"{name}: {msg}")
    if not issubclass(cls, RetryableError):
        # KeyError reprs its arg; keep the message readable either way.
        return cls(msg)
    kwargs: Dict[str, Any] = {}
    if d.get("retry_after_s") is not None:
        kwargs["retry_after_s"] = float(d["retry_after_s"])
    if issubclass(cls, UnsupportedSchedule) and "supported" in d:
        kwargs["supported"] = list(d["supported"])
    if issubclass(cls, (ReplicaDraining, SessionLost, ReplicaOverBudget)) \
            and "replica" in d:
        kwargs["replica"] = d["replica"]
    if issubclass(cls, ReplicaOverBudget):
        for f in ("budget_bytes", "resident_bytes", "program_peak_bytes"):
            if f in d:
                kwargs[f] = int(d[f])
    return cls(msg, **kwargs)


def request_wire(req: ViewRequest) -> dict:
    """Serialize a request for the worker's ``submit`` op.  The worker
    rebuilds the exact ViewRequest/TrajectoryRequest (same id, seed,
    schedule, session), so results and the RNG stream are bit-identical
    to an in-process submit."""
    return {
        "id": req.id,
        "trajectory": req.is_trajectory,
        "seed": req.seed,
        "n_views": req.n_views,
        "timeout_s": req.timeout_s,
        "sampler_kind": req.sampler_kind,
        "steps": req.steps,
        "session_id": req.session_id,
        "views": {
            "imgs": req.imgs0[None],
            "R": req.R,
            "T": req.T,
            "K": req.K,
        },
    }


def request_from_wire(d: dict) -> ViewRequest:
    cls = TrajectoryRequest if d.get("trajectory") else ViewRequest
    return cls(d["views"], seed=int(d.get("seed", 0)),
               n_views=d.get("n_views"),
               timeout_s=d.get("timeout_s"),
               request_id=d.get("id"),
               sampler_kind=d.get("sampler_kind"),
               steps=d.get("steps"),
               session_id=d.get("session_id"))


# ---------------------------------------------------------------------------
# Client connection: one socket, serialized request/response RPCs
# ---------------------------------------------------------------------------


class Connection:
    """One framed RPC connection to a worker.

    ``_io_lock`` is a *leaf* lock serializing the wire (one in-flight
    RPC per connection); no other lock is ever taken while holding it.
    Callers that need concurrency open more connections — RemoteReplica
    keeps one for short control RPCs, one for the poller thread, and
    dials ephemeral ones for long lifecycle calls (drain) so a 30 s
    drain can never stall routing reads.
    """

    def __init__(self, host: str, port: int, *, timeout_s: float = 10.0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self.host, self.port = host, int(port)
        self.timeout_s = float(timeout_s)
        self.max_frame_bytes = int(max_frame_bytes)
        self._io_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None  # guarded-by: self._io_lock
        #: Last round-trip in ms (benign racy read: a float snapshot for
        #: metrics, monotonic writers only on this connection).
        self.last_rtt_ms: Optional[float] = None

    def _dial(self) -> socket.socket:
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def call(self, op: str, args: Optional[dict] = None,
             timeout_s: Optional[float] = None) -> Any:
        """One RPC; returns the response value or raises the rehydrated
        typed error.  Any socket fault closes the connection (the next
        call redials) and raises :class:`TransportError`."""
        # Dial outside the lock; install under it (losers close theirs).
        with self._io_lock:
            sock = self._sock
        if sock is None:
            try:
                fresh = self._dial()
            except OSError as e:
                raise TransportError(
                    f"{self.host}:{self.port}: connect failed: {e}") from e
            with self._io_lock:
                if self._sock is None:
                    self._sock = fresh
                else:
                    fresh.close()
        t0 = time.monotonic()
        with self._io_lock:
            sock = self._sock
            if sock is None:
                raise TransportError(
                    f"{self.host}:{self.port}: connection closed")
            try:
                sock.settimeout(self.timeout_s if timeout_s is None
                                else float(timeout_s))
                send_frame(sock, {"op": op, "args": args or {}},
                           self.max_frame_bytes)
                resp = recv_frame(sock, self.max_frame_bytes)
            except TransportError:
                self._close_locked()
                raise
            except (OSError, socket.timeout) as e:
                self._close_locked()
                raise TransportError(
                    f"{self.host}:{self.port}: {op} failed: {e}") from e
        self.last_rtt_ms = (time.monotonic() - t0) * 1e3
        if resp is None:
            with self._io_lock:
                self._close_locked()
            raise FrameTruncated(
                f"{self.host}:{self.port}: peer closed before replying "
                f"to {op}")
        if resp.get("ok"):
            return resp.get("value")
        raise decode_error(resp.get("error") or {})

    def _close_locked(self) -> None:  # guarded-by: self._io_lock
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        with self._io_lock:
            self._close_locked()

    @property
    def connected(self) -> bool:
        with self._io_lock:
            return self._sock is not None


# ---------------------------------------------------------------------------
# RemoteReplica: the Replica duck-type over a Connection
# ---------------------------------------------------------------------------


class RemoteReplica:
    """A worker process seen through the replica surface.

    The router reads ``health``/``depth``/``supports`` and calls
    ``submit``/``drain``/``resume``/``swap_params``/``kill`` exactly as
    it would on an in-process :class:`~diff3d_tpu.serving.fleet.Replica`
    — placement logic is unchanged.  Three connections: ``_conn`` for
    short control RPCs, ``_poll_conn`` owned by the poller/heartbeat
    thread, and ephemeral dials for long lifecycle calls.

    Death is terminal, mirroring the in-process contract: once the
    heartbeat goes ``heartbeat_timeout_s`` without a successful probe
    the replica reports ``dead`` forever, in-flight requests are
    rejected with :class:`SessionLost` naming it, and the router tells
    its sticky sessions the record is gone.
    """

    def __init__(self, host: str, port: int, *,
                 name: Optional[str] = None,
                 rpc_timeout_s: float = 10.0,
                 heartbeat_interval_s: float = 0.25,
                 heartbeat_timeout_s: float = 3.0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self.host, self.port = host, int(port)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self._conn = Connection(host, port, timeout_s=rpc_timeout_s,
                                max_frame_bytes=max_frame_bytes)
        self._poll_conn = Connection(host, port, timeout_s=rpc_timeout_s,
                                     max_frame_bytes=max_frame_bytes)
        self._lock = threading.Lock()
        self._state: Dict[str, Any] = {}  # guarded-by: self._lock
        self._inflight: Dict[str, ViewRequest] = {}  # guarded-by: self._lock
        self._cursors: Dict[str, int] = {}  # guarded-by: self._lock
        self._dead = False  # guarded-by: self._lock
        self._dead_reason = ""  # guarded-by: self._lock
        self._hb_timeouts = 0  # guarded-by: self._lock
        self._last_ok = time.monotonic()  # guarded-by: self._lock
        self._stop_evt = threading.Event()
        self._wake_evt = threading.Event()
        self._poller: Optional[threading.Thread] = None
        # Adopt the worker's replica name so SessionLost / the session
        # ledger / GET /fleet all name the same identity fleet-wide.
        state = self._conn.call("state")
        with self._lock:
            self._state = state
        self.name = str(name or state.get("name")
                        or f"w@{host}:{port}")

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "RemoteReplica":
        if self._poller is None:
            self._poller = threading.Thread(
                target=self._poll_loop,
                name=f"diff3d-remote-{self.name}", daemon=True)
            self._poller.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        """Detach from the worker (the worker process keeps running —
        ``worker_cli`` owns its lifecycle).  Local in-flight futures are
        rejected so no client hangs on a connection we no longer poll."""
        self._stop_evt.set()
        self._wake_evt.set()
        if self._poller is not None:
            self._poller.join(timeout)
        self._reject_inflight(EngineStopped(
            f"remote replica {self.name}: front door detached"))
        self._conn.close()
        self._poll_conn.close()

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Worker-side drain over an ephemeral connection (it can block
        for the full timeout without stalling control RPCs)."""
        wait = 30.0 if timeout is None else float(timeout)
        conn = Connection(self.host, self.port, timeout_s=wait + 10.0)
        try:
            return bool(conn.call("drain", {"timeout": timeout},
                                  timeout_s=wait + 10.0))
        except TransportError:
            return False
        finally:
            conn.close()

    def resume(self) -> None:
        try:
            self._conn.call("resume")
        except TransportError as e:
            log.warning("remote %s: resume failed: %s", self.name, e)

    def kill(self, reason: str = "killed") -> None:
        """Kill the *replica on the worker* (chaos parity with the
        in-process path); the worker process survives to report dead."""
        try:
            self._conn.call("kill", {"reason": reason})
        except TransportError:
            # Worker unreachable — the heartbeat will mark us dead.
            pass

    # -- state the router reads ------------------------------------------

    def _cached(self, key: str, default=None):
        with self._lock:
            return self._state.get(key, default)

    @property
    def health(self) -> str:
        with self._lock:
            if self._dead:
                return HEALTH_DEAD
            return str(self._state.get("health", HEALTH_DEAD))

    def depth(self) -> int:
        try:
            return int(self._conn.call("depth"))
        except TransportError:
            return int(self._cached("depth", 1 << 30))

    def supports(self, sampler_kind: Optional[str] = None,
                 steps: Optional[int] = None) -> bool:
        try:
            return bool(self._conn.call(
                "supports", {"sampler_kind": sampler_kind, "steps": steps}))
        except TransportError:
            return False

    def supported_schedules(self) -> List[str]:
        return list(self._cached("supported_schedules", []))

    @property
    def params_version(self) -> str:
        return str(self._cached("params_version", "unknown"))

    def session_records(self) -> Dict[str, int]:
        """Live ledger; falls back to the last heartbeat's copy so the
        zero-migration audit still sees a SIGKILLed worker's sessions."""
        try:
            got = self._conn.call("session_records")
            return {str(k): int(v) for k, v in got.items()}
        except TransportError:
            return dict(self._cached("session_records", {}))

    def session_count(self, session_id: str) -> int:
        return self.session_records().get(session_id, 0)

    def swap_params(self, params, version: Optional[str] = None) -> str:
        """Ship the new params as flat leaves (the worker unflattens
        against its own treedef and runs the registry's shape guard) —
        the blue/green rollout path, now cross-process."""
        import jax

        leaves = [np.asarray(leaf) for leaf in jax.tree.leaves(params)]
        conn = Connection(self.host, self.port,
                          timeout_s=max(60.0, self.rpc_timeout_s),
                          max_frame_bytes=self._conn.max_frame_bytes)
        try:
            return str(conn.call("swap_params",
                                 {"leaves": leaves, "version": version}))
        finally:
            conn.close()

    def snapshot(self) -> dict:
        try:
            snap = self._conn.call("snapshot")
        except TransportError:
            snap = {"name": self.name, "health": self.health,
                    "queue_depth": self._cached("depth", 0),
                    "params_version": self.params_version,
                    "supported_schedules": self.supported_schedules(),
                    "sessions": len(self._cached("session_records", {}))}
        snap["transport"] = self.transport_stats()
        return snap

    def transport_stats(self) -> dict:
        """Connection-supervision block: RTT, liveness and the counters
        the router folds into GET /metrics."""
        with self._lock:
            dead, hb = self._dead, self._hb_timeouts
            state = self._state
        rtts = [c.last_rtt_ms for c in (self._conn, self._poll_conn)
                if c.last_rtt_ms is not None]
        return {
            "remote": f"{self.host}:{self.port}",
            "connected": not dead and (self._conn.connected
                                       or self._poll_conn.connected),
            "rtt_ms": round(min(rtts), 3) if rtts else None,
            "heartbeat_timeouts": hb,
            "admission_rejects_hbm": int(
                (state.get("hbm") or {}).get("rejects", 0)),
        }

    # -- request path ----------------------------------------------------

    def submit(self, req: ViewRequest) -> ViewRequest:
        """Wire submit + poller registration.  Raises the same typed
        taxonomy as the in-process submit (rehydrated from the wire);
        the returned request resolves asynchronously when the poller
        streams the worker's result back."""
        with self._lock:
            if self._dead:
                reason = self._dead_reason
                raise EngineStopped(
                    f"{req.id}: remote replica {self.name} is dead"
                    f" ({reason})")
        self._conn.call("submit", request_wire(req))
        with self._lock:
            self._inflight[req.id] = req
            self._cursors[req.id] = 0
        self._wake_evt.set()
        return req

    # -- poller / heartbeat thread ---------------------------------------

    def _poll_loop(self) -> None:
        while not self._stop_evt.is_set():
            had_work = self._heartbeat()
            if self._is_dead():
                self._reject_inflight(SessionLost(
                    f"remote replica {self.name} stopped heartbeating; "
                    "its device-resident records are lost — restart "
                    "sessions from their committed views",
                    replica=self.name))
                return
            had_work = self._poll_inflight() or had_work
            if not had_work:
                self._wake_evt.wait(self.heartbeat_interval_s)
                self._wake_evt.clear()

    def _is_dead(self) -> bool:
        with self._lock:
            return self._dead

    def _heartbeat(self) -> bool:
        """One probe: refresh cached state or advance the death clock.
        Returns True when in-flight work exists (skip the idle sleep)."""
        try:
            state = self._poll_conn.call(
                "state", timeout_s=min(self.rpc_timeout_s,
                                       self.heartbeat_timeout_s))
        except TransportError as e:
            with self._lock:
                expired = (time.monotonic() - self._last_ok
                           > self.heartbeat_timeout_s)
                if expired and not self._dead:
                    self._dead = True
                    self._dead_reason = f"heartbeat timeout: {e}"
                    self._hb_timeouts += 1
            if self._is_dead():
                log.warning("remote %s: marked dead (%s)", self.name, e)
            return False
        with self._lock:
            self._state = state
            self._last_ok = time.monotonic()
            return bool(self._inflight)

    def _poll_inflight(self) -> bool:
        with self._lock:
            pending: List[Tuple[str, ViewRequest, int]] = [
                (rid, req, self._cursors.get(rid, 0))
                for rid, req in self._inflight.items()]
        for rid, req, cursor in pending:
            try:
                got = self._poll_conn.call(
                    "poll", {"id": rid, "from": cursor,
                             "wait_s": 0.2 if req.is_trajectory else 0.2})
            except TransportError:
                return True     # heartbeat owns the death decision
            self._apply_poll(rid, req, got)
        return bool(pending)

    def _apply_poll(self, rid: str, req: ViewRequest, got: dict) -> None:
        frames = got.get("frames") or []
        if frames and req.is_trajectory:
            with self._lock:
                start = self._cursors.get(rid, 0)
            for i, frame in enumerate(frames):
                # frame k (0-based) is synthesised view k+1; the
                # request's commit hook drops out-of-order duplicates.
                req._commit_frame(start + i + 1, np.asarray(frame))
            with self._lock:
                self._cursors[rid] = start + len(frames)
        status = got.get("status")
        if status == "done":
            req.cached = bool(got.get("cached", False))
            req._resolve(np.asarray(got["result"]))
        elif status == "failed":
            req._reject(decode_error(got.get("error") or {}))
        elif status == "unknown":
            req._reject(EngineStepError(
                f"{rid}: remote replica {self.name} no longer knows this "
                "request (worker restarted?)"))
        else:
            return
        with self._lock:
            self._inflight.pop(rid, None)
            self._cursors.pop(rid, None)

    def _reject_inflight(self, exc: BaseException) -> None:
        with self._lock:
            victims = list(self._inflight.values())
            self._inflight.clear()
            self._cursors.clear()
        for req in victims:
            req._reject(exc)
