"""Replica lifecycle for the multi-replica serving fleet.

A :class:`Replica` is one self-contained serving engine — its own
bounded :class:`~diff3d_tpu.serving.scheduler.Scheduler`,
:class:`~diff3d_tpu.serving.engine.Engine` (device executor),
:class:`~diff3d_tpu.serving.cache.ParamsRegistry`,
:class:`~diff3d_tpu.serving.cache.ProgramCache`,
:class:`~diff3d_tpu.serving.cache.ResultCache` and
:class:`~diff3d_tpu.serving.metrics.MetricsRegistry` — under a stable
name.  The router (``serving/router.py``) owns N of them behind one
HTTP surface and routes *requests to state*: an object session's
device-resident record (DESIGN.md §6b) lives on whichever replica
served its first view, so every later view of that session must land
there.  The replica therefore keeps the per-session record ledger
(:meth:`Replica.session_records`) that the affinity contract is
asserted against — one session appearing on two replicas' ledgers IS a
record migration, and the tests treat it as a bug.

Lifecycle::

    start -> (drain -> swap_params -> resume)* -> stop
                     \\-> kill                    (chaos path)

``kill`` is abrupt and non-blocking — it simulates process death.  A
killed replica reports health ``"dead"`` and never serves again: the
router fails sessionless traffic over to the survivors and rejects the
replica's orphaned sticky sessions with a typed
:class:`~diff3d_tpu.serving.scheduler.SessionLost` naming the lost
owner.

Sharing one ``sampler`` object across replicas (the
:func:`build_fleet` default) shares its jit cache, so the fleet pays
one compile per program shape instead of N — replica isolation is at
the scheduler/engine/record level, not the compiled-code level, which
is exactly the in-process-fleet shape.  Each replica still owns its
ProgramCache (per-replica program *stats*), scheduler and metrics.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from diff3d_tpu.config import Config
from diff3d_tpu.serving.cache import (ParamsRegistry, ProgramCache,
                                      ResultCache)
from diff3d_tpu.serving.engine import Engine, EngineStopTimeout
from diff3d_tpu.serving.metrics import MetricsRegistry
from diff3d_tpu.serving.scheduler import (EngineStopped, Scheduler,
                                          ViewRequest)

log = logging.getLogger(__name__)

#: Replica-level health state beyond the engine's ok|degraded|draining
#: (DESIGN.md §7): a killed replica (or one whose worker thread is gone)
#: is ``dead`` — terminal, never routed to again.
HEALTH_DEAD = "dead"


class Replica:
    """One named engine replica: scheduler + engine + caches + metrics.

    Thin by design — all serving behavior lives in the engine; the
    replica adds the identity, the session record ledger, and the
    drain/swap/resume/kill lifecycle the router composes.
    """

    def __init__(self, name: str, sampler, cfg: Config,
                 extra_samplers: Optional[dict] = None,
                 params_version: str = "v0", cascade=None):
        """``extra_samplers`` maps ``(sampler_kind, steps)`` to extra
        Sampler instances (sharing ``sampler``'s params) — the
        schedules this replica serves beyond the default sampler's own
        (the PR 4 schedule registry, now per-replica so the router can
        place 8-step-DDIM traffic on distilled-student replicas and
        parity traffic on teacher replicas).  ``cascade`` is an optional
        :class:`~diff3d_tpu.cascade.CascadeSampler` enabling the
        progressive-preview surface on this replica (DESIGN.md §20)."""
        cfg.serving.validate()
        self.name = str(name)
        self.cfg = cfg
        self.metrics = MetricsRegistry()
        self.scheduler = Scheduler(
            max_queue=cfg.serving.max_queue,
            max_wait_s=cfg.serving.max_wait_ms / 1e3,
            default_timeout_s=cfg.serving.default_timeout_s,
            metrics=self.metrics)
        self.registry = ParamsRegistry(sampler.params,
                                       version=params_version)
        samplers = {(getattr(sampler, "sampler_kind", None),
                     getattr(sampler, "steps", None)): sampler,
                    **(extra_samplers or {})}
        self.engine = Engine(
            sampler, self.scheduler, self.metrics, cfg.serving,
            params_registry=self.registry,
            result_cache=ResultCache(cfg.serving.result_cache_entries,
                                     self.metrics),
            program_cache=ProgramCache(
                samplers if len(samplers) > 1 else sampler, self.metrics),
            extra_samplers=extra_samplers, cascade=cascade)
        self._lock = threading.Lock()
        # Session record ledger: session_id -> requests served into that
        # session's record on THIS replica.  The router's zero-migration
        # contract is asserted against these counters.
        self._session_records: Dict[str, int] = {}  # guarded-by: self._lock
        self._killed = False  # guarded-by: self._lock
        self._records_ctr = self.metrics.counter(
            "replica_session_records_total",
            "session-carrying requests served into this replica's records")

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Replica":
        self.engine.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        try:
            self.engine.stop(timeout=timeout)
        except EngineStopTimeout:
            # The worker thread is leaked (wedged in a device call); the
            # fleet keeps shutting the other replicas down — one wedged
            # replica must not leak its siblings too.
            log.error("replica %s: worker thread leaked on stop",
                      self.name)

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Stop admissions, wait for queued + in-flight work (the
        blue/green rollout step).  New submissions get EngineDraining;
        the router additionally turns the session-sticky ones into
        :class:`~diff3d_tpu.serving.scheduler.ReplicaDraining` before
        they reach the scheduler."""
        return self.engine.drain(timeout=timeout)

    def resume(self) -> None:
        """Re-admit after a drain (rollout complete for this replica)."""
        self.engine.resume()

    def kill(self, reason: str = "killed") -> None:
        """Simulate replica death: non-blocking, idempotent.  In-flight
        and queued requests resolve with typed retryable errors; the
        replica reports ``dead`` forever after.  Device-resident records
        die with it — the router owns telling sessions so."""
        with self._lock:
            if self._killed:
                return
            self._killed = True
        log.warning("replica %s: killed (%s)", self.name, reason)
        self.engine.kill(EngineStopped(
            f"replica {self.name} {reason}: in-flight work lost"))

    # -- state the router reads ------------------------------------------

    @property
    def health(self) -> str:
        """``ok|degraded|draining`` from the engine, or ``dead`` once
        killed / the worker thread is gone for good."""
        with self._lock:
            if self._killed:
                return HEALTH_DEAD
        return self.engine.health if self.engine.alive else HEALTH_DEAD

    def depth(self) -> int:
        """Load proxy for least-loaded placement: queued + in-flight."""
        return self.scheduler.depth() + self.engine.inflight()

    def supports(self, sampler_kind: Optional[str] = None,
                 steps: Optional[int] = None) -> bool:
        return self.engine.supports_schedule(sampler_kind, steps)

    def supported_schedules(self) -> List[str]:
        return self.engine.supported_schedules()

    def supports_cascade(self, plan_spec: Optional[str] = None) -> bool:
        return self.engine.supports_cascade(plan_spec)

    @property
    def params_version(self) -> str:
        return self.registry.version

    # -- request path ----------------------------------------------------

    def submit(self, req: ViewRequest) -> ViewRequest:
        """Engine submit + session-record accounting.  The ledger counts
        only *accepted* requests — a rejected submit leaves no trace, so
        a failed first view does not pin the session here."""
        req = self.engine.submit(req)
        if req.session_id is not None:
            with self._lock:
                self._session_records[req.session_id] = (
                    self._session_records.get(req.session_id, 0) + 1)
            self._records_ctr.inc()
        return req

    def submit_cascade(self, req) -> "ViewRequest":
        """Cascade submit + session-record accounting.  The refine phase
        conditions on (and extends) this replica's session record, so a
        session-carrying cascade pins the session here exactly like a
        plain view request."""
        req = self.engine.submit_cascade(req)
        if req.session_id is not None:
            with self._lock:
                self._session_records[req.session_id] = (
                    self._session_records.get(req.session_id, 0) + 1)
            self._records_ctr.inc()
        return req

    def session_records(self) -> Dict[str, int]:
        """Copy of the session -> served-request-count ledger."""
        with self._lock:
            return dict(self._session_records)

    def session_count(self, session_id: str) -> int:
        with self._lock:
            return self._session_records.get(session_id, 0)

    # -- rollout ---------------------------------------------------------

    def swap_params(self, params, version: Optional[str] = None) -> str:
        """Hot-swap this replica's params (serving/cache.py swap path);
        returns the new version string.  Callers drain first if they
        need no request to straddle two versions (the router's rollout
        does); the swap itself is safe mid-flight — the engine reads
        ``registry.current()`` once per view step."""
        return self.registry.swap(params, version)

    def snapshot(self) -> dict:
        """Per-replica block of ``GET /fleet``."""
        return {
            "name": self.name,
            "health": self.health,
            "queue_depth": self.scheduler.depth(),
            "inflight": self.engine.inflight(),
            "params_version": self.registry.version,
            "supported_schedules": self.supported_schedules(),
            "cascade": (self.engine.cascade.plan.spec()
                        if self.engine.cascade is not None else None),
            "sessions": len(self.session_records()),
            "session_records_total": sum(
                self.session_records().values()),
            "engine_restarts": self.engine._restarts,
            # Per-trajectory progress (frames committed / path length)
            # for every camera-path request in flight on this replica —
            # the ``GET /fleet`` view of the streaming pipeline.
            "trajectories": self.engine.trajectory_progress(),
        }


def build_fleet(sampler, cfg: Config, n: Optional[int] = None,
                extra_samplers: Optional[dict] = None,
                per_replica_extra: Optional[Dict[int, dict]] = None,
                params_version: str = "v0",
                name_prefix: str = "r", cascade=None) -> List[Replica]:
    """Build ``n`` replicas (default ``cfg.serving.replicas``) sharing
    one sampler object (one jit cache -> one compile per program across
    the fleet).  ``extra_samplers`` applies to every replica;
    ``per_replica_extra[i]`` adds replica-``i``-only schedules — the
    heterogeneous-fleet shape (e.g. one distilled-student replica in a
    teacher fleet).  A shared ``cascade``
    (:class:`~diff3d_tpu.cascade.CascadeSampler`) enables the
    progressive-preview surface fleet-wide, again paying one compile per
    cascade program."""
    n = cfg.serving.replicas if n is None else int(n)
    if n < 1:
        raise ValueError(f"fleet size {n} must be >= 1")
    replicas = []
    for i in range(n):
        extra = dict(extra_samplers or {})
        extra.update((per_replica_extra or {}).get(i, {}))
        replicas.append(Replica(f"{name_prefix}{i}", sampler, cfg,
                                extra_samplers=extra or None,
                                params_version=params_version,
                                cascade=cascade))
    return replicas
