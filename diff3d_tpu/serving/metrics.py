"""Service metrics: counters, gauges and windowed histograms.

The training side already has :class:`diff3d_tpu.utils.profiling.StepTimer`
for step cadence; serving needs the same discipline for *request* shapes —
queue depth, batch occupancy, padding waste, time-to-first-view and
end-to-end latency percentiles.  Everything here is host-side and
thread-safe (the engine, the scheduler and N HTTP handler threads all
write concurrently); no device syncs are introduced by observing a metric.

Two exposition forms:
  * :meth:`MetricsRegistry.snapshot` — JSON-able nested dict (the
    ``/metrics?format=json`` endpoint and the bench tooling consume this);
  * :meth:`MetricsRegistry.exposition` — Prometheus-style text lines (the
    plain ``/metrics`` endpoint), counters/gauges as ``name value``,
    histograms as ``name{quantile="p50"} value`` plus ``_count``/``_sum``.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, Optional

import numpy as np


class Counter:
    """Monotonic counter."""

    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._lock = threading.Lock()
        self._value = 0.0  # guarded-by: self._lock

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value."""

    def __init__(self, name: str, help_: str = ""):
        self.name, self.help = name, help_
        self._lock = threading.Lock()
        self._value = 0.0  # guarded-by: self._lock

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, d: float) -> None:
        with self._lock:
            self._value += d

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Percentiles over a bounded window of observations.

    Keeps the last ``window`` samples (same retention policy as
    ``StepTimer``) plus lifetime ``count``/``sum`` — percentiles reflect
    recent behaviour, totals reflect the whole run.
    """

    def __init__(self, name: str, help_: str = "", window: int = 1024):
        self.name, self.help = name, help_
        self._lock = threading.Lock()
        self._window: Deque[float] = (
            deque(maxlen=window))  # guarded-by: self._lock
        self._count = 0  # guarded-by: self._lock
        self._sum = 0.0  # guarded-by: self._lock

    def observe(self, v: float) -> None:
        with self._lock:
            self._window.append(float(v))
            self._count += 1
            self._sum += float(v)

    def summary(self) -> dict:
        with self._lock:
            if not self._count:
                return {"count": 0, "sum": 0.0}
            vals = np.asarray(self._window)
            return {
                "count": self._count,
                "sum": self._sum,
                "mean": float(vals.mean()),
                "p50": float(np.percentile(vals, 50)),
                "p95": float(np.percentile(vals, 95)),
                "p99": float(np.percentile(vals, 99)),
                "max": float(vals.max()),
            }


class MetricsRegistry:
    """Named get-or-create registry for the three metric kinds."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}  # guarded-by: self._lock
        self._gauges: Dict[str, Gauge] = {}  # guarded-by: self._lock
        self._histograms: Dict[str, Histogram] = (
            {})  # guarded-by: self._lock

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            if name not in self._counters:
                self._counters[name] = Counter(name, help_)
            return self._counters[name]

    def gauge(self, name: str, help_: str = "") -> Gauge:
        with self._lock:
            if name not in self._gauges:
                self._gauges[name] = Gauge(name, help_)
            return self._gauges[name]

    def histogram(self, name: str, help_: str = "",
                  window: int = 1024) -> Histogram:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = Histogram(name, help_, window)
            return self._histograms[name]

    def snapshot(self, extra: Optional[dict] = None) -> dict:
        """JSON-able snapshot of every registered metric."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        snap = {
            "counters": {n: c.value for n, c in sorted(counters.items())},
            "gauges": {n: g.value for n, g in sorted(gauges.items())},
            "histograms": {n: h.summary()
                           for n, h in sorted(hists.items())},
        }
        if extra:
            snap.update(extra)
        return snap

    def exposition(self) -> str:
        """Prometheus-style text form."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)
        lines = []
        for n, c in sorted(counters.items()):
            if c.help:
                lines.append(f"# HELP {n} {c.help}")
            lines.append(f"# TYPE {n} counter")
            lines.append(f"{n} {c.value:g}")
        for n, g in sorted(gauges.items()):
            if g.help:
                lines.append(f"# HELP {n} {g.help}")
            lines.append(f"# TYPE {n} gauge")
            lines.append(f"{n} {g.value:g}")
        for n, h in sorted(hists.items()):
            s = h.summary()
            if h.help:
                lines.append(f"# HELP {n} {h.help}")
            lines.append(f"# TYPE {n} summary")
            for q in ("p50", "p95", "p99"):
                if q in s:
                    lines.append(f'{n}{{quantile="{q}"}} {s[q]:g}')
            lines.append(f"{n}_count {s['count']}")
            lines.append(f"{n}_sum {s['sum']:g}")
        return "\n".join(lines) + "\n"
