"""Procedural stand-in dataset with the same sample contract as
:class:`diff3d_tpu.data.srn.SRNDataset`.

No reference counterpart — the reference has no test fixtures at all
(SURVEY.md §4).  Used by unit tests, the benchmark, and smoke training when
the real SRN zips are absent.  Cameras are placed on a sphere looking at the
origin with SRN-like intrinsics, and images are a deterministic function of
the object id and view angle (a shaded gradient), so two views of the same
"object" are geometrically consistent enough to overfit.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def _look_at(cam_pos: np.ndarray) -> np.ndarray:
    """World-from-camera rotation for a camera at ``cam_pos`` looking at the
    origin (OpenCV convention: +z forward, +y down)."""
    fwd = -cam_pos / np.linalg.norm(cam_pos)
    up = np.array([0.0, 0.0, 1.0])
    if abs(fwd @ up) > 0.99:
        up = np.array([0.0, 1.0, 0.0])
    right = np.cross(fwd, up)
    right /= np.linalg.norm(right)
    down = np.cross(fwd, right)
    return np.stack([right, down, fwd], axis=1)


class SyntheticDataset:
    """``sample(idx, rng)`` matches :class:`SRNDataset`'s contract."""

    def __init__(self, num_objects: int = 8, num_views: int = 16,
                 imgsize: int = 16, seed: int = 0, sample_views: int = 2):
        self.num_objects = num_objects
        self.num_views = num_views
        self.imgsize = imgsize
        self.sample_views = sample_views
        s = imgsize
        # SRN-style intrinsics: focal ~ s, principal point at the center.
        self.K = np.array([[s * 1.2, 0.0, s / 2],
                           [0.0, s * 1.2, s / 2],
                           [0.0, 0.0, 1.0]], np.float32)
        rng = np.random.default_rng(seed)
        self._phases = rng.uniform(0, 2 * np.pi, size=(num_objects, 3))

    def __len__(self) -> int:
        return self.num_objects

    def _view(self, obj: int, view: int):
        theta = 2 * np.pi * view / self.num_views
        phi = 0.3 + 0.2 * np.sin(self._phases[obj, 0] + view)
        r = 2.0
        cam = r * np.array([np.cos(theta) * np.cos(phi),
                            np.sin(theta) * np.cos(phi),
                            np.sin(phi)], np.float32)
        R = _look_at(cam).astype(np.float32)
        s = self.imgsize
        yy, xx = np.meshgrid(np.linspace(-1, 1, s), np.linspace(-1, 1, s),
                             indexing="ij")
        ph = self._phases[obj]
        img = np.stack([np.sin(3 * xx + theta + ph[0]),
                        np.cos(2 * yy - theta + ph[1]),
                        np.sin(xx * yy + ph[2] + phi)], axis=-1)
        return img.astype(np.float32), R, cam

    def sample(self, idx: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        views = rng.choice(self.num_views, size=self.sample_views,
                           replace=False)
        imgs, Rs, Ts = zip(*(self._view(idx, v) for v in views))
        return {"imgs": np.stack(imgs), "R": np.stack(Rs),
                "T": np.stack(Ts), "K": self.K}

    def all_views(self, obj: int) -> Dict[str, np.ndarray]:
        imgs, Rs, Ts = zip(*(self._view(obj, v)
                             for v in range(self.num_views)))
        return {"imgs": np.stack(imgs), "R": np.stack(Rs),
                "T": np.stack(Ts), "K": self.K}
