"""Procedural stand-in dataset with the same sample contract as
:class:`diff3d_tpu.data.srn.SRNDataset`.

No reference counterpart — the reference has no test fixtures at all
(SURVEY.md §4).  Used by unit tests, the benchmark, and smoke training when
the real SRN zips are absent.  Cameras are placed on a sphere looking at the
origin with SRN-like intrinsics, and images are a deterministic function of
the object id and view angle (a shaded gradient), so two views of the same
"object" are geometrically consistent enough to overfit.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def _look_at(cam_pos: np.ndarray) -> np.ndarray:
    """World-from-camera rotation for a camera at ``cam_pos`` looking at the
    origin (OpenCV convention: +z forward, +y down)."""
    fwd = -cam_pos / np.linalg.norm(cam_pos)
    up = np.array([0.0, 0.0, 1.0])
    if abs(fwd @ up) > 0.99:
        up = np.array([0.0, 1.0, 0.0])
    right = np.cross(fwd, up)
    right /= np.linalg.norm(right)
    down = np.cross(fwd, right)
    return np.stack([right, down, fwd], axis=1)


class SyntheticDataset:
    """``sample(idx, rng)`` matches :class:`SRNDataset`'s contract."""

    def __init__(self, num_objects: int = 8, num_views: int = 16,
                 imgsize: int = 16, seed: int = 0, sample_views: int = 2):
        self.num_objects = num_objects
        self.num_views = num_views
        self.imgsize = imgsize
        self.sample_views = sample_views
        self.ids = list(range(num_objects))   # SRNDataset contract
        s = imgsize
        # SRN-style intrinsics: focal ~ s, principal point at the center.
        self.K = np.array([[s * 1.2, 0.0, s / 2],
                           [0.0, s * 1.2, s / 2],
                           [0.0, 0.0, 1.0]], np.float32)
        rng = np.random.default_rng(seed)
        self._phases = rng.uniform(0, 2 * np.pi, size=(num_objects, 3))

    def __len__(self) -> int:
        return self.num_objects

    def _view(self, obj: int, view: int):
        theta = 2 * np.pi * view / self.num_views
        phi = 0.3 + 0.2 * np.sin(self._phases[obj, 0] + view)
        r = 2.0
        cam = r * np.array([np.cos(theta) * np.cos(phi),
                            np.sin(theta) * np.cos(phi),
                            np.sin(phi)], np.float32)
        R = _look_at(cam).astype(np.float32)
        s = self.imgsize
        yy, xx = np.meshgrid(np.linspace(-1, 1, s), np.linspace(-1, 1, s),
                             indexing="ij")
        ph = self._phases[obj]
        img = np.stack([np.sin(3 * xx + theta + ph[0]),
                        np.cos(2 * yy - theta + ph[1]),
                        np.sin(xx * yy + ph[2] + phi)], axis=-1)
        return img.astype(np.float32), R, cam

    def sample(self, idx: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        views = rng.choice(self.num_views, size=self.sample_views,
                           replace=False)
        imgs, Rs, Ts = zip(*(self._view(idx, v) for v in views))
        return {"imgs": np.stack(imgs), "R": np.stack(Rs),
                "T": np.stack(Ts), "K": self.K}

    def all_views(self, obj: int) -> Dict[str, np.ndarray]:
        imgs, Rs, Ts = zip(*(self._view(obj, v)
                             for v in range(self.num_views)))
        return {"imgs": np.stack(imgs), "R": np.stack(Rs),
                "T": np.stack(Ts), "K": self.K}


def _rays_np(R: np.ndarray, t: np.ndarray, K: np.ndarray, H: int, W: int):
    """Numpy mirror of :func:`diff3d_tpu.geometry.pinhole_rays` (same
    pixel-center + world-from-camera convention; equality is asserted in
    tests/test_data.py so the renderer and the model's conditioning always
    agree on camera geometry)."""
    u = np.arange(W, dtype=np.float64) + 0.5
    v = np.arange(H, dtype=np.float64) + 0.5
    uu, vv = np.meshgrid(u, v)
    px = np.stack([uu, vv, np.ones_like(uu)], axis=-1)        # [H, W, 3]
    dir_cam = np.einsum("ij,hwj->hwi", np.linalg.inv(K), px)
    dirs = np.einsum("ij,hwj->hwi", R, dir_cam)
    dirs /= np.linalg.norm(dirs, axis=-1, keepdims=True)
    pos = np.broadcast_to(t, dirs.shape)
    return pos, dirs


def render_spheres(pos: np.ndarray, dirs: np.ndarray,
                   centers: np.ndarray, radii: np.ndarray,
                   colors: np.ndarray) -> np.ndarray:
    """Lambertian-shaded ray-traced spheres; returns ``[H, W, 3]`` in
    [-1, 1].  Nearest positive ray-sphere intersection wins; misses get a
    view-direction gradient background."""
    oc = pos[None] - centers[:, None, None]                   # [S, H, W, 3]
    b = 2.0 * np.einsum("shwc,hwc->shw", oc, dirs)
    c = np.einsum("shwc,shwc->shw", oc, oc) - radii[:, None, None] ** 2
    disc = b * b - 4.0 * c
    hit = disc > 0
    t_hit = np.where(hit, (-b - np.sqrt(np.maximum(disc, 0.0))) / 2.0,
                     np.inf)
    t_hit = np.where(t_hit > 1e-6, t_hit, np.inf)             # behind cam
    nearest = np.argmin(t_hit, axis=0)                        # [H, W]
    depth = np.take_along_axis(t_hit, nearest[None], axis=0)[0]
    any_hit = np.isfinite(depth)
    depth = np.where(any_hit, depth, 1.0)     # keep the miss math finite

    p = pos + depth[..., None] * dirs                         # hit points
    ctr = centers[nearest]                                    # [H, W, 3]
    n = p - ctr
    n /= np.maximum(np.linalg.norm(n, axis=-1, keepdims=True), 1e-9)
    light = np.array([0.577, 0.577, 0.577])
    lam = 0.35 + 0.65 * np.clip(n @ light, 0.0, 1.0)
    col = colors[nearest] * lam[..., None]

    bg = np.stack([0.15 * dirs[..., 2] - 0.55,
                   0.15 * dirs[..., 2] - 0.45,
                   0.25 * dirs[..., 2] - 0.35], axis=-1)
    img = np.where(any_hit[..., None], col, bg)
    return np.clip(img, -1.0, 1.0).astype(np.float32)


class SyntheticScenesDataset:
    """True-3D procedural dataset: each object is a handful of colored
    spheres, views are ray-traced renders from the SAME pinhole geometry
    the model conditions on.  Unlike :class:`SyntheticDataset`'s angle-
    parameterised patterns, these images ARE projections of a consistent
    3D scene, so novel-view synthesis on them is the real task at toy
    scale — used for the quality-evidence training runs (RESULTS.md) when
    the SRN zips are absent.  Same ``sample``/``all_views`` contract as
    :class:`diff3d_tpu.data.srn.SRNDataset`.
    """

    def __init__(self, num_objects: int = 16, num_views: int = 24,
                 imgsize: int = 64, seed: int = 0, sample_views: int = 2,
                 spheres_per_object: int = 4):
        self.num_objects = num_objects
        self.num_views = num_views
        self.imgsize = imgsize
        self.sample_views = sample_views
        self.ids = list(range(num_objects))   # SRNDataset contract
        s = imgsize
        self.K = np.array([[s * 1.2, 0.0, s / 2],
                           [0.0, s * 1.2, s / 2],
                           [0.0, 0.0, 1.0]], np.float32)
        # Per-object generators keyed (seed, obj): object i's scene is
        # invariant to num_objects, so eval sets of different sizes score
        # the SAME scenes (a single (num_objects, ...) draw would shift
        # every object after a size change).
        n_sph = spheres_per_object
        per_obj = [np.random.default_rng((seed, i))
                   for i in range(num_objects)]
        self._centers = np.stack(
            [r.uniform(-0.55, 0.55, (n_sph, 3)) for r in per_obj])
        self._radii = np.stack(
            [r.uniform(0.18, 0.4, n_sph) for r in per_obj])
        self._colors = np.stack(
            [r.uniform(-0.2, 1.0, (n_sph, 3)) for r in per_obj])
        self._phase = np.array([r.uniform(0, 2 * np.pi) for r in per_obj])

    def __len__(self) -> int:
        return self.num_objects

    def _view(self, obj: int, view: int):
        theta = 2 * np.pi * view / self.num_views + self._phase[obj]
        phi = 0.25 + 0.2 * np.sin(self._phase[obj] + 2.1 * view)
        cam = 2.6 * np.array([np.cos(theta) * np.cos(phi),
                              np.sin(theta) * np.cos(phi),
                              np.sin(phi)])
        R = _look_at(cam)
        pos, dirs = _rays_np(R, cam, self.K.astype(np.float64),
                             self.imgsize, self.imgsize)
        img = render_spheres(pos, dirs, self._centers[obj],
                             self._radii[obj], self._colors[obj])
        return img, R.astype(np.float32), cam.astype(np.float32)

    def sample(self, idx: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        views = rng.choice(self.num_views, size=self.sample_views,
                           replace=False)
        imgs, Rs, Ts = zip(*(self._view(idx, v) for v in views))
        return {"imgs": np.stack(imgs), "R": np.stack(Rs),
                "T": np.stack(Ts), "K": self.K}

    def all_views(self, obj: int) -> Dict[str, np.ndarray]:
        imgs, Rs, Ts = zip(*(self._view(obj, v)
                             for v in range(self.num_views)))
        return {"imgs": np.stack(imgs), "R": np.stack(Rs),
                "T": np.stack(Ts), "K": self.K}
