"""Image quantization at the host->device boundary.

PNG sources are 8-bit, but the reference ships float32 images to the
device (4 bytes/px/channel).  On TPU the host->HBM link (and on this
image's tunneled dev chip, the tunnel itself) is the scarce resource, so
batches cross it as uint8 — 4x less traffic and host RAM — and the
normalization to [-1, 1] runs on-device inside the jitted step, where
XLA fuses it into the first conv for free.

The [-1, 1] float pipeline quantizes to the same 1/127.5 grid the 8-bit
sources came from, so the roundtrip costs at most half a quantization
step (resized pixels land off-grid by < 1/255 — invisible to training).
"""

from __future__ import annotations

import logging
import os

import numpy as np

log = logging.getLogger(__name__)
# warn_state for direct quantize_uint8(imgs) calls (public API default):
# one first-call range check process-wide.
_default_warn_state: dict = {}


def _check_always() -> bool:
    """DIFF3D_CHECK_RANGE=always: range-check EVERY batch (full min/max
    scan) instead of only each loader's first — for debugging data that
    may go out of range mid-run (e.g. a warmup-scheduled augmentation).
    Read per call (os.environ lookup is ~100ns against a min/max scan of
    a multi-MB batch) so flipping the env var mid-process takes effect."""
    return os.environ.get("DIFF3D_CHECK_RANGE", "").lower() == "always"


def quantize_uint8(imgs: np.ndarray, warn_state: dict = None) -> np.ndarray:
    """Host-side ``[-1, 1] float`` -> ``[0, 255] uint8`` (round-to-nearest).

    Inputs are expected in [-1, 1]; anything outside (a future dataset or
    augmentation with wider range / >8-bit precision) would be silently
    clipped and quantized.  ``warn_state`` is a per-caller mutable dict
    (e.g. one per :class:`InfiniteLoader`): the FIRST array it sees is
    range-checked and an out-of-range source logged, then the flag flips
    so steady state pays no min/max scan and one loader's bad data never
    silences another's warning.  Default: a process-wide first-call
    check.  Data that only goes out of range later in a run is NOT
    caught by the first-batch check — set ``DIFF3D_CHECK_RANGE=always``
    to scan every batch, or opt out of uint8 transport per loader with
    ``InfiniteLoader(images_uint8=False)`` for wide-range data.
    """
    imgs = np.asarray(imgs)
    if warn_state is None:
        warn_state = _default_warn_state
    if _check_always() or not warn_state.get("checked"):
        # Benign race under the loader's thread pool: concurrent first
        # calls may each scan (and at worst double-log) — per-loader
        # state just bounds it to that loader's first batch.
        warn_state["checked"] = True
        lo, hi = float(imgs.min()), float(imgs.max())
        if lo < -1.0001 or hi > 1.0001:
            # Warn on the first offence, then only when the violation
            # WORSENS past the previously warned extremes: a steady
            # out-of-range stream logs once, but data drifting further
            # out mid-run (always-mode's stated use case) keeps
            # signalling instead of being latched silent (ADVICE r4).
            worst_lo = warn_state.get("warned_lo", -1.0)
            worst_hi = warn_state.get("warned_hi", 1.0)
            if lo < worst_lo - 1e-6 or hi > worst_hi + 1e-6:
                warn_state["warned_lo"] = min(lo, worst_lo)
                warn_state["warned_hi"] = max(hi, worst_hi)
                log.warning(
                    "quantize_uint8: input range [%.3f, %.3f] exceeds "
                    "[-1, 1]; values will be clipped (pass "
                    "images_uint8=False to the loader to keep full "
                    "precision)", lo, hi)
    return np.clip((imgs + 1.0) * 127.5 + 0.5, 0, 255).astype(np.uint8)


def dequantize(imgs):
    """``uint8 [0, 255]`` -> ``float32 [-1, 1]``; float inputs pass through.

    jnp- and np-compatible (dtype dispatch is static under jit), so it is
    safe inside compiled train/eval steps.
    """
    if imgs.dtype == np.uint8:
        return imgs.astype(np.float32) / 127.5 - 1.0
    return imgs
