"""Image quantization at the host->device boundary.

PNG sources are 8-bit, but the reference ships float32 images to the
device (4 bytes/px/channel).  On TPU the host->HBM link (and on this
image's tunneled dev chip, the tunnel itself) is the scarce resource, so
batches cross it as uint8 — 4x less traffic and host RAM — and the
normalization to [-1, 1] runs on-device inside the jitted step, where
XLA fuses it into the first conv for free.

The [-1, 1] float pipeline quantizes to the same 1/127.5 grid the 8-bit
sources came from, so the roundtrip costs at most half a quantization
step (resized pixels land off-grid by < 1/255 — invisible to training).
"""

from __future__ import annotations

import logging

import numpy as np

log = logging.getLogger(__name__)
_warned_out_of_range = False


def quantize_uint8(imgs: np.ndarray) -> np.ndarray:
    """Host-side ``[-1, 1] float`` -> ``[0, 255] uint8`` (round-to-nearest).

    Inputs are expected in [-1, 1]; anything outside (a future dataset or
    augmentation with wider range / >8-bit precision) would be silently
    clipped and quantized, so the first offending batch is logged.  Opt out
    of uint8 transport per loader with ``InfiniteLoader(images_uint8=
    False)`` for such data.
    """
    imgs = np.asarray(imgs)
    global _warned_out_of_range
    if not _warned_out_of_range:
        lo, hi = float(imgs.min()), float(imgs.max())
        if lo < -1.0001 or hi > 1.0001:
            _warned_out_of_range = True
            log.warning(
                "quantize_uint8: input range [%.3f, %.3f] exceeds [-1, 1]; "
                "values will be clipped (pass images_uint8=False to the "
                "loader to keep full precision)", lo, hi)
    return np.clip((imgs + 1.0) * 127.5 + 0.5, 0, 255).astype(np.uint8)


def dequantize(imgs):
    """``uint8 [0, 255]`` -> ``float32 [-1, 1]``; float inputs pass through.

    jnp- and np-compatible (dtype dispatch is static under jit), so it is
    safe inside compiled train/eval steps.
    """
    if imgs.dtype == np.uint8:
        return imgs.astype(np.float32) / 127.5 - 1.0
    return imgs
