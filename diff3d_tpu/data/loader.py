"""Infinite, per-host-sharded batch loader with device prefetch.

Replaces the reference's ``MultiEpochsDataLoader`` + ``_RepeatSampler``
(``SRNdataset.py:12-40``, persistent workers that yield forever) and its
broken ``DistributedSampler`` usage (``train.py:224-226``, see SURVEY.md
§2.7).  TPU-native design:

  * each host draws its own disjoint slice of the global batch, derived
    deterministically from ``(seed, step, global_slot)`` — no sampler state
    to synchronise and resume is exact: seek to any step by number;
  * **elasticity determinism rule**: the global batch stream is a pure
    function of ``(seed, step)`` alone — host ``h`` of ``H`` takes global
    slots ``[h*B, (h+1)*B)`` of a per-step draw of ``H*B`` global slots.
    Re-partitioning the same global batch across a *different* host count
    (with the per-host batch size rescaled so ``H*B`` is constant) yields
    the identical global stream, so an elastic re-mesh neither replays
    nor skips examples;
  * a thread pool overlaps image decode with device compute;
  * :func:`prefetch_to_device` keeps ``depth`` batches in flight as sharded
    device arrays (the JAX equivalent of pinned-memory prefetch).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, Optional

import numpy as np

from diff3d_tpu.data.images import quantize_uint8


def _collate(samples) -> Dict[str, np.ndarray]:
    return {k: np.stack([s[k] for s in samples]) for k in samples[0]}


class InfiniteLoader:
    """Yields ``{'imgs':[B,V,H,W,3], 'R':[B,V,3,3], 'T':[B,V,3], 'K':[B,3,3]}``
    forever, ``B`` = per-host batch size.

    Sampling is stateless-per-step: the *global* batch ``n`` is a pure
    function of ``(seed, n)`` and host ``h`` takes global slots
    ``[h*B, (h+1)*B)`` of it, so checkpoint resume replays the exact data
    order without any loader state (the reference's resume restores only
    the step counter, ``train.py:244-251``) and an elastic host-count
    change re-derives the same global stream under the new partition.
    """

    def __init__(self, dataset, batch_size: int, *, seed: int = 0,
                 host_id: int = 0, num_hosts: int = 1,
                 num_workers: int = 8, start_step: int = 0,
                 images_uint8: bool = True, sample_mode: str = "iid"):
        """``sample_mode``:

        * ``'iid'`` (default, training) — objects drawn independently with
          replacement per slot;
        * ``'permute'`` — without-replacement epoch permutations: global
          draw ``g = step * global_batch + global_slot`` indexes a
          per-epoch shuffle of the dataset, so every object is seen
          exactly once per ``len(dataset)`` consecutive global draws (the
          reference's epoch semantics, ``SRNdataset.py:12-40``) while
          staying a pure function of ``(seed, step, global_slot)``.
          Default for val loaders — no double-counted objects in small
          val splits.
        """
        if sample_mode not in ("iid", "permute"):
            raise ValueError(f"unknown sample_mode {sample_mode!r}")
        self.dataset = dataset
        self.batch_size = batch_size
        self.seed = seed
        self.host_id = host_id
        self.num_hosts = num_hosts
        self.images_uint8 = images_uint8
        self.sample_mode = sample_mode
        self._step = start_step
        self._quant_warn: Dict[str, bool] = {}   # see quantize_uint8
        self._perm_cache: Dict[int, np.ndarray] = {}
        self._pool = (ThreadPoolExecutor(num_workers)
                      if num_workers > 0 else None)

    # rng-lineage: stream(epoch permutation: SeedSequence entropy=(seed,
    # 0x7065726D) spawn_key=(epoch,) — entropy-disjoint from _batch's
    # per-sample tree, identical on every host)
    def _epoch_perm(self, epoch: int) -> np.ndarray:
        perm = self._perm_cache.get(epoch)
        if perm is None:
            # Distinct ENTROPY (not just spawn_key) from the per-sample
            # streams: _batch's root spawn((step,)) children are
            # (step, global_slot) keys over entropy=seed, so any key-only
            # scheme could collide (spawn appends a child index).  The
            # permutation is shared by all hosts.
            rng = np.random.default_rng(np.random.SeedSequence(
                entropy=(self.seed, 0x7065726D), spawn_key=(epoch,)))
            perm = rng.permutation(len(self.dataset))
            self._perm_cache[epoch] = perm
            for old in sorted(self._perm_cache)[:-4]:
                del self._perm_cache[old]
        return perm

    # rng-lineage: stream(global-batch seed tree: SeedSequence
    # entropy=seed spawn_key=(step,) spawned once per GLOBAL slot, host
    # takes slots [host_id*B, host_id*B+B) — the stream is a pure
    # function of (seed, step, global_slot), pinned by the 'loader'
    # manifest under runs/rngcheck/)
    def _batch(self, step: int) -> Dict[str, np.ndarray]:
        # Elasticity determinism: spawn the *global* batch's seed streams
        # (spawn_key depends on step only) and slice this host's
        # contiguous slot range.  Any (host_id, num_hosts) partition of
        # the same global batch size reproduces the identical global
        # stream, so a re-mesh resumes without replaying or skipping.
        global_batch = self.batch_size * self.num_hosts
        lo = self.host_id * self.batch_size
        root = np.random.SeedSequence(entropy=self.seed, spawn_key=(step,))
        seqs = root.spawn(global_batch)[lo:lo + self.batch_size]
        n = len(self.dataset)

        if self.sample_mode == "permute":
            g0 = step * global_batch + lo
            idxs = [int(self._epoch_perm((g0 + b) // n)[(g0 + b) % n])
                    for b in range(self.batch_size)]
        else:
            idxs = [None] * self.batch_size

        def one(args):
            idx, seq = args
            rng = np.random.default_rng(seq)
            if idx is None:
                idx = int(rng.integers(n))
            s = self.dataset.sample(idx, rng)
            if (self.images_uint8 and "imgs" in s
                    and s["imgs"].dtype != np.uint8):
                # Per sample, inside the worker pool: the batch stacks
                # directly as uint8 (4x less host RAM and host->device
                # traffic; see data/images.py) and the conversion
                # parallelizes across workers.  The jitted step
                # dequantizes on device.
                s = dict(s, imgs=quantize_uint8(s["imgs"],
                                                self._quant_warn))
            return s

        if self._pool is not None:
            samples = list(self._pool.map(one, zip(idxs, seqs)))
        else:
            samples = [one(a) for a in zip(idxs, seqs)]
        return _collate(samples)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        batch = self._batch(self._step)
        self._step += 1
        return batch


def prefetch_to_device(it: Iterator, sharding=None, depth: int = 2,
                       to_device: bool = True) -> Iterator:
    """Runs ``it`` in a background thread, keeping ``depth`` batches ahead;
    each batch is ``jax.device_put`` with ``sharding`` (a NamedSharding with
    the batch axis on the mesh's data axis) so the global array lands
    already sharded."""
    import jax

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _SENTINEL = object()
    error: list = []

    def producer():
        try:
            from diff3d_tpu.parallel.multihost import shard_host_local

            for batch in it:
                if stop.is_set():
                    return
                if to_device:
                    # Multi-host: each host's local slice becomes its
                    # shards of ONE global array (make_array_from_
                    # process_local_data); single-host: plain device_put.
                    batch = shard_host_local(batch, sharding)
                q.put(batch)
        except BaseException as e:  # surface on the consumer side
            error.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True)
    t.start()

    class _Prefetcher:
        def __iter__(self):
            return self

        def __next__(self):
            item = q.get()
            if item is _SENTINEL:
                if error:
                    raise error[0]
                raise StopIteration
            return item

        def close(self):
            stop.set()
            while True:  # drain so the producer can observe `stop`
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    return _Prefetcher()
