"""Infinite, per-host-sharded batch loader with device prefetch.

Replaces the reference's ``MultiEpochsDataLoader`` + ``_RepeatSampler``
(``SRNdataset.py:12-40``, persistent workers that yield forever) and its
broken ``DistributedSampler`` usage (``train.py:224-226``, see SURVEY.md
§2.7).  TPU-native design:

  * each host draws its own disjoint slice of the global batch, derived
    deterministically from ``(seed, step, host_id)`` — no sampler state to
    synchronise and resume is exact: seek to any step by number;
  * a thread pool overlaps image decode with device compute;
  * :func:`prefetch_to_device` keeps ``depth`` batches in flight as sharded
    device arrays (the JAX equivalent of pinned-memory prefetch).
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, Optional

import numpy as np

from diff3d_tpu.data.images import quantize_uint8


def _collate(samples) -> Dict[str, np.ndarray]:
    return {k: np.stack([s[k] for s in samples]) for k in samples[0]}


class InfiniteLoader:
    """Yields ``{'imgs':[B,V,H,W,3], 'R':[B,V,3,3], 'T':[B,V,3], 'K':[B,3,3]}``
    forever, ``B`` = per-host batch size.

    Sampling is stateless-per-step: batch ``n`` on host ``h`` is a pure
    function of ``(seed, n, h)``, so checkpoint resume replays the exact
    data order without any loader state (the reference's resume restores
    only the step counter, ``train.py:244-251``).
    """

    def __init__(self, dataset, batch_size: int, *, seed: int = 0,
                 host_id: int = 0, num_hosts: int = 1,
                 num_workers: int = 8, start_step: int = 0,
                 images_uint8: bool = True):
        self.dataset = dataset
        self.batch_size = batch_size
        self.seed = seed
        self.host_id = host_id
        self.num_hosts = num_hosts
        self.images_uint8 = images_uint8
        self._step = start_step
        self._pool = (ThreadPoolExecutor(num_workers)
                      if num_workers > 0 else None)

    def _batch(self, step: int) -> Dict[str, np.ndarray]:
        root = np.random.SeedSequence(
            entropy=self.seed, spawn_key=(step, self.host_id))
        seqs = root.spawn(self.batch_size)
        n = len(self.dataset)

        def one(seq):
            rng = np.random.default_rng(seq)
            s = self.dataset.sample(int(rng.integers(n)), rng)
            if (self.images_uint8 and "imgs" in s
                    and s["imgs"].dtype != np.uint8):
                # Per sample, inside the worker pool: the batch stacks
                # directly as uint8 (4x less host RAM and host->device
                # traffic; see data/images.py) and the conversion
                # parallelizes across workers.  The jitted step
                # dequantizes on device.
                s = dict(s, imgs=quantize_uint8(s["imgs"]))
            return s

        if self._pool is not None:
            samples = list(self._pool.map(one, seqs))
        else:
            samples = [one(s) for s in seqs]
        return _collate(samples)

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        batch = self._batch(self._step)
        self._step += 1
        return batch


def prefetch_to_device(it: Iterator, sharding=None, depth: int = 2,
                       to_device: bool = True) -> Iterator:
    """Runs ``it`` in a background thread, keeping ``depth`` batches ahead;
    each batch is ``jax.device_put`` with ``sharding`` (a NamedSharding with
    the batch axis on the mesh's data axis) so the global array lands
    already sharded."""
    import jax

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()
    _SENTINEL = object()
    error: list = []

    def producer():
        try:
            from diff3d_tpu.parallel.multihost import shard_host_local

            for batch in it:
                if stop.is_set():
                    return
                if to_device:
                    # Multi-host: each host's local slice becomes its
                    # shards of ONE global array (make_array_from_
                    # process_local_data); single-host: plain device_put.
                    batch = shard_host_local(batch, sharding)
                q.put(batch)
        except BaseException as e:  # surface on the consumer side
            error.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=producer, daemon=True)
    t.start()

    class _Prefetcher:
        def __iter__(self):
            return self

        def __next__(self):
            item = q.get()
            if item is _SENTINEL:
                if error:
                    raise error[0]
                raise StopIteration
            return item

        def close(self):
            stop.set()
            while True:  # drain so the producer can observe `stop`
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

    return _Prefetcher()
