from diff3d_tpu.data.images import dequantize, quantize_uint8
from diff3d_tpu.data.loader import InfiniteLoader, prefetch_to_device
from diff3d_tpu.data.srn import (SRNDataset, build_index, load_intrinsics,
                                 load_object_views, load_pose, split_ids)
from diff3d_tpu.data.synthetic import (SyntheticDataset,
                                       SyntheticScenesDataset)

__all__ = [
    "SRNDataset", "build_index", "load_intrinsics", "load_object_views",
    "load_pose", "split_ids",
    "InfiniteLoader", "prefetch_to_device", "SyntheticDataset",
    "SyntheticScenesDataset",
    "dequantize", "quantize_uint8",
]
