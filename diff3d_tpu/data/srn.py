"""SRN Cars/Chairs dataset (ShapeNet renders), TPU-native layout.

Capability parity with the reference's ``SRNdataset.py:42-95``:

  * an index maps object-id -> list of view png filenames.  The reference
    ships this as ``data/{cars,chairs}.pickle``; :func:`build_index`
    regenerates it by globbing ``<path>/<obj>/rgb/*.png`` when the pickle is
    absent (the repo's pickles are stripped from the mount,
    ``.MISSING_LARGE_BLOBS``), and loads/saves the same pickle format.
  * deterministic 90/10 train/val split: ``random.seed(0)`` + shuffle of the
    sorted ids (``SRNdataset.py:50-57``).
  * a sample is 2 random views of one object: image resized to ``imgsize``,
    scaled to [-1, 1], first 3 channels; pose ``4x4`` txt -> ``R [3,3]``,
    ``T [3]``; one shared ``3x3`` intrinsics K read from the first view's
    txt (``SRNdataset.py:64-93``).

Differences by design: images are **NHWC** float32 (TPU-native; reference is
CHW), and sampling takes an explicit ``numpy.random.Generator`` so the
pipeline is reproducible and per-host shardable (the reference uses the
global ``random`` module).
"""

from __future__ import annotations

import os
import pickle
import random
from typing import Dict, List, Sequence, Tuple

import numpy as np

try:  # PIL ships with the image; gate anyway so array-only use works.
    from PIL import Image
    _HAVE_PIL = True
except ImportError:  # pragma: no cover
    _HAVE_PIL = False

from diff3d_tpu import native


def build_index(path: str, picklefile: str | None = None,
                save: bool = False) -> Dict[str, List[str]]:
    """Load or regenerate the object-id -> view-filename index.

    If ``picklefile`` exists it is loaded (reference format: dict of id ->
    list of png basenames, ``SRNdataset.py:48``).  Otherwise the index is
    rebuilt by globbing ``<path>/<obj>/rgb/*.png`` and optionally saved back
    to ``picklefile``.
    """
    if picklefile and os.path.exists(picklefile):
        with open(picklefile, "rb") as f:
            return pickle.load(f)
    index: Dict[str, List[str]] = {}
    for obj in sorted(os.listdir(path)):
        rgb = os.path.join(path, obj, "rgb")
        if not os.path.isdir(rgb):
            continue
        views = sorted(f for f in os.listdir(rgb) if f.endswith(".png"))
        if views:
            index[obj] = views
    if not index:
        raise FileNotFoundError(f"no SRN objects under {path}")
    if save and picklefile:
        os.makedirs(os.path.dirname(picklefile) or ".", exist_ok=True)
        with open(picklefile, "wb") as f:
            pickle.dump(index, f)
    return index


def split_ids(ids: Sequence[str], split: str, seed: int = 0,
              train_fraction: float = 0.9) -> List[str]:
    """Reference split semantics (``SRNdataset.py:50-57``): seed the stdlib
    RNG, shuffle the sorted ids, first 90% train / rest val."""
    allthevid = sorted(ids)
    rng = random.Random(seed)
    rng.shuffle(allthevid)
    cut = int(len(allthevid) * train_fraction)
    return allthevid[:cut] if split == "train" else allthevid[cut:]


def load_pose(path: str) -> Tuple[np.ndarray, np.ndarray]:
    """``pose/<view>.txt`` holds a flat 4x4 world-from-camera matrix;
    returns ``(R [3,3], T [3])`` (``SRNdataset.py:86-93``)."""
    mat = np.loadtxt(path).reshape(4, 4)
    return mat[:3, :3], mat[:3, 3]


def load_intrinsics(path: str) -> np.ndarray:
    """``intrinsics/<view>.txt`` holds a flat 3x3 K (``SRNdataset.py:68-69``)."""
    return np.loadtxt(path).reshape(3, 3)


def _decode_image(img, imgsize: int) -> np.ndarray:
    """PIL image -> ``[s, s, 3] float32`` in [-1, 1] (resize, grayscale
    promotion, alpha drop — reference ``SRNdataset.py:76-83``).  BOX
    (area-average) resampling, matching the native C++ decoder exactly."""
    if img.size != (imgsize, imgsize):
        img = img.resize((imgsize, imgsize), Image.BOX)
    arr = np.asarray(img, np.float32) / 255.0 * 2.0 - 1.0
    if arr.ndim == 2:
        arr = np.repeat(arr[..., None], 3, axis=-1)
    return arr[..., :3]


def load_view_image(path: str, imgsize: int,
                    use_native: bool = True) -> np.ndarray:
    """One view png -> ``[s, s, 3] float32`` in [-1, 1].  Routes through the
    C++ decoder (:mod:`diff3d_tpu.native`) when available — ctypes releases
    the GIL for the native call, so loader threads decode truly in parallel
    — else the PIL path."""
    if use_native and native.available():
        return native.decode_image(path, imgsize)
    if not _HAVE_PIL:
        raise RuntimeError("neither native decoder nor PIL available")
    return _decode_image(Image.open(path), imgsize)


def decode_view_batch(paths: Sequence[str], imgsize: int,
                      use_native: bool = True) -> np.ndarray:
    """``[N, s, s, 3]`` for N view pngs.  One call into the shared C++
    worker pool (GIL-free, decodes in parallel) when available; PIL loop
    otherwise."""
    if use_native:
        pool = native.shared_pool()
        if pool is not None:
            return pool.decode_batch(list(paths), imgsize)
    return np.stack([load_view_image(p, imgsize, use_native=False)
                     for p in paths])


def load_object_views(object_dir: str, imgsize: int = 64
                      ) -> Dict[str, np.ndarray]:
    """Every view of one SRN object dir (``rgb/ pose/ intrinsics/``) — what
    the reference sampler loads for its autoregressive loop
    (``sampling.py:26-48``)."""
    rgb = os.path.join(object_dir, "rgb")
    views = sorted(f for f in os.listdir(rgb) if f.endswith(".png"))
    if not views:
        raise FileNotFoundError(f"no views under {rgb}")
    imgs = decode_view_batch([os.path.join(rgb, v) for v in views], imgsize)
    Rs, Ts = [], []
    for v in views:
        R, T = load_pose(os.path.join(object_dir, "pose", v[:-4] + ".txt"))
        Rs.append(R.astype(np.float32))
        Ts.append(T.astype(np.float32))
    K = load_intrinsics(os.path.join(object_dir, "intrinsics",
                                     views[0][:-4] + ".txt"))
    return {"imgs": imgs, "R": np.stack(Rs), "T": np.stack(Ts),
            "K": K.astype(np.float32)}


class SRNDataset:
    """Map-style two-view dataset over SRN objects.

    ``sample(idx, rng)`` returns a dict with ``imgs [2, s, s, 3] f32`` in
    [-1, 1] NHWC, ``R [2, 3, 3] f32``, ``T [2, 3] f32``, ``K [3, 3] f32``.
    """

    def __init__(self, split: str, path: str, picklefile: str | None = None,
                 imgsize: int = 64, split_seed: int = 0,
                 train_fraction: float = 0.9, num_views: int = 2,
                 use_native: bool = True):
        if not _HAVE_PIL and not (use_native and native.available()):
            raise RuntimeError("PIL required for SRNDataset image loading")
        self.path = path
        self.imgsize = imgsize
        self.num_views = num_views
        self.use_native = use_native
        self.index = build_index(path, picklefile)
        self.ids = split_ids(list(self.index.keys()), split, split_seed,
                             train_fraction)
        if not self.ids:
            raise ValueError(f"empty split {split!r}")

    def __len__(self) -> int:
        return len(self.ids)

    def _load_views(self, obj: str, names: Sequence[str]
                    ) -> Dict[str, np.ndarray]:
        imgs = decode_view_batch(
            [os.path.join(self.path, obj, "rgb", v) for v in names],
            self.imgsize, use_native=self.use_native)
        Rs, Ts = zip(*(load_pose(
            os.path.join(self.path, obj, "pose", v[:-4] + ".txt"))
            for v in names))
        K = load_intrinsics(os.path.join(
            self.path, obj, "intrinsics", self.index[obj][0][:-4] + ".txt"))
        return {
            "imgs": imgs.astype(np.float32),
            "R": np.stack(Rs).astype(np.float32),
            "T": np.stack(Ts).astype(np.float32),
            "K": K.astype(np.float32),
        }

    def sample(self, idx: int, rng: np.random.Generator) -> Dict[str, np.ndarray]:
        obj = self.ids[idx]
        views = self.index[obj]
        chosen = rng.choice(len(views), size=self.num_views, replace=False)
        return self._load_views(obj, [views[i] for i in chosen])

    def all_views(self, obj: str) -> Dict[str, np.ndarray]:
        """Every view of one object, for the sampler's autoregressive loop
        (reference ``sampling.py:26-48`` loads the whole target dir)."""
        return self._load_views(obj, self.index[obj])
