"""rngflow: the linear-key dataflow core behind GL101 and rngcheck.

JAX PRNG keys are **linear resources**: each key is either *derived
from* (``split`` / ``fold_in``) or *consumed by* (a draw) exactly once
— reusing a key replays its stream, and every determinism contract in
this repo (the ancestral-256 bit-parity oracle, the chunked carried-RNG
schedule independence, the elastic consumed-batch-stream invariant)
sits on top of that discipline.  This module is the shared machinery:

  * the **single-scope linear scanner** — per function, source-ordered
    consume/store events over plain-name keys, exactly graftlint
    GL101's model (a re-store re-arms the carry: ``rng, k = split(rng)``
    stays silent).  GL101 is now a thin alias over
    :func:`linear_violations` with no call graph, so the fast path and
    rngcheck's RC501/RC502 can never disagree on the shared cases;
  * the **program graph** — every ``def`` in the analyzed file set with
    an interprocedural *consumes* summary computed to fixpoint: a
    function consumes a key parameter if its body (or anything it
    passes the key to, across modules) draws from it before rebinding
    it.  Call resolution is conservative: exact for same-module defs
    and ``from diff3d_tpu...`` imports, bare-name with
    all-candidates-must-agree otherwise, silent for anything ambiguous;
  * the **lineage annotation grammar** — ``# rng-lineage:`` trailing
    comments on a ``def`` header declaring key params and overriding
    the inferred summary (``keys(...)``, ``not-keys(...)``,
    ``consumes(...)``, ``passthrough(...)``) plus free-text
    ``stream(...)`` docs for derivation schemes the dataflow cannot
    see (numpy ``SeedSequence`` trees, teacher/student splits);
  * the **runtime witness** (:func:`install_rng_witness`) — wraps the
    key-consuming ``jax.random`` entry points so a trace (``.lower``)
    or an eager run records an ordered stream of key-derivation events
    and per-key consumption counts; a key consumed twice is a recorded
    violation.  The ordered event list digests into the per-program
    stream manifests committed under ``runs/rngcheck/``;
  * the **loader stream probe** — drives the real
    :class:`~diff3d_tpu.data.loader.InfiniteLoader` seed-derivation
    path (numpy ``SeedSequence`` spawn tree + epoch permutations) on a
    stub dataset and digests the drawn streams, so the elastic
    "global batch is a pure function of (seed, step)" invariant is
    pinned by manifest too.

No ``jax`` import at module level: graftlint (pure AST, used in
editors) imports this file; everything runtime lives behind lazy
imports.
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from diff3d_tpu.analysis.rules.context import (ModuleContext, dotted_name,
                                               param_names)

#: jax.random attrs that do NOT consume their key argument.  ``split``
#: is deliberately absent: the *parent* of a split is spent (reusing it
#: replays the children) — that is RC502's whole subject.
NON_CONSUMING = {"PRNGKey", "key", "fold_in", "key_data",
                 "wrap_key_data", "key_impl", "clone",
                 "default_prng_impl"}

#: jax.random attrs that derive new keys from a parent (assignment
#: targets of these calls are tracked as derived keys for RC503).
DERIVING = {"split", "fold_in", "PRNGKey", "key", "clone"}

#: Dotted-name roots whose calls never consume a key linearly (library
#: namespaces the repo treats as value-semantics).  ``jax.random`` draws
#: are recognised *before* this set applies.
SAFE_CALL_ROOTS = {
    "jax", "jnp", "np", "numpy", "lax", "math", "os", "sys", "json",
    "time", "optax", "flax", "nn", "chex", "functools", "itertools",
    "logging", "threading", "queue", "ast", "re", "dataclasses",
    "collections", "einops",
}

#: Builtin callables that never consume a key.
SAFE_BUILTINS = {
    "print", "len", "int", "float", "str", "bool", "list", "tuple",
    "dict", "set", "frozenset", "sorted", "min", "max", "abs", "sum",
    "isinstance", "issubclass", "repr", "zip", "enumerate", "range",
    "map", "filter", "getattr", "setattr", "hasattr", "id", "type",
    "iter", "next", "vars", "format", "hash",
}

#: Parameter names classified as PRNG keys by convention.
KEY_NAME_RE = re.compile(
    r"^(rngs?|keys?|k\d*|k_\w+|\w*_rngs?|\w*_keys?)$")


def is_key_name(name: str) -> bool:
    return bool(KEY_NAME_RE.match(name))


# ---------------------------------------------------------------------
# lineage annotations
# ---------------------------------------------------------------------

ANNOT_RE = re.compile(r"#\s*rng-lineage:\s*(.*)$")
_DIRECTIVE_HEAD_RE = re.compile(r"\s*([A-Za-z][\w-]*)\s*\(")

#: directive -> takes a name list (True) or free text (False).
_DIRECTIVES = {"keys": True, "not-keys": True, "consumes": True,
               "passthrough": True, "stream": False}


@dataclasses.dataclass
class LineageAnnotations:
    """Parsed ``# rng-lineage:`` directives for one function."""

    keys: Set[str] = dataclasses.field(default_factory=set)
    not_keys: Set[str] = dataclasses.field(default_factory=set)
    consumes: Set[str] = dataclasses.field(default_factory=set)
    passthrough: Set[str] = dataclasses.field(default_factory=set)
    streams: List[str] = dataclasses.field(default_factory=list)
    #: (lineno, message) pairs for malformed directives (RC003).
    errors: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list)

    def __bool__(self) -> bool:
        return bool(self.keys or self.not_keys or self.consumes
                    or self.passthrough or self.streams or self.errors)


def _parse_directives(spec: str, lineno: int,
                      out: LineageAnnotations) -> None:
    pos = 0
    while pos < len(spec):
        m = _DIRECTIVE_HEAD_RE.match(spec, pos)
        if not m:
            rest = spec[pos:].strip()
            if rest:
                out.errors.append(
                    (lineno, f"unparseable rng-lineage text {rest!r} — "
                             f"expected directive(...) tokens"))
            return
        directive = m.group(1)
        # Balanced-paren argument (free text may nest parens).
        depth, start = 0, m.end()
        arg, end = None, None
        for i in range(m.end() - 1, len(spec)):
            if spec[i] == "(":
                depth += 1
            elif spec[i] == ")":
                depth -= 1
                if depth == 0:
                    arg, end = spec[start:i], i + 1
                    break
        if arg is None:
            arg, end = spec[start:], len(spec)
        pos = end
        if directive not in _DIRECTIVES:
            out.errors.append(
                (lineno, f"unknown rng-lineage directive "
                         f"'{directive}' — one of "
                         f"{sorted(_DIRECTIVES)}"))
            continue
        if _DIRECTIVES[directive]:
            names = {n.strip() for n in arg.split(",") if n.strip()}
            bad = {n for n in names if not n.isidentifier()}
            if bad or not names:
                out.errors.append(
                    (lineno, f"rng-lineage {directive}(...) needs a "
                             f"comma-separated identifier list, got "
                             f"{arg.strip()!r}"))
                continue
            attr = directive.replace("-", "_")
            getattr(out, attr).update(names)
        else:
            text = arg.strip()
            if not text:
                out.errors.append(
                    (lineno, "rng-lineage stream(...) is empty — "
                             "describe the derivation scheme"))
                continue
            out.streams.append(text)


def parse_lineage_annotations(ctx: ModuleContext,
                              fn: ast.AST) -> LineageAnnotations:
    """Directives on the ``def`` header lines (trailing comments on
    the signature, which may span several lines) and in the contiguous
    comment block immediately above the def/decorators."""
    out = LineageAnnotations()
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return out
    first = min([fn.lineno] + [d.lineno for d in fn.decorator_list])
    above = first - 1
    while above >= 1 and ctx.lines[above - 1].strip().startswith("#"):
        above -= 1
    first_body = fn.body[0].lineno if fn.body else fn.lineno + 1
    for lineno in range(above + 1, first_body):
        if lineno - 1 >= len(ctx.lines):
            break
        m = ANNOT_RE.search(ctx.lines[lineno - 1])
        if m:
            _parse_directives(m.group(1), lineno, out)
    return out


# ---------------------------------------------------------------------
# single-scope linear scanner (shared GL101 / RC501 / RC502 core)
# ---------------------------------------------------------------------


@dataclasses.dataclass
class Violation:
    """One linearity violation: ``name`` consumed at ``node`` although
    already consumed at ``prev_line`` (by a ``prev_kind`` event)."""

    node: ast.AST
    name: str
    prev_line: int
    prev_kind: str   # "draw" | "split" | "call"
    kind: str        # the second consumption's kind
    detail: str = ""  # callee name for "call" events


def consuming_random_call(ctx: ModuleContext,
                          node: ast.Call) -> Tuple[str, str]:
    """``(key_name, kind)`` for a consuming ``jax.random`` call with a
    plain-name first argument, else ``("", "")``.  ``kind`` is
    ``"split"`` for split, ``"draw"`` otherwise."""
    if not isinstance(node.func, ast.Attribute):
        return "", ""
    base = dotted_name(node.func.value)
    if base not in ctx.random_aliases:
        return "", ""
    if node.func.attr in NON_CONSUMING:
        return "", ""
    if not node.args:
        return "", ""
    first = node.args[0]
    if not isinstance(first, ast.Name):
        return "", ""
    kind = "split" if node.func.attr == "split" else "draw"
    return first.id, kind


def _scope_key(ctx: ModuleContext, node: ast.AST) -> int:
    fn = ctx.enclosing_function(node)
    return id(fn) if fn is not None else 0


def collect_scope_events(
        ctx: ModuleContext,
        graph: Optional["ProgramGraph"] = None,
) -> Dict[int, List[Tuple[Tuple[int, int], str, str, ast.AST, str]]]:
    """Source-ordered key events grouped by enclosing function scope
    (0 = module scope).  Events: ``(pos, kind, name, node, detail)``
    with kind in {store, draw, split, call}.  ``graph`` enables the
    interprocedural ``call`` consume events (a plain-name argument
    handed to a resolved callee whose summary consumes that
    parameter)."""
    scopes: Dict[int, List[Tuple[Tuple[int, int], str, str,
                                 ast.AST, str]]] = {}

    def add(node, pos, kind, name, detail=""):
        scopes.setdefault(_scope_key(ctx, node), []).append(
            (pos, kind, name, node, detail))

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            name, kind = consuming_random_call(ctx, node)
            if name:
                add(node, (node.lineno, node.col_offset + 1), kind, name)
                continue
            if graph is not None:
                for arg_name, callee in graph.consuming_call_args(
                        ctx, node):
                    add(node, (node.lineno, node.col_offset + 1),
                        "call", arg_name, callee)
        elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Store):
            # Stores get a line-end bias so `rng, k = split(rng)`
            # re-arms rng (consume sorts before the same-line store).
            add(node, (node.lineno, 10_000), "store", node.id)
    for events in scopes.values():
        events.sort(key=lambda e: e[0])
    return scopes


def linear_violations(
        ctx: ModuleContext,
        graph: Optional["ProgramGraph"] = None,
        scopes: Optional[dict] = None) -> Iterator[Violation]:
    """The linear-resource scan: a second consumption of a name with no
    re-store in between is a violation.  Same continue-counting as the
    original GL101 (each extra consumption reports once)."""
    if scopes is None:
        scopes = collect_scope_events(ctx, graph)
    for events in scopes.values():
        consumed_at: Dict[str, Tuple[int, str]] = {}
        for _, kind, name, node, detail in events:
            if kind == "store":
                consumed_at.pop(name, None)
            elif name in consumed_at:
                prev_line, prev_kind = consumed_at[name]
                yield Violation(node=node, name=name,
                                prev_line=prev_line,
                                prev_kind=prev_kind, kind=kind,
                                detail=detail)
                consumed_at[name] = (node.lineno, kind)
            else:
                consumed_at[name] = (node.lineno, kind)


def dead_derived_keys(
        ctx: ModuleContext) -> Iterator[Tuple[ast.AST, str]]:
    """Derived-but-never-used keys: a name assigned from a deriving
    ``jax.random`` call (split / fold_in / PRNGKey / key) that is never
    loaded anywhere else in its function (nested closures count as
    use).  ``_``-prefixed names are sanctioned discards."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Attribute)
                and dotted_name(value.func.value) in ctx.random_aliases
                and value.func.attr in DERIVING):
            continue
        targets: List[ast.Name] = []
        for t in node.targets:
            if isinstance(t, ast.Name):
                targets.append(t)
            elif isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(e for e in t.elts
                               if isinstance(e, ast.Name))
        if not targets:
            continue
        scope = ctx.enclosing_function(node) or ctx.tree
        in_value = {id(n) for n in ast.walk(value)}
        for target in targets:
            name = target.id
            if name.startswith("_"):
                continue
            used = False
            for other in ast.walk(scope):
                if (isinstance(other, ast.Name) and other.id == name
                        and isinstance(other.ctx, ast.Load)
                        and id(other) not in in_value):
                    used = True
                    break
            if not used:
                yield target, name


# ---------------------------------------------------------------------
# program graph (interprocedural consumes-summary fixpoint)
# ---------------------------------------------------------------------


@dataclasses.dataclass
class FunctionSummary:
    """One ``def`` in the analyzed file set."""

    path: str
    module: str            # dotted module name ("" outside the package)
    name: str
    qualname: str
    lineno: int
    params: Tuple[str, ...]          # positional, self/cls dropped
    kwonly: Tuple[str, ...]
    has_varargs: bool
    annotations: LineageAnnotations
    #: params the function consumes (directly or via callees), to
    #: fixpoint.  Annotations override: consumes() adds,
    #: passthrough() removes.
    consumes: Set[str] = dataclasses.field(default_factory=set)

    @property
    def all_params(self) -> Set[str]:
        return set(self.params) | set(self.kwonly)

    @property
    def key_params(self) -> Set[str]:
        names = {p for p in self.all_params if is_key_name(p)}
        names |= self.annotations.keys
        names -= self.annotations.not_keys
        return names


def _module_name(path: str) -> str:
    norm = path.replace("\\", "/")
    idx = norm.rfind("diff3d_tpu/")
    if idx < 0:
        return ""
    mod = norm[idx:]
    if mod.endswith(".py"):
        mod = mod[:-3]
    if mod.endswith("/__init__"):
        mod = mod[:-len("/__init__")]
    return mod.replace("/", ".")


class ProgramGraph:
    """Cross-module function index + consumes summaries.

    Built once per rngcheck run over every analyzed file; rules then
    re-scan their own :class:`ModuleContext` against it.  Summaries key
    by ``(relpath-ish, name, lineno)`` so rules working on a *separate
    parse* of the same file still resolve locally-defined callees."""

    MAX_CANDIDATES = 4
    _FIXPOINT_ROUNDS = 10

    def __init__(self, sources: Dict[str, str]):
        self.ctxs: List[ModuleContext] = []
        self.by_name: Dict[str, List[FunctionSummary]] = {}
        self.by_loc: Dict[Tuple[str, str, int], FunctionSummary] = {}
        self.by_module: Dict[Tuple[str, str], FunctionSummary] = {}
        #: per-ctx import alias tables, identity-checked (rule passes
        #: hand us fresh ModuleContexts for the same files).
        self._imports: Dict[int, Tuple[ModuleContext,
                                       Dict[str, Tuple[str, str]]]] = {}
        for path in sorted(sources):
            try:
                tree = ast.parse(sources[path], filename=path)
            except SyntaxError:
                continue
            ctx = ModuleContext(path, sources[path], tree)
            self.ctxs.append(ctx)
            self._index_module(ctx)
        self._fixpoint()

    # -- construction ---------------------------------------------------

    def _import_table(self, ctx: ModuleContext):
        entry = self._imports.get(id(ctx))
        if entry is not None and entry[0] is ctx:
            return entry[1]
        imports: Dict[str, Tuple[str, str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    imports[a.asname or a.name] = (node.module, a.name)
        self._imports[id(ctx)] = (ctx, imports)
        return imports

    def _index_module(self, ctx: ModuleContext) -> None:
        module = _module_name(ctx.path)

        def qual(fn: ast.AST) -> str:
            parts = [fn.name]
            cur = ctx.parent.get(id(fn))
            while cur is not None:
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef, ast.ClassDef)):
                    parts.append(cur.name)
                cur = ctx.parent.get(id(cur))
            return ".".join(reversed(parts))

        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            args = node.args
            names = [a.arg for a in args.posonlyargs + args.args]
            if names and names[0] in ("self", "cls"):
                names = names[1:]
            summary = FunctionSummary(
                path=ctx.path, module=module, name=node.name,
                qualname=qual(node), lineno=node.lineno,
                params=tuple(names),
                kwonly=tuple(a.arg for a in args.kwonlyargs),
                has_varargs=args.vararg is not None,
                annotations=parse_lineage_annotations(ctx, node))
            summary.consumes |= summary.annotations.consumes
            self.by_name.setdefault(node.name, []).append(summary)
            self.by_loc[(_loc_path(ctx.path), node.name,
                         node.lineno)] = summary
            if module:
                self.by_module.setdefault((module, node.name), summary)

    def _fixpoint(self) -> None:
        for _ in range(self._FIXPOINT_ROUNDS):
            changed = False
            for ctx in self.ctxs:
                scopes = collect_scope_events(ctx, graph=self)
                for node in ast.walk(ctx.tree):
                    if not isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                        continue
                    summary = self.by_loc.get(
                        (_loc_path(ctx.path), node.name, node.lineno))
                    if summary is None:
                        continue
                    events = scopes.get(id(node), [])
                    consumed = _params_consumed(summary, events)
                    consumed |= summary.annotations.consumes
                    consumed -= summary.annotations.passthrough
                    if consumed != summary.consumes:
                        summary.consumes = consumed
                        changed = True
            if not changed:
                return

    # -- resolution -----------------------------------------------------

    def summary_for(self, ctx: ModuleContext,
                    fn: ast.AST) -> Optional[FunctionSummary]:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
        return self.by_loc.get(
            (_loc_path(ctx.path), fn.name, fn.lineno))

    def _candidates(self, ctx: ModuleContext,
                    call: ast.Call) -> List[FunctionSummary]:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in SAFE_BUILTINS:
                return []
            local = ctx.resolve_local(call, func.id)
            if local is not None:
                summary = self.summary_for(ctx, local)
                return [summary] if summary is not None else []
            imp = self._import_table(ctx).get(func.id)
            if imp is not None:
                module, name = imp
                if not module.startswith("diff3d_tpu"):
                    return []
                hit = self.by_module.get((module, name))
                return [hit] if hit is not None else []
            return list(self.by_name.get(func.id, ()))
        if isinstance(func, ast.Attribute):
            dotted = dotted_name(func.value)
            if dotted is not None:
                root = dotted.split(".")[0]
                if (dotted in ctx.random_aliases
                        or root in SAFE_CALL_ROOTS):
                    return []
            return list(self.by_name.get(func.attr, ()))
        return []

    def consuming_call_args(
            self, ctx: ModuleContext,
            call: ast.Call) -> List[Tuple[str, str]]:
        """``(arg_name, callee_name)`` for every plain-Name argument of
        ``call`` that every resolved candidate agrees is a consumed key
        parameter.  Empty when the callee is unresolved/ambiguous."""
        cands = self._candidates(ctx, call)
        if not cands or len(cands) > self.MAX_CANDIDATES:
            return []
        out: List[Tuple[str, str]] = []
        seen: Set[str] = set()
        mapped = [_map_call_args(call, c) for c in cands]
        for i, arg in enumerate(call.args):
            if not isinstance(arg, ast.Name) or arg.id in seen:
                continue
            if all(("pos", i) in m and m[("pos", i)] in c.consumes
                   for m, c in zip(mapped, cands)):
                out.append((arg.id, cands[0].name))
                seen.add(arg.id)
        for kw in call.keywords:
            if (kw.arg is None or not isinstance(kw.value, ast.Name)
                    or kw.value.id in seen):
                continue
            if all(kw.arg in c.all_params and kw.arg in c.consumes
                   for c in cands):
                out.append((kw.value.id, cands[0].name))
                seen.add(kw.value.id)
        return out


def _loc_path(path: str) -> str:
    norm = path.replace("\\", "/")
    idx = norm.rfind("diff3d_tpu/")
    return norm[idx:] if idx >= 0 else norm


def _map_call_args(call: ast.Call,
                   summary: FunctionSummary) -> Dict[tuple, str]:
    """positional index -> callee param name (keywords handled by the
    caller directly)."""
    out: Dict[tuple, str] = {}
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(summary.params):
            out[("pos", i)] = summary.params[i]
        elif not summary.has_varargs:
            break
    return out


def _params_consumed(summary: FunctionSummary, events) -> Set[str]:
    """Params consumed before any rebinding (the caller-visible
    contract: a rebound name no longer aliases the caller's key)."""
    rebound: Set[str] = set()
    consumed: Set[str] = set()
    params = summary.all_params
    for _, kind, name, _node, _detail in events:
        if kind == "store":
            rebound.add(name)
        elif name in params and name not in rebound:
            consumed.add(name)
    return consumed


def build_program_graph(
        sources: Dict[str, str]) -> ProgramGraph:
    return ProgramGraph(sources)


# ---------------------------------------------------------------------
# runtime witness
# ---------------------------------------------------------------------


class RngWitnessViolation(AssertionError):
    """Raised by :meth:`RngStreamWitness.check` on a key consumed more
    than once while the witness was installed."""


#: jax.random draws that consume their key argument.
DRAW_OPS = ("normal", "uniform", "randint", "bernoulli", "categorical",
            "choice", "permutation", "shuffle", "gamma", "beta",
            "poisson", "exponential", "laplace", "logistic", "gumbel",
            "truncated_normal", "dirichlet", "multivariate_normal",
            "cauchy", "rademacher", "bits")

_SHAPE_ARG_INDEX = {"normal": 1, "uniform": 1, "randint": 1, "bits": 1,
                    "bernoulli": 2, "truncated_normal": 3}
_DTYPE_ARG_INDEX = {"normal": 2, "uniform": 2}


def _fmt_shape(shape) -> str:
    if shape is None:
        return ""
    try:
        return str(tuple(int(d) for d in shape))
    except (TypeError, ValueError):
        return "[?]"


def _fmt_dtype(dtype) -> str:
    if dtype is None:
        return ""
    try:
        import numpy as np

        return f":{np.dtype(dtype).name}"
    except TypeError:
        return ":?"


class RngStreamWitness:
    """Ordered key-derivation events + per-key consumption counts for
    one traced (or eagerly run) program.

    Keys are tracked by object identity — within one trace every
    ``jax.random`` result is a distinct tracer, so handing the *same*
    object to two consuming calls is exactly the linear-resource
    violation the static rules look for.  The witness pins a reference
    to every key it sees so ids are never recycled."""

    def __init__(self):
        self.events: List[str] = []
        self._key_seq: Dict[int, int] = {}
        self._counts: Dict[int, int] = {}
        self._refs: List[object] = []
        self._violations: List[str] = []
        self._next = 0

    def _seq_for(self, key) -> int:
        seq = self._key_seq.get(id(key))
        if seq is None:
            self._next += 1
            seq = self._key_seq[id(key)] = self._next
            self._refs.append(key)
        return seq

    def _consume(self, op: str, key) -> None:
        seq = self._seq_for(key)
        n = self._counts[seq] = self._counts.get(seq, 0) + 1
        if n > 1:
            self._violations.append(
                f"key #{seq} consumed {n}x — jax.random.{op} reused a "
                f"key already spent (split it, or jax.random.clone for "
                f"intentional reuse)")

    def record(self, text: str) -> None:
        self.events.append(text)

    # -- results --------------------------------------------------------

    def consumption_counts(self) -> Dict[int, int]:
        return dict(self._counts)

    def violations(self) -> List[str]:
        return list(self._violations)

    def digest(self) -> str:
        return stream_digest(self.events)

    def check(self) -> None:
        if self._violations:
            raise RngWitnessViolation(
                f"rng witness found {len(self._violations)} "
                "violation(s):\n" + "\n".join(self._violations))

    def report(self) -> str:
        head = (f"rng witness: {len(self.events)} event(s), "
                f"{len(self._counts)} key(s) consumed, "
                f"{len(self._violations)} violation(s), "
                f"digest {self.digest()}")
        if self._violations:
            head += "\n" + "\n".join(self._violations)
        return head


def stream_digest(events: Sequence[str]) -> str:
    return hashlib.sha256("\n".join(events).encode()).hexdigest()


def install_rng_witness(witness: Optional[RngStreamWitness] = None):
    """Monkeypatch the key-consuming ``jax.random`` entry points so
    every call while installed records a stream event (and consumption
    accounting).  Returns ``(witness, uninstall)``; ``uninstall`` is
    idempotent.  Install *after* building models/params and *before*
    ``.lower()``/running — tracing re-executes the Python body, so the
    trace IS the stream."""
    import functools

    import jax.random as jrandom

    w = witness if witness is not None else RngStreamWitness()
    originals: Dict[str, object] = {}

    def _wrap_draw(name, orig):
        shape_idx = _SHAPE_ARG_INDEX.get(name)
        dtype_idx = _DTYPE_ARG_INDEX.get(name)

        @functools.wraps(orig)
        def wrapped(*args, **kwargs):
            if args:
                w._consume(name, args[0])
            shape = kwargs.get("shape")
            if (shape is None and shape_idx is not None
                    and len(args) > shape_idx):
                shape = args[shape_idx]
            dtype = kwargs.get("dtype")
            if (dtype is None and dtype_idx is not None
                    and len(args) > dtype_idx):
                dtype = args[dtype_idx]
            w.record(f"{name}{_fmt_shape(shape)}{_fmt_dtype(dtype)}")
            return orig(*args, **kwargs)

        return wrapped

    def _wrap_split(orig):
        @functools.wraps(orig)
        def wrapped(key, num=2, *args, **kwargs):
            w._consume("split", key)
            w.record(f"split[{num if isinstance(num, int) else '?'}]")
            return orig(key, num, *args, **kwargs)

        return wrapped

    def _wrap_fold_in(orig):
        @functools.wraps(orig)
        def wrapped(key, data, *args, **kwargs):
            w._seq_for(key)   # registered, NOT consumed (derivation)
            tag = data if isinstance(data, int) else "?"
            w.record(f"fold_in[{tag}]")
            return orig(key, data, *args, **kwargs)

        return wrapped

    def _wrap_source(name, orig):
        @functools.wraps(orig)
        def wrapped(seed, *args, **kwargs):
            tag = seed if isinstance(seed, int) else "?"
            w.record(f"{name}[{tag}]")
            return orig(seed, *args, **kwargs)

        return wrapped

    def _patch(name, wrapper):
        orig = getattr(jrandom, name, None)
        if orig is None or not callable(orig):
            return
        originals[name] = orig
        setattr(jrandom, name, wrapper(orig))

    _patch("split", _wrap_split)
    _patch("fold_in", _wrap_fold_in)
    for nm in ("PRNGKey", "key"):
        _patch(nm, lambda orig, _n=nm: _wrap_source(_n, orig))
    for nm in DRAW_OPS:
        _patch(nm, lambda orig, _n=nm: _wrap_draw(_n, orig))

    done: List[bool] = []

    def uninstall() -> None:
        if done:
            return
        done.append(True)
        for nm, orig in originals.items():
            setattr(jrandom, nm, orig)

    return w, uninstall


# ---------------------------------------------------------------------
# loader stream probe
# ---------------------------------------------------------------------


class _ProbeDataset:
    """Stub dataset whose samples fingerprint the per-slot rng stream
    the loader derives — (chosen index, two 63-bit draws)."""

    def __init__(self, n: int):
        self._n = n

    def __len__(self) -> int:
        return self._n

    def sample(self, idx, rng):
        import numpy as np

        return {"idx": np.asarray([idx], np.int64),
                "probe": rng.integers(0, 2 ** 63 - 1, size=2,
                                      dtype=np.int64)}


def loader_stream_events(*, seed: int = 0, batch_size: int = 2,
                         num_hosts: int = 2, steps: int = 3,
                         dataset_len: int = 8) -> List[str]:
    """Drive the REAL loader seed-derivation path (both sample modes,
    every host of a ``num_hosts`` partition) and digest the streams.
    The manifest pins the elasticity contract: the global batch stream
    is a pure function of ``(seed, step, global_slot)``."""
    import numpy as np

    from diff3d_tpu.data.loader import InfiniteLoader

    events: List[str] = []
    for mode in ("iid", "permute"):
        for host in range(num_hosts):
            loader = InfiniteLoader(
                _ProbeDataset(dataset_len), batch_size, seed=seed,
                host_id=host, num_hosts=num_hosts, num_workers=0,
                sample_mode=mode)
            for step in range(steps):
                batch = loader._batch(step)
                blob = (np.ascontiguousarray(batch["idx"]).tobytes()
                        + np.ascontiguousarray(batch["probe"]).tobytes())
                h = hashlib.sha256(blob).hexdigest()[:12]
                events.append(
                    f"loader_{mode}[step={step} host={host}/"
                    f"{num_hosts} B={batch_size}]#{h}")
    return events
