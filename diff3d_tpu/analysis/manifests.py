"""Shared manifest machinery for the manifest-backed analysis pillars.

Four pillars pin observations as JSON manifests under ``runs/<tool>/``
(shardcheck comms budgets, memcheck memory budgets, rngcheck stream
digests, equivcheck semantic fingerprints).  They share one contract:

  * a manifest is ``{version, tool, program, budgets, observed,
    suppressions}``, written with ``indent=1, sort_keys=True`` and a
    trailing newline so diffs are line-stable;
  * loading validates ``version``/``tool`` and raises ``ValueError``
    otherwise — an unreadable manifest is a *finding* at the call site,
    never a crash;
  * suppressions are key-scoped (``key`` names one subject, ``"*"``
    covers the rule) and reason-mandatory: a reasonless suppression is
    itself reported (GL002/SC002/MC002/RC002/EQ002);
  * ``--update`` re-pins observations but PRESERVES committed
    suppressions — they are reviewed policy, not observations.

This module is the single implementation of that contract; the pillar
modules keep their own schemas (budgets differ) and finding factories
(rule ids and message styles differ) and delegate the shared half here.
Behavior is pinned by the pillars' existing round-trip tests.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, List, Optional, Sequence

from diff3d_tpu.analysis.lint import Finding


@dataclasses.dataclass
class Suppression:
    """One key-scoped manifest suppression.  ``key`` names the subject
    (a collective op, an arg index, a canonical-op key); ``"*"`` covers
    the whole rule.  ``reason`` is mandatory policy — enforced by the
    per-pillar reasonless rule, not here."""

    rule: str
    key: str = "*"
    reason: Optional[str] = None

    def covers(self, rule: str, key: str) -> bool:
        return self.rule == rule and self.key in ("*", key)


def parse_suppressions(entries: Sequence[dict]) -> List[Suppression]:
    """Tolerant ``suppressions`` block -> dataclasses (missing fields
    get the documented defaults)."""
    return [Suppression(rule=str(s.get("rule", "")),
                        key=str(s.get("key", "*")),
                        reason=s.get("reason"))
            for s in entries or []]


def manifest_path(program: str, manifest_dir: str) -> str:
    return os.path.join(manifest_dir, f"{program}.json")


def load_manifest_data(path: str, tool: str, version: int,
                       kind: str) -> dict:
    """Load + validate the shared envelope; ``kind`` is the human name
    used in the error (e.g. ``"shardcheck manifest"``)."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if (not isinstance(data, dict)
            or data.get("version") != version
            or data.get("tool") != tool):
        raise ValueError(f"{path}: not a {kind} (version {version})")
    return data


def write_manifest_data(path: str, data: dict) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")


def apply_suppressions(
        findings: Sequence[Finding], supps: Sequence[Suppression],
        make_reasonless: Callable[[Suppression], Finding]
) -> List[Finding]:
    """Mark each finding whose ``(rule, key)`` a suppression covers
    (key = the last ``\\x00`` field of ``fingerprint_data``), then
    report every reasonless suppression via ``make_reasonless`` (the
    pillar supplies its own rule id / message style)."""
    out: List[Finding] = []
    for f in findings:
        key = (f.fingerprint_data or "").split("\x00")[-1]
        supp = next((s for s in supps if s.covers(f.rule, key)), None)
        if supp is not None:
            f = dataclasses.replace(f, suppressed=True,
                                    suppress_reason=supp.reason)
        out.append(f)
    for s in supps:
        if not s.reason:
            out.append(make_reasonless(s))
    return out


def carry_suppressions(path: str, loader: Callable[[str], object]) -> list:
    """The ``--update`` half of the contract: committed suppressions
    survive a re-pin.  ``loader`` is the pillar's manifest loader; an
    unreadable/absent manifest carries nothing (the re-pin starts
    clean).  Returns whatever suppression list the loaded manifest
    holds — dataclasses for the dataclass-manifest pillars, parsed
    entries for the dict-manifest ones."""
    if not os.path.exists(path):
        return []
    try:
        loaded = loader(path)
    except (ValueError, json.JSONDecodeError):
        return []
    if isinstance(loaded, dict):
        return parse_suppressions(loaded.get("suppressions", []))
    return list(getattr(loaded, "suppressions", []))
