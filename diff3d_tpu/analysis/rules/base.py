"""Rule protocol for graftlint.

A rule is a stateless object with an ``id`` (``GLnnn``), a short
``name``, a default ``severity``, and ``check(ctx) -> Iterator[Finding]``
over one :class:`~diff3d_tpu.analysis.rules.context.ModuleContext`.
Rules must be conservative: an unsuppressed false positive blocks the
tier-1 gate, so when a pattern is ambiguous the rule stays silent — the
runtime harness (``analysis/runtime.py``) catches what static analysis
declines to guess at.
"""

from __future__ import annotations

import ast
from typing import Iterator

from diff3d_tpu.analysis.rules.context import ModuleContext


class Rule:
    id: str = "GL000"
    name: str = "abstract"
    severity: str = "error"
    description: str = ""

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST,
                message: str, severity: str = None):
        from diff3d_tpu.analysis.lint import Finding
        return Finding(
            path=ctx.path, rule=self.id,
            line=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0),
            severity=severity or self.severity,
            message=message)
