"""Shared AST analysis for the lint rules.

One :class:`ModuleContext` is built per linted file and handed to every
rule, so the expensive whole-module passes (symbol tables, the jit
registry, traced-context discovery) run once.

Two vocabulary items every rule leans on:

  * the **jit registry** — every ``jax.jit``/``pjit`` call site in the
    module, with the wrapped function resolved to its local ``def`` /
    ``lambda`` when possible, plus the ``static_argnums`` /
    ``static_argnames`` / ``donate_argnums`` it was compiled with and the
    name(s) the jitted callable was bound to (``f = jax.jit(...)`` or
    ``self._f = jax.jit(...)``);
  * **traced contexts** — function nodes whose *parameters are tracers*
    when they run: jit-decorated/jit-wrapped functions and the body
    functions handed to ``lax.scan`` / ``while_loop`` / ``fori_loop`` /
    ``cond`` / ``vmap`` / ``pmap`` / ``grad``, plus every ``def`` nested
    inside one.  Rules deliberately do NOT propagate "traced" through
    ordinary call edges — a helper called from a traced function often
    receives concrete Python values (config flags, shapes), and flagging
    its ``if``s would drown the gate in false positives.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

#: Callables whose function-valued first argument runs under trace.
TRACING_ENTRY_POINTS = {
    "jax.jit", "jit", "jax.pjit", "pjit", "jax.experimental.pjit.pjit",
    "jax.vmap", "vmap", "jax.pmap", "pmap",
    "jax.grad", "grad", "jax.value_and_grad", "value_and_grad",
    "jax.lax.scan", "lax.scan", "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop", "jax.lax.cond", "lax.cond",
    "jax.checkpoint", "jax.remat",
}

#: The subset that is a jit boundary (static/donate argnums apply).
JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit",
             "jax.experimental.pjit.pjit"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.scan`` for nested Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _unwrap_partial(call: ast.Call) -> Optional[ast.Call]:
    """``partial(jax.jit, ...)`` -> a synthetic view of the jit call."""
    name = dotted_name(call.func)
    if name in ("functools.partial", "partial") and call.args:
        inner = dotted_name(call.args[0])
        if inner in JIT_NAMES or inner in TRACING_ENTRY_POINTS:
            synthetic = ast.Call(func=call.args[0], args=call.args[1:],
                                 keywords=call.keywords)
            ast.copy_location(synthetic, call)
            return synthetic
    return None


def _int_elements(node: ast.AST) -> Tuple[int, ...]:
    """Integer literals of an int / tuple / list literal (else empty)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
        return tuple(out)
    return ()


def _str_elements(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(e.value for e in node.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
    return ()


def param_names(fn: ast.AST) -> List[str]:
    """Positional parameter names of a def/lambda (self excluded)."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
        return []
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if names and names[0] in ("self", "cls"):
        names = names[1:]
    return names


@dataclasses.dataclass
class JitSite:
    """One ``jax.jit(...)`` call site."""

    call: ast.Call
    #: resolved wrapped function node (def/lambda), when local.
    fn: Optional[ast.AST]
    static_argnums: Tuple[int, ...]
    static_argnames: Tuple[str, ...]
    donate_argnums: Tuple[int, ...]
    #: names the jitted callable is bound to: plain names and, for
    #: ``self.x = jax.jit(...)``, the attribute name (matched by attr).
    bound_names: Tuple[str, ...] = ()
    bound_attrs: Tuple[str, ...] = ()


class _ScopeCollector(ast.NodeVisitor):
    """name -> def node, per enclosing scope chain (module + functions)."""

    def __init__(self):
        self.defs: Dict[int, Dict[str, ast.AST]] = {}
        self._stack: List[ast.AST] = []

    def visit_Module(self, node):
        self._stack.append(node)
        self.defs[id(node)] = {}
        self.generic_visit(node)
        self._stack.pop()

    def _visit_fn(self, node):
        self.defs[id(self._stack[-1])][node.name] = node
        self._stack.append(node)
        self.defs[id(node)] = {}
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_ClassDef(self, node):
        # Methods live in the class namespace; rules only ever resolve
        # plain names, so class scopes are transparent here.
        self.generic_visit(node)

    def visit_Lambda(self, node):
        self._stack.append(node)
        self.defs[id(node)] = {}
        self.generic_visit(node)
        self._stack.pop()


class ModuleContext:
    """Everything the rules share about one parsed module."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree

        # Parent links (ast has none) + source-ordered node walk.
        self.parent: Dict[int, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[id(child)] = node

        # Scope chains for name -> local def resolution.
        collector = _ScopeCollector()
        collector.visit(tree)
        self._scope_defs = collector.defs

        # Aliases of the jax.random module ("jr", "random", ...).
        self.random_aliases: Set[str] = {"jax.random"}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.random":
                        self.random_aliases.add(a.asname or "jax.random")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "random":
                            self.random_aliases.add(a.asname or "random")
                elif node.module == "jax.random":
                    pass  # direct function imports handled by callers

        self.jit_sites: List[JitSite] = []
        self._collect_jit_sites()
        self.traced_functions: Set[int] = set()
        self._traced_nodes: List[ast.AST] = []
        self._collect_traced()

    # -- scope / name resolution ---------------------------------------

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parent.get(id(node))
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parent.get(id(cur))
        return None

    def resolve_local(self, node: ast.AST,
                      name: str) -> Optional[ast.AST]:
        """The def bound to ``name`` visible from ``node``'s scope."""
        scope = self.enclosing_function(node)
        while True:
            defs = self._scope_defs.get(id(scope if scope is not None
                                            else self.tree), {})
            if name in defs:
                return defs[name]
            if scope is None:
                return None
            scope = self.enclosing_function(scope)
            if scope is None:
                defs = self._scope_defs.get(id(self.tree), {})
                return defs.get(name)

    # -- jit registry ---------------------------------------------------

    def _collect_jit_sites(self) -> None:
        # Decorator form first: @jax.jit / @partial(jax.jit, ...) on a
        # def associates the site with the decorated function itself.
        decorated: Set[int] = set()
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for deco in node.decorator_list:
                call = deco if isinstance(deco, ast.Call) else None
                if call is not None:
                    name = dotted_name(call.func)
                    if name not in JIT_NAMES:
                        call = _unwrap_partial(call)
                        if (call is None
                                or dotted_name(call.func)
                                not in JIT_NAMES):
                            continue
                elif dotted_name(deco) in JIT_NAMES:
                    call = ast.Call(func=deco, args=[], keywords=[])
                    ast.copy_location(call, deco)
                else:
                    continue
                decorated.add(id(deco))
                static_nums: Tuple[int, ...] = ()
                static_names: Tuple[str, ...] = ()
                donate: Tuple[int, ...] = ()
                for kw in call.keywords:
                    if kw.arg == "static_argnums":
                        static_nums = _int_elements(kw.value)
                    elif kw.arg == "static_argnames":
                        static_names = _str_elements(kw.value)
                    elif kw.arg in ("donate_argnums", "donate_argnames"):
                        donate = _int_elements(kw.value)
                self.jit_sites.append(JitSite(
                    call=call, fn=node, static_argnums=static_nums,
                    static_argnames=static_names, donate_argnums=donate,
                    bound_names=(node.name,)))
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if id(node) in decorated:
                continue
            call = node
            name = dotted_name(call.func)
            if name not in JIT_NAMES:
                unwrapped = _unwrap_partial(call)
                if (unwrapped is None
                        or dotted_name(unwrapped.func) not in JIT_NAMES):
                    continue
                call = unwrapped
            fn_node: Optional[ast.AST] = None
            if call.args:
                target = call.args[0]
                if isinstance(target, ast.Lambda):
                    fn_node = target
                elif isinstance(target, ast.Name):
                    fn_node = self.resolve_local(node, target.id)
            static_nums: Tuple[int, ...] = ()
            static_names: Tuple[str, ...] = ()
            donate: Tuple[int, ...] = ()
            for kw in call.keywords:
                if kw.arg == "static_argnums":
                    static_nums = _int_elements(kw.value)
                elif kw.arg == "static_argnames":
                    static_names = _str_elements(kw.value)
                elif kw.arg in ("donate_argnums", "donate_argnames"):
                    donate = _int_elements(kw.value)
            bound_names: List[str] = []
            bound_attrs: List[str] = []
            parent = self.parent.get(id(node))
            # Walk through decorator application: `f = jax.jit(g)`.
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        bound_names.append(t.id)
                    elif isinstance(t, ast.Attribute):
                        bound_attrs.append(t.attr)
            self.jit_sites.append(JitSite(
                call=node if call is node else node, fn=fn_node,
                static_argnums=static_nums, static_argnames=static_names,
                donate_argnums=donate, bound_names=tuple(bound_names),
                bound_attrs=tuple(bound_attrs)))

    def jit_site_for_callable_name(self, name: str,
                                   is_attr: bool) -> Optional[JitSite]:
        """The jit site bound to ``name`` (attr name for self.X calls)."""
        for site in self.jit_sites:
            if is_attr and name in site.bound_attrs:
                return site
            if not is_attr and name in site.bound_names:
                return site
        return None

    # -- traced contexts ------------------------------------------------

    def _mark_traced(self, fn: Optional[ast.AST]) -> None:
        if fn is None or id(fn) in self.traced_functions:
            return
        self.traced_functions.add(id(fn))
        self._traced_nodes.append(fn)
        # Nested defs run under the same trace.
        for node in ast.walk(fn):
            if node is fn:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                if id(node) not in self.traced_functions:
                    self.traced_functions.add(id(node))
                    self._traced_nodes.append(node)

    def _collect_traced(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    name = dotted_name(deco)
                    if name is None and isinstance(deco, ast.Call):
                        name = dotted_name(deco.func)
                        if name not in TRACING_ENTRY_POINTS:
                            inner = _unwrap_partial(deco)
                            name = (dotted_name(inner.func)
                                    if inner is not None else None)
                    if name in TRACING_ENTRY_POINTS:
                        self._mark_traced(node)
            elif isinstance(node, ast.Call):
                call = node
                name = dotted_name(call.func)
                if name not in TRACING_ENTRY_POINTS:
                    unwrapped = _unwrap_partial(call)
                    if unwrapped is None:
                        continue
                    call, name = unwrapped, dotted_name(unwrapped.func)
                    if name not in TRACING_ENTRY_POINTS:
                        continue
                if not call.args:
                    continue
                target = call.args[0]
                if isinstance(target, ast.Lambda):
                    self._mark_traced(target)
                elif isinstance(target, ast.Name):
                    self._mark_traced(self.resolve_local(node, target.id))

    def traced_nodes(self) -> Sequence[ast.AST]:
        return tuple(self._traced_nodes)

    def static_params_of(self, fn: ast.AST) -> Set[str]:
        """Param names of ``fn`` that some jit site marks static."""
        names = param_names(fn)
        static: Set[str] = set()
        for site in self.jit_sites:
            if site.fn is not fn:
                continue
            static.update(site.static_argnames)
            for i in site.static_argnums:
                if 0 <= i < len(names):
                    static.add(names[i])
        return static
