"""GL108/GL109: sharding hygiene at jit boundaries.

The IR analyzer (``analysis/ir.py``) audits what GSPMD actually did;
these two rules catch the *source* patterns that most often cause what
it flags:

  * **GL108** — a ``jax.jit`` call that passes only one of
    ``in_shardings`` / ``out_shardings``, or passes neither while the
    wrapped function uses ``with_sharding_constraint`` internally (so it
    is demonstrably on a mesh path).  Half-specified boundaries leave
    the other side to sharding propagation, which silently picks
    whatever minimises *this* program — usually replication, paid for
    as an all-gather at the boundary.
  * **GL109** — a jitted function closing over a concrete device array
    built in an *enclosing function* (``jnp.array`` / ``zeros`` /
    ``device_put`` / ``jax.random.*`` results).  Closure captures are
    baked into the compiled program as constants: the buffer is
    replicated onto every device, never donated, and a "new" value
    needs a retrace.  Module-level constants are excluded (idiomatic
    lookup tables) and attribute references (``self.w``) are out of
    scope — the rule targets the easy-to-miss local capture.

Both rules only fire on resolvable in-module functions, per the
conservatism contract in ``rules/base.py``.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import (ModuleContext, dotted_name,
                                               param_names)

_SHARDING_KWARGS = {"in_shardings", "out_shardings"}
#: Calls whose result is a concrete (device) array when bound at
#: function scope.  numpy constructors are deliberately excluded —
#: closing over a host lookup table is idiomatic and the capture is
#: intentional.
_ARRAY_CONSTRUCTOR_PREFIXES = ("jnp.", "jax.numpy.", "jax.random.")
_ARRAY_CONSTRUCTOR_NAMES = {"jax.device_put"}


def _uses_sharding_constraint(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.endswith("with_sharding_constraint"):
                return True
    return False


def _is_array_constructor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func)
    if name is None:
        return False
    return (name in _ARRAY_CONSTRUCTOR_NAMES
            or any(name.startswith(p)
                   for p in _ARRAY_CONSTRUCTOR_PREFIXES))


class ShardingSpecRule(Rule):
    id = "GL108"
    name = "half-specified-shardings"
    severity = "warning"
    description = ("jit boundary on a mesh path with missing/half "
                   "in_shardings/out_shardings")

    def check(self, ctx: ModuleContext) -> Iterator:
        for site in ctx.jit_sites:
            # A **kwargs splat may carry the specs (the sampler's
            # `**_specs(...)` idiom) — unverifiable, stay silent.
            if any(kw.arg is None for kw in site.call.keywords):
                continue
            given = {kw.arg for kw in site.call.keywords
                     if kw.arg in _SHARDING_KWARGS}
            if len(given) == 1:
                missing = (_SHARDING_KWARGS - given).pop()
                yield self.finding(
                    ctx, site.call,
                    f"jit passes {given.pop()} but not {missing} — the "
                    "unspecified side is left to sharding propagation, "
                    "which may silently replicate (all-gather at the "
                    "boundary); specify both")
            elif (not given and site.fn is not None
                  and _uses_sharding_constraint(site.fn)):
                yield self.finding(
                    ctx, site.call,
                    "jit wraps a function using with_sharding_constraint "
                    "but passes neither in_shardings nor out_shardings — "
                    "boundary placement is left to propagation; "
                    "specify both")


class ClosedOverArrayRule(Rule):
    id = "GL109"
    name = "jit-closure-constant-capture"
    severity = "warning"
    description = ("jitted function closes over a device array built in "
                   "an enclosing function (baked-in replicated constant)")

    def check(self, ctx: ModuleContext) -> Iterator:
        for site in ctx.jit_sites:
            fn = site.fn
            if fn is None:
                continue
            free = _free_loads(fn)
            if not free:
                continue
            scope = ctx.enclosing_function(fn)
            while scope is not None:
                for name, value in _own_scope_array_bindings(scope, fn):
                    if name in free:
                        yield self.finding(
                            ctx, site.call,
                            f"jitted function closes over '{name}' = "
                            f"{_call_label(value)} built in the "
                            "enclosing function — captured as a baked-in "
                            "compiled constant (replicated on every "
                            "device, retrace to change); pass it as an "
                            "argument instead")
                        free.discard(name)
                scope = ctx.enclosing_function(scope)


def _free_loads(fn: ast.AST) -> Set[str]:
    """Names loaded in ``fn`` but neither parameters nor locally bound."""
    bound = set(param_names(fn))
    args = fn.args
    for extra in (args.kwonlyargs,):
        bound.update(a.arg for a in extra)
    for va in (args.vararg, args.kwarg):
        if va is not None:
            bound.add(va.arg)
    loads: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.add(node.id)
            else:
                bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node is not fn:
                bound.add(node.name)
    return loads - bound


def _own_scope_array_bindings(scope: ast.AST, exclude: ast.AST):
    """``(name, value)`` for array-constructor assignments in ``scope``'s
    own body (nested function bodies — including ``exclude`` — skipped)."""
    out = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Assign):
                if _is_array_constructor(child.value):
                    for t in child.targets:
                        if isinstance(t, ast.Name):
                            out.append((t.id, child.value))
            elif (isinstance(child, ast.AnnAssign)
                  and child.value is not None
                  and isinstance(child.target, ast.Name)
                  and _is_array_constructor(child.value)):
                out.append((child.target.id, child.value))
            visit(child)

    visit(scope)
    return out


def _call_label(value: ast.Call) -> str:
    return f"{dotted_name(value.func) or 'an array constructor'}(...)"
