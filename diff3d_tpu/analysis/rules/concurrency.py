"""LC-rule pack: concurrency analysis for the threaded runtime.

The serving engine, the async checkpointer and the prefetch loader are
the only places this codebase runs real threads — and they are exactly
the places a deadlock or a torn read cannot be caught by example-based
tests (the interleaving that breaks is the one the test never runs).
This module is the *static* half of lockcheck (DESIGN.md §12): a
whole-module concurrency model shared by rules LC301–LC308, built once
per file like graftlint's jit registry.

The model:

  * **Lock discovery** — ``self.X = threading.Lock()`` (and RLock /
    Condition / Semaphore / Event / queue.Queue) attribute inits, plus
    ``_lock = threading.Lock()`` module globals.  A ``Condition(lock)``
    canonicalises to its underlying lock: holding the condition *is*
    holding the lock.
  * **guarded-by annotations** — a trailing ``# guarded-by: self._lock``
    comment on an attribute (or global) initialiser declares the lock
    that must be held at every access (LC302).  The same comment on a
    ``def`` line declares a *precondition*: callers hold the lock, so
    the method body is analysed with it held (the ``_locked``-suffix
    internal-method convention).
  * **Held-set dataflow** — every function is walked once with the set
    of held locks threaded through ``with`` blocks and statement-level
    ``.acquire()``/``.release()`` pairs.  Acquisitions while other
    locks are held become edges in a per-class lock-order graph;
    ``self.method()`` calls propagate acquisitions across methods
    (fixpoint), so an A→B order buried two calls deep still closes a
    cycle (LC301).

Rules stay conservative (base.py contract): anything ambiguous —
unknown receiver types, cross-class aliasing, locks passed as
arguments — is left to the runtime witness (``analysis/witness.py``).
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import ModuleContext, dotted_name

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")

#: Factory terminal name -> kind, for threading/queue object discovery.
_FACTORY_KINDS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Event": "event",
    "Semaphore": "semaphore", "BoundedSemaphore": "semaphore",
    "Queue": "queue", "LifoQueue": "queue", "PriorityQueue": "queue",
    "SimpleQueue": "queue",
}
_FACTORY_MODULES = {"threading", "queue", "multiprocessing"}

#: Methods that mutate a list/dict/set in place (LC308 global check).
_MUTATORS = {"append", "extend", "insert", "add", "update", "setdefault",
             "pop", "popitem", "clear", "remove", "discard"}

#: Callback-suggesting parameter / attribute name suffixes (LC306).
_CALLBACK_NAME_RE = re.compile(
    r"(^|_)(callback|factory|hook|fn)$|^on_[a-z_]+$")


def _factory_kind(node: ast.AST) -> Optional[str]:
    """'lock' / 'condition' / ... when ``node`` is a threading-object
    constructor call, else None."""
    if not isinstance(node, ast.Call):
        return None
    dn = dotted_name(node.func)
    if dn is None:
        return None
    parts = dn.split(".")
    kind = _FACTORY_KINDS.get(parts[-1])
    if kind is None:
        return None
    if len(parts) == 1 or parts[0] in _FACTORY_MODULES:
        return kind
    return None


def _base_key(expr: ast.AST) -> Optional[str]:
    """Canonical receiver key: ``self.X`` -> "self.X", bare name -> name."""
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        return f"self.{expr.attr}"
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_true(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


def _is_false(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


@dataclasses.dataclass
class LockDecl:
    key: str                       # "self._lock" or module-global name
    kind: str                      # lock | rlock | condition
    node: ast.AST
    canonical: str                 # conditions resolve to their lock


@dataclasses.dataclass
class UnitInfo:
    """One lock-analysis unit: a class, or the module's global scope."""

    name: str
    node: ast.AST
    is_module: bool
    locks: Dict[str, LockDecl] = dataclasses.field(default_factory=dict)
    events: Set[str] = dataclasses.field(default_factory=set)
    queues: Set[str] = dataclasses.field(default_factory=set)
    semaphores: Set[str] = dataclasses.field(default_factory=set)
    #: attr/global key -> (canonical lock key, declaring node)
    guarded: Dict[str, Tuple[str, ast.AST]] = dataclasses.field(
        default_factory=dict)
    #: guarded-by specs naming a lock the unit never declares
    bad_guards: List[Tuple[str, ast.AST]] = dataclasses.field(
        default_factory=list)
    callbacks: Set[str] = dataclasses.field(default_factory=set)
    methods: Dict[str, ast.AST] = dataclasses.field(default_factory=dict)
    #: method name -> canonical lock held on entry (def-line guarded-by)
    preconditions: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: module unit only: module-level mutable globals (dict/list/set)
    mutables: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class _Acquire:
    lock: str
    node: ast.AST
    held: FrozenSet[str]
    reentrant: bool


@dataclasses.dataclass
class _Access:
    key: str
    node: ast.AST
    held: FrozenSet[str]
    store: bool


@dataclasses.dataclass
class _Blocking:
    desc: str
    node: ast.AST
    held: FrozenSet[str]


@dataclasses.dataclass
class _CondWait:
    cond: str
    node: ast.AST
    held: FrozenSet[str]
    in_loop: bool


@dataclasses.dataclass
class _SelfCall:
    method: str
    node: ast.AST
    held: FrozenSet[str]


@dataclasses.dataclass
class _CallbackCall:
    name: str
    node: ast.AST
    held: FrozenSet[str]


@dataclasses.dataclass
class _JoinCall:
    key: str                      # terminal name of the joined object
    node: ast.AST
    held: FrozenSet[str]


@dataclasses.dataclass
class _ThreadCreate:
    node: ast.Call
    daemon: bool
    bound: Optional[str]          # terminal name it is assigned to
    target_fn: Optional[ast.AST]  # resolved target def, when local


@dataclasses.dataclass
class _GlobalMut:
    name: str
    node: ast.AST


@dataclasses.dataclass
class _FnScan:
    fn: ast.AST
    unit: UnitInfo
    acquires: List[_Acquire] = dataclasses.field(default_factory=list)
    double_acquires: List[_Acquire] = dataclasses.field(
        default_factory=list)
    accesses: List[_Access] = dataclasses.field(default_factory=list)
    blocking: List[_Blocking] = dataclasses.field(default_factory=list)
    cond_waits: List[_CondWait] = dataclasses.field(default_factory=list)
    self_calls: List[_SelfCall] = dataclasses.field(default_factory=list)
    callback_calls: List[_CallbackCall] = dataclasses.field(
        default_factory=list)
    joins: List[_JoinCall] = dataclasses.field(default_factory=list)
    threads: List[_ThreadCreate] = dataclasses.field(default_factory=list)
    global_muts: List[_GlobalMut] = dataclasses.field(default_factory=list)
    direct_locks: Set[str] = dataclasses.field(default_factory=set)


class _FnScanner:
    """One pass over a function body, threading the held-lock set."""

    def __init__(self, ctx: ModuleContext, unit: UnitInfo,
                 module_unit: UnitInfo, fn: ast.AST,
                 pre_held: Sequence[str] = ()):
        self.ctx = ctx
        self.unit = unit
        self.module_unit = module_unit
        self.fn = fn
        self.scan = _FnScan(fn=fn, unit=unit)
        self.held: Set[str] = set(pre_held)
        self.loop_depth = 0
        self.local_locks: Dict[str, LockDecl] = {}
        self.globals_declared: Set[str] = set()
        self.nested: List[ast.AST] = []
        self.callback_params = self._callback_params(fn)

    @staticmethod
    def _callback_params(fn: ast.AST) -> Set[str]:
        out: Set[str] = set()
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return out
        for a in fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs:
            if a.arg in ("self", "cls"):
                continue
            ann = ast.unparse(a.annotation) if a.annotation else ""
            if "Callable" in ann or _CALLBACK_NAME_RE.search(a.arg):
                out.add(a.arg)
        return out

    # -- resolution -----------------------------------------------------

    def resolve_lock(self, expr: ast.AST) -> Optional[LockDecl]:
        key = _base_key(expr)
        if key is None:
            return None
        if key.startswith("self."):
            return self.unit.locks.get(key)
        return (self.module_unit.locks.get(key)
                or self.local_locks.get(key))

    def _kind_of(self, key: Optional[str], kind_set_attr: str) -> bool:
        if key is None:
            return False
        if key.startswith("self."):
            return key in getattr(self.unit, kind_set_attr)
        return key in getattr(self.module_unit, kind_set_attr)

    # -- recording ------------------------------------------------------

    def record_acquire(self, decl: LockDecl, node: ast.AST) -> None:
        held = frozenset(self.held)
        reentrant = decl.kind == "rlock"
        evt = _Acquire(lock=decl.canonical, node=node, held=held,
                       reentrant=reentrant)
        self.scan.acquires.append(evt)
        self.scan.direct_locks.add(decl.canonical)
        if decl.canonical in self.held and not reentrant:
            self.scan.double_acquires.append(evt)

    def _record_attr_access(self, node: ast.Attribute) -> None:
        if not (isinstance(node.value, ast.Name)
                and node.value.id == "self"):
            return
        key = f"self.{node.attr}"
        if key in self.unit.guarded:
            self.scan.accesses.append(_Access(
                key=key, node=node, held=frozenset(self.held),
                store=isinstance(node.ctx, (ast.Store, ast.Del))))

    def _record_name_access(self, node: ast.Name) -> None:
        if node.id in self.module_unit.guarded:
            self.scan.accesses.append(_Access(
                key=node.id, node=node, held=frozenset(self.held),
                store=isinstance(node.ctx, (ast.Store, ast.Del))))
        if (node.id in self.globals_declared
                and isinstance(node.ctx, (ast.Store, ast.Del))
                and not self.held):
            self.scan.global_muts.append(_GlobalMut(node.id, node))

    # -- statement walk -------------------------------------------------

    def scan_function(self) -> _FnScan:
        body = getattr(self.fn, "body", None)
        if isinstance(self.fn, ast.Lambda):
            self.scan_expr(self.fn.body)
        elif body is not None:
            self.scan_block(body)
        return self.scan

    def scan_block(self, stmts: Sequence[ast.stmt]) -> None:
        for s in stmts:
            self.scan_stmt(s)

    def scan_stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.With, ast.AsyncWith)):
            entered: List[str] = []
            for item in s.items:
                self.scan_expr(item.context_expr)
                decl = self.resolve_lock(item.context_expr)
                if decl is not None:
                    self.record_acquire(decl, item.context_expr)
                    if decl.canonical not in self.held:
                        self.held.add(decl.canonical)
                        entered.append(decl.canonical)
            self.scan_block(s.body)
            for key in entered:
                self.held.discard(key)
        elif isinstance(s, ast.While):
            self.scan_expr(s.test)
            self.loop_depth += 1
            self.scan_block(s.body)
            self.loop_depth -= 1
            self.scan_block(s.orelse)
        elif isinstance(s, ast.For):
            self.scan_expr(s.iter)
            self.scan_expr(s.target)
            self.scan_block(s.body)
            self.scan_block(s.orelse)
        elif isinstance(s, ast.If):
            self.scan_expr(s.test)
            self.scan_block(s.body)
            self.scan_block(s.orelse)
        elif isinstance(s, ast.Try):
            self.scan_block(s.body)
            for h in s.handlers:
                if h.type is not None:
                    self.scan_expr(h.type)
                self.scan_block(h.body)
            self.scan_block(s.orelse)
            self.scan_block(s.finalbody)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.nested.append(s)
        elif isinstance(s, ast.ClassDef):
            pass  # nested classes: out of scope for this pass
        elif isinstance(s, ast.Global):
            self.globals_declared.update(s.names)
        elif isinstance(s, ast.Assign):
            self.scan_expr(s.value)
            kind = _factory_kind(s.value)
            if (kind in ("lock", "rlock")
                    and len(s.targets) == 1
                    and isinstance(s.targets[0], ast.Name)):
                name = s.targets[0].id
                self.local_locks[name] = LockDecl(
                    key=name, kind=kind, node=s.value, canonical=name)
            for t in s.targets:
                self.scan_expr(t)
        elif isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            # Statement-level acquire()/release() adjust the held set.
            call = s.value
            if isinstance(call.func, ast.Attribute):
                decl = self.resolve_lock(call.func.value)
                if decl is not None and call.func.attr == "acquire":
                    if not self._nonblocking_acquire(call):
                        self.record_acquire(decl, call)
                        self.held.add(decl.canonical)
                    for a in call.args:
                        self.scan_expr(a)
                    for kw in call.keywords:
                        self.scan_expr(kw.value)
                    return
                if decl is not None and call.func.attr == "release":
                    self.held.discard(decl.canonical)
                    return
            self.scan_expr(call)
        else:
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.scan_expr(child)
                elif isinstance(child, ast.stmt):
                    self.scan_stmt(child)

    @staticmethod
    def _nonblocking_acquire(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "timeout":
                return True
            if kw.arg == "blocking" and _is_false(kw.value):
                return True
        return bool(call.args) and _is_false(call.args[0])

    # -- expression walk ------------------------------------------------

    def scan_expr(self, node: Optional[ast.AST]) -> None:
        if node is None or isinstance(node, ast.Constant):
            return
        if isinstance(node, ast.Lambda):
            self.nested.append(node)
            return
        if isinstance(node, ast.Call):
            self.handle_call(node)
            return
        if isinstance(node, ast.Attribute):
            self._record_attr_access(node)
            self.scan_expr(node.value)
            return
        if isinstance(node, ast.Name):
            self._record_name_access(node)
            return
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, (ast.Store, ast.Del))
                and isinstance(node.value, ast.Name)
                and node.value.id in self.module_unit.mutables
                and not self.held):
            self.scan_expr(node.slice)
            self.scan_expr(node.value)
            self.scan.global_muts.append(
                _GlobalMut(node.value.id, node))
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.scan_expr(child)
            elif isinstance(child, ast.comprehension):
                self.scan_expr(child.iter)
                self.scan_expr(child.target)
                for i in child.ifs:
                    self.scan_expr(i)
            elif isinstance(child, ast.keyword):
                self.scan_expr(child.value)

    def handle_call(self, call: ast.Call) -> None:
        held = frozenset(self.held)
        dn = dotted_name(call.func)

        if dn is not None and self._is_thread_ctor(dn):
            self._record_thread(call)
        elif dn in ("time.sleep", "jax.block_until_ready") and held:
            self.scan.blocking.append(_Blocking(dn, call, held))
        elif dn is not None and held and (
                dn.startswith("urllib.request.")
                or dn.startswith("requests.")
                or dn in ("socket.create_connection",)):
            self.scan.blocking.append(
                _Blocking(f"{dn} (network I/O)", call, held))

        if isinstance(call.func, ast.Attribute):
            self._handle_method_call(call, call.func, held)
        elif isinstance(call.func, ast.Name):
            if call.func.id in self.callback_params and held:
                self.scan.callback_calls.append(_CallbackCall(
                    call.func.id, call, held))

        self.scan_expr(call.func)
        for a in call.args:
            self.scan_expr(a)
        for kw in call.keywords:
            self.scan_expr(kw.value)

    @staticmethod
    def _is_thread_ctor(dn: str) -> bool:
        parts = dn.split(".")
        return parts[-1] == "Thread" and (
            len(parts) == 1 or parts[0] in _FACTORY_MODULES)

    def _record_thread(self, call: ast.Call) -> None:
        daemon = any(kw.arg == "daemon" and _is_true(kw.value)
                     for kw in call.keywords)
        bound: Optional[str] = None
        parent = self.ctx.parent.get(id(call))
        if isinstance(parent, ast.Assign):
            for t in parent.targets:
                if isinstance(t, ast.Name):
                    bound = t.id
                elif isinstance(t, ast.Attribute):
                    bound = t.attr
        target_fn: Optional[ast.AST] = None
        for kw in call.keywords:
            if kw.arg != "target":
                continue
            tkey = _base_key(kw.value)
            if tkey is None:
                continue
            if tkey.startswith("self."):
                target_fn = self.unit.methods.get(tkey[5:])
            else:
                target_fn = self.ctx.resolve_local(call, tkey)
        self.scan.threads.append(_ThreadCreate(
            node=call, daemon=daemon, bound=bound, target_fn=target_fn))

    def _handle_method_call(self, call: ast.Call, func: ast.Attribute,
                            held: FrozenSet[str]) -> None:
        meth = func.attr
        base = func.value
        key = _base_key(base)
        decl = self.resolve_lock(base)

        if decl is not None:
            if meth in ("wait", "wait_for") and decl.kind == "condition":
                others = held - {decl.canonical}
                if meth == "wait":
                    self.scan.cond_waits.append(_CondWait(
                        cond=decl.canonical, node=call, held=held,
                        in_loop=self.loop_depth > 0))
                if others:
                    self.scan.blocking.append(_Blocking(
                        f"Condition.{meth} while holding "
                        f"{', '.join(sorted(others))}", call, others))
            return

        if self._kind_of(key, "events"):
            if meth == "wait" and held:
                self.scan.blocking.append(_Blocking(
                    f"{key}.wait (Event.wait)", call, held))
            return
        if self._kind_of(key, "queues"):
            if meth in ("get", "put") and held \
                    and not self._bounded_queue_call(call):
                self.scan.blocking.append(_Blocking(
                    f"{key}.{meth} without timeout", call, held))
            elif meth == "join" and held:
                self.scan.blocking.append(_Blocking(
                    f"{key}.join (queue drain)", call, held))
            return
        if self._kind_of(key, "semaphores"):
            if meth == "acquire" and held \
                    and not self._nonblocking_acquire(call):
                self.scan.blocking.append(_Blocking(
                    f"{key}.acquire (semaphore)", call, held))
            return

        if meth == "block_until_ready" and held:
            self.scan.blocking.append(_Blocking(
                ".block_until_ready()", call, held))
        elif meth == "join" and key is not None:
            self.scan.joins.append(_JoinCall(
                key=key.split(".")[-1], node=call, held=held))

        if (isinstance(base, ast.Name) and base.id == "self"
                and meth in self.unit.callbacks and held):
            self.scan.callback_calls.append(_CallbackCall(
                f"self.{meth}", call, held))
        elif (isinstance(base, ast.Name) and base.id == "self"
              and meth in self.unit.methods):
            self.scan.self_calls.append(_SelfCall(meth, call, held))

    @staticmethod
    def _bounded_queue_call(call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "timeout" and not (
                    isinstance(kw.value, ast.Constant)
                    and kw.value.value is None):
                return True
            if kw.arg == "block" and _is_false(kw.value):
                return True
        return False


class ConcurrencyModel:
    """The whole-module concurrency model, built once and memoised on
    the :class:`ModuleContext` (mirrors the jit-registry pattern)."""

    def __init__(self, ctx: ModuleContext):
        self.ctx = ctx
        self.module_unit = UnitInfo(name="<module>", node=ctx.tree,
                                    is_module=True)
        self.class_units: List[UnitInfo] = []
        self.scans: List[_FnScan] = []
        #: terminal names something calls ``.join()`` on, module-wide
        self.join_names: Set[str] = set()

        self._collect_units()
        self._collect_guarded()
        self._scan_functions()
        for scan in self.scans:
            for j in scan.joins:
                self.join_names.add(j.key)

    # -- pass 1: object discovery --------------------------------------

    def _collect_units(self) -> None:
        tree = self.ctx.tree
        for stmt in tree.body:
            self._collect_module_stmt(stmt)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self.class_units.append(self._collect_class(node))

    def _collect_module_stmt(self, stmt: ast.stmt) -> None:
        unit = self.module_unit
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            name, value = stmt.targets[0].id, stmt.value
        elif isinstance(stmt, ast.AnnAssign) \
                and isinstance(stmt.target, ast.Name) \
                and stmt.value is not None:
            name, value = stmt.target.id, stmt.value
        else:
            return
        kind = _factory_kind(value)
        if kind in ("lock", "rlock"):
            unit.locks[name] = LockDecl(key=name, kind=kind, node=value,
                                        canonical=name)
        elif kind == "condition":
            under = _base_key(value.args[0]) if value.args else None
            unit.locks[name] = LockDecl(
                key=name, kind="condition", node=value,
                canonical=under if under else name)
        elif kind == "event":
            unit.events.add(name)
        elif kind == "queue":
            unit.queues.add(name)
        elif kind == "semaphore":
            unit.semaphores.add(name)
        elif isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                                ast.ListComp, ast.SetComp)):
            unit.mutables.add(name)
        elif isinstance(value, ast.Call) \
                and dotted_name(value.func) in ("dict", "list", "set",
                                                "collections.OrderedDict",
                                                "collections.defaultdict",
                                                "collections.deque"):
            unit.mutables.add(name)

    def _collect_class(self, cls: ast.ClassDef) -> UnitInfo:
        unit = UnitInfo(name=cls.name, node=cls, is_module=False)
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                unit.methods[stmt.name] = stmt
        for method in unit.methods.values():
            for node in ast.walk(method):
                self._collect_attr_init(unit, method, node)
        # Second look for conditions: their underlying lock may have
        # been declared after them in source order.
        for decl in unit.locks.values():
            if decl.kind == "condition" and decl.canonical != decl.key \
                    and decl.canonical not in unit.locks:
                decl.canonical = decl.key
        return unit

    def _collect_attr_init(self, unit: UnitInfo, method: ast.AST,
                           node: ast.AST) -> None:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value, ann = node.targets[0], node.value, None
        elif isinstance(node, ast.AnnAssign):
            target, value, ann = node.target, node.value, node.annotation
        else:
            return
        if not (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            return
        key = f"self.{target.attr}"
        kind = _factory_kind(value) if value is not None else None
        if kind in ("lock", "rlock"):
            unit.locks[key] = LockDecl(key=key, kind=kind, node=value,
                                       canonical=key)
        elif kind == "condition":
            under = _base_key(value.args[0]) if value.args else None
            unit.locks[key] = LockDecl(
                key=key, kind="condition", node=value,
                canonical=under if under else key)
        elif kind == "event":
            unit.events.add(key)
        elif kind == "queue":
            unit.queues.add(key)
        elif kind == "semaphore":
            unit.semaphores.add(key)
        # Callback attrs: annotated Callable, or assigned from a
        # callback-named / Callable-annotated parameter of the method.
        ann_src = ast.unparse(ann) if ann is not None else ""
        if "Callable" in ann_src:
            unit.callbacks.add(target.attr)
        elif isinstance(value, ast.Name):
            margs = getattr(method, "args", None)
            params = (margs.posonlyargs + margs.args + margs.kwonlyargs
                      if margs is not None else [])
            for a in params:
                if a.arg != value.id:
                    continue
                p_ann = ast.unparse(a.annotation) if a.annotation else ""
                if "Callable" in p_ann \
                        or _CALLBACK_NAME_RE.search(a.arg):
                    unit.callbacks.add(target.attr)

    # -- pass 2: guarded-by annotations --------------------------------

    def _unit_for(self, node: ast.AST) -> UnitInfo:
        cur = self.ctx.parent.get(id(node))
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                for u in self.class_units:
                    if u.node is cur:
                        return u
            cur = self.ctx.parent.get(id(cur))
        return self.module_unit

    @staticmethod
    def _annotation_line(node: ast.AST,
                         annotated: Dict[int, str]) -> Optional[int]:
        """The guarded-by comment line this statement owns, if any: any
        signature line of a ``def``, or the first/last line of an
        assignment (multiline initialisers put the comment after the
        closing paren)."""
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            first = node.lineno
            last = node.body[0].lineno - 1 if node.body else node.lineno
            for line in range(first, last + 1):
                if line in annotated:
                    return line
            return None
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            for line in (node.lineno, node.end_lineno):
                if line in annotated:
                    return line
        return None

    def _collect_guarded(self) -> None:
        annotated: Dict[int, str] = {}
        for i, text in enumerate(self.ctx.lines, start=1):
            m = _GUARDED_BY_RE.search(text)
            if m:
                annotated[i] = m.group(1)
        if not annotated:
            return
        for node in ast.walk(self.ctx.tree):
            line = self._annotation_line(node, annotated)
            if line is None:
                continue
            spec = annotated[line]
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                unit = self._unit_for(node)
                decl = unit.locks.get(spec) \
                    or self.module_unit.locks.get(spec)
                if decl is None:
                    unit.bad_guards.append((spec, node))
                else:
                    unit.preconditions[node.name] = decl.canonical
                del annotated[line]
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    key = _base_key(t)
                    if key is None:
                        continue
                    unit = (self._unit_for(node)
                            if key.startswith("self.")
                            else self.module_unit)
                    decl = unit.locks.get(spec) \
                        or self.module_unit.locks.get(spec)
                    if decl is None:
                        unit.bad_guards.append((spec, node))
                    else:
                        unit.guarded[key] = (decl.canonical, node)
                if line in annotated:
                    del annotated[line]

    # -- pass 3: function scans ----------------------------------------

    def _scan_functions(self) -> None:
        pending: List[Tuple[UnitInfo, ast.AST, Tuple[str, ...]]] = []
        for unit in self.class_units:
            for name, method in unit.methods.items():
                pre = unit.preconditions.get(name)
                pending.append((unit, method, (pre,) if pre else ()))
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                pre = self.module_unit.preconditions.get(stmt.name)
                pending.append((self.module_unit, stmt,
                                (pre,) if pre else ()))
        seen: Set[int] = set()
        while pending:
            unit, fn, pre = pending.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            scanner = _FnScanner(self.ctx, unit, self.module_unit, fn,
                                 pre_held=pre)
            self.scans.append(scanner.scan_function())
            # Nested defs (thread targets, closures) run on their own
            # stack: scanned with an empty held set, same unit.
            for nested in scanner.nested:
                pending.append((unit, nested, ()))

    # -- derived views --------------------------------------------------

    def unit_scans(self, unit: UnitInfo) -> List[_FnScan]:
        return [s for s in self.scans if s.unit is unit]

    def may_acquire(self, unit: UnitInfo) -> Dict[str, Set[str]]:
        """Method name -> locks it may acquire, transitively through
        ``self.method()`` calls (fixpoint)."""
        direct: Dict[str, Set[str]] = {}
        calls: Dict[str, Set[str]] = {}
        for scan in self.unit_scans(unit):
            name = getattr(scan.fn, "name", None)
            if name is None or scan.fn is not unit.methods.get(name):
                continue
            direct.setdefault(name, set()).update(scan.direct_locks)
            calls.setdefault(name, set()).update(
                c.method for c in scan.self_calls)
        out = {m: set(locks) for m, locks in direct.items()}
        changed = True
        while changed:
            changed = False
            for m, callees in calls.items():
                for callee in callees:
                    extra = out.get(callee, set()) - out[m]
                    if extra:
                        out[m].update(extra)
                        changed = True
        return out

    def order_edges(self, unit: UnitInfo) -> Dict[Tuple[str, str],
                                                  ast.AST]:
        """Lock-order edges (held -> acquired) within one unit,
        including acquisitions reached through self-method calls."""
        edges: Dict[Tuple[str, str], ast.AST] = {}
        may = self.may_acquire(unit) if not unit.is_module else {}
        for scan in self.unit_scans(unit):
            for acq in scan.acquires:
                for h in acq.held:
                    if h != acq.lock:
                        edges.setdefault((h, acq.lock), acq.node)
            for call in scan.self_calls:
                if not call.held:
                    continue
                for b in may.get(call.method, ()):
                    for h in call.held:
                        if h != b:
                            edges.setdefault((h, b), call.node)
        if unit.is_module:
            # Module functions propagate through bare-name calls too —
            # approximate with direct acquires only (conservative).
            pass
        return edges

    @staticmethod
    def find_cycles(edges: Dict[Tuple[str, str], ast.AST]
                    ) -> List[List[str]]:
        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
        cycles: List[List[str]] = []
        seen_cycles: Set[FrozenSet[str]] = set()

        def dfs(node: str, path: List[str], on_path: Set[str],
                done: Set[str]) -> None:
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        cycles.append(cyc)
                    continue
                if nxt in done:
                    continue
                path.append(nxt)
                on_path.add(nxt)
                dfs(nxt, path, on_path, done)
                on_path.discard(nxt)
                path.pop()
            done.add(node)

        done: Set[str] = set()
        for start in sorted(adj):
            if start not in done:
                dfs(start, [start], {start}, done)
        return cycles


def model_for(ctx: ModuleContext) -> ConcurrencyModel:
    cached = getattr(ctx, "_concurrency_model", None)
    if cached is None:
        cached = ConcurrencyModel(ctx)
        ctx._concurrency_model = cached
    return cached


# ---------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------


class LockOrderCycleRule(Rule):
    id = "LC301"
    name = "lock-order-cycle"
    severity = "error"
    description = ("two locks of one class are acquired in both orders "
                   "on different paths — a deadlock waiting for the "
                   "right interleaving")

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        model = model_for(ctx)
        for unit in [model.module_unit] + model.class_units:
            edges = model.order_edges(unit)
            for cyc in model.find_cycles(edges):
                # Anchor the finding at the first edge of the cycle.
                node = edges.get((cyc[0], cyc[1]), unit.node)
                yield self.finding(
                    ctx, node,
                    f"lock-order cycle in {unit.name}: "
                    f"{' -> '.join(cyc)} — acquire these locks in one "
                    f"global order")


class GuardedByRule(Rule):
    id = "LC302"
    name = "unguarded-access"
    severity = "error"
    description = ("state annotated '# guarded-by: <lock>' is accessed "
                   "without that lock held")

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        model = model_for(ctx)
        for unit in [model.module_unit] + model.class_units:
            for spec, node in unit.bad_guards:
                yield self.finding(
                    ctx, node,
                    f"guarded-by names '{spec}' which is not a lock "
                    f"declared in {unit.name}", severity="warning")
            for scan in model.unit_scans(unit):
                fn_name = getattr(scan.fn, "name", None)
                if fn_name == "__init__" and not unit.is_module:
                    continue  # single-threaded construction
                for acc in scan.accesses:
                    entry = unit.guarded.get(acc.key) \
                        or model.module_unit.guarded.get(acc.key)
                    if entry is None:
                        continue
                    lock, _decl = entry
                    if lock in acc.held:
                        continue
                    verb = "written" if acc.store else "read"
                    yield self.finding(
                        ctx, acc.node,
                        f"{acc.key} is guarded by {lock} but {verb} "
                        f"here without it (in "
                        f"{fn_name or '<lambda>'})")


class BlockingUnderLockRule(Rule):
    id = "LC303"
    name = "blocking-under-lock"
    severity = "error"
    description = ("a blocking call (Event.wait, unbounded queue "
                   "get/put, sleep, device sync, join, network I/O) "
                   "runs while a lock is held")

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        model = model_for(ctx)
        for scan in model.scans:
            for b in scan.blocking:
                yield self.finding(
                    ctx, b.node,
                    f"blocking call {b.desc} while holding "
                    f"{', '.join(sorted(b.held))} — every other thread "
                    f"needing that lock stalls behind it")
            # Thread joins under a lock: only flag receivers we have
            # seen created as threads in this module.
            thread_names = {t.bound for s in model.scans
                            for t in s.threads if t.bound}
            for j in scan.joins:
                if j.held and j.key in thread_names:
                    yield self.finding(
                        ctx, j.node,
                        f"joining thread '{j.key}' while holding "
                        f"{', '.join(sorted(j.held))}")


class WaitWithoutPredicateRule(Rule):
    id = "LC304"
    name = "wait-without-predicate"
    severity = "error"
    description = ("Condition.wait outside a while-predicate loop — "
                   "spurious wakeups and stolen notifications break it")

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        model = model_for(ctx)
        for scan in model.scans:
            for w in scan.cond_waits:
                if not w.in_loop:
                    yield self.finding(
                        ctx, w.node,
                        f"Condition.wait on {w.cond} is not inside a "
                        f"while-predicate loop; use "
                        f"'while not pred: cv.wait()'")


class ThreadLeakRule(Rule):
    id = "LC305"
    name = "thread-leak"
    severity = "warning"
    description = ("threading.Thread with neither daemon=True nor a "
                   "reachable join — it outlives shutdown")

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        model = model_for(ctx)
        for scan in model.scans:
            for t in scan.threads:
                if t.daemon:
                    continue
                if t.bound is not None and t.bound in model.join_names:
                    continue
                yield self.finding(
                    ctx, t.node,
                    "thread is neither daemon=True nor joined anywhere "
                    "in this module — it will outlive close()/shutdown")


class CallbackUnderLockRule(Rule):
    id = "LC306"
    name = "callback-under-lock"
    severity = "error"
    description = ("a user-supplied callback is invoked while holding "
                   "the lock that registered it — re-entrancy deadlock")

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        model = model_for(ctx)
        for scan in model.scans:
            for c in scan.callback_calls:
                yield self.finding(
                    ctx, c.node,
                    f"callback {c.name}() invoked while holding "
                    f"{', '.join(sorted(c.held))} — a callback that "
                    f"calls back in deadlocks; capture under the lock, "
                    f"invoke after release")


class DoubleAcquireRule(Rule):
    id = "LC307"
    name = "double-acquire"
    severity = "error"
    description = ("a non-reentrant Lock is acquired on a path that "
                   "already holds it — self-deadlock")

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        model = model_for(ctx)
        for scan in model.scans:
            for d in scan.double_acquires:
                yield self.finding(
                    ctx, d.node,
                    f"{d.lock} is already held here; threading.Lock is "
                    f"not reentrant — this deadlocks the calling "
                    f"thread")
        for unit in model.class_units:
            may = model.may_acquire(unit)
            for scan in model.unit_scans(unit):
                for call in scan.self_calls:
                    reacq = call.held & may.get(call.method, set())
                    for lock in sorted(reacq):
                        decl = unit.locks.get(lock) \
                            or model.module_unit.locks.get(lock)
                        if decl is not None and decl.kind == "rlock":
                            continue
                        yield self.finding(
                            ctx, call.node,
                            f"self.{call.method}() may re-acquire "
                            f"{lock}, already held here — deadlock on "
                            f"a non-reentrant Lock")


class UnguardedGlobalMutationRule(Rule):
    id = "LC308"
    name = "unguarded-global-mutation"
    severity = "error"
    description = ("a thread target mutates a shared module global "
                   "without holding any lock")

    def check(self, ctx: ModuleContext) -> Iterator["Finding"]:
        model = model_for(ctx)
        target_ids = {id(t.target_fn) for s in model.scans
                      for t in s.threads if t.target_fn is not None}
        if not target_ids:
            return
        for scan in model.scans:
            if id(scan.fn) not in target_ids:
                continue
            for m in scan.global_muts:
                yield self.finding(
                    ctx, m.node,
                    f"module global '{m.name}' mutated from a thread "
                    f"target without holding a lock — racing writes "
                    f"tear state")


LC_RULES = (
    LockOrderCycleRule(),
    GuardedByRule(),
    BlockingUnderLockRule(),
    WaitWithoutPredicateRule(),
    ThreadLeakRule(),
    CallbackUnderLockRule(),
    DoubleAcquireRule(),
    UnguardedGlobalMutationRule(),
)

LC_RULES_BY_ID = {r.id: r for r in LC_RULES}

__all__ = ["LC_RULES", "LC_RULES_BY_ID", "ConcurrencyModel", "model_for"]
