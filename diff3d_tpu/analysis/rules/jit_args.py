"""GL105: shape-like jit parameter left traced (retracing hazard).

A parameter named ``shape`` / ``size`` / ``axis`` / ... that reaches a
``jax.jit`` boundary as a *traced* argument cannot actually stay traced
— the first use in ``jnp.zeros(shape)`` or ``x.reshape(size)``
concretizes it, so every distinct value triggers a silent retrace.  The
recompilation storm shows up as a perf cliff, never as an error (the
BENCH history has the scars).  The fix is one keyword:
``static_argnums``/``static_argnames``.

The rule only fires when the wrapped function is resolvable in-module
and the parameter's name is unambiguously shape-like — anything fuzzier
belongs to the runtime sentinel, not the linter.
"""

from __future__ import annotations

import ast
from typing import Iterator

from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import ModuleContext, param_names

_SHAPE_LIKE = {"shape", "shapes", "size", "sizes", "axis", "axes",
               "ndim", "num_devices", "n_lanes"}


class StaticShapeArgRule(Rule):
    id = "GL105"
    name = "missing-static-argnums"
    severity = "warning"
    description = ("shape-like parameter of a jitted function is not in "
                   "static_argnums/static_argnames")

    def check(self, ctx: ModuleContext) -> Iterator:
        for site in ctx.jit_sites:
            if site.fn is None:
                continue
            names = param_names(site.fn)
            static = set(site.static_argnames)
            for i in site.static_argnums:
                if 0 <= i < len(names):
                    static.add(names[i])
            for name in names:
                if name in _SHAPE_LIKE and name not in static:
                    yield self.finding(
                        ctx, site.call,
                        f"jitted function parameter '{name}' looks "
                        "shape-like but is traced — every distinct "
                        "value retraces; add static_argnames="
                        f"('{name}',) (or pass it via closure)")
