"""GL106: timing device work without block_until_ready.

JAX dispatch is async: ``t1 - t0`` around a jitted call measures the
*enqueue*, not the compute — the classic way a benchmark reports a 400x
"speedup" that is actually an unawaited future.  The rule finds pairs of
wall-clock captures (``time.monotonic``/``perf_counter``/``time``) in
one statement block with device work dispatched in between and no sync
— ``block_until_ready`` / ``device_get`` / ``np.asarray`` / ``.item()``
— anywhere in the timed span.

"Device work" is deliberately narrow: calls to module-local jitted
bindings (the jit registry) and the repo's known device entry points
(``step_many`` / ``synthesize*`` / ``.apply``).  Timing host code with
two clock reads is fine and stays silent.
"""

from __future__ import annotations

import ast
from typing import Iterator, List

from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import ModuleContext, dotted_name

_CLOCKS = {"time.monotonic", "time.perf_counter", "time.time",
           "monotonic", "perf_counter"}
_SYNC_ATTRS = {"block_until_ready", "item", "tolist"}
_SYNC_CALLS = {"jax.block_until_ready", "jax.device_get", "np.asarray",
               "np.array", "numpy.asarray", "jax.effects_barrier"}
_DEVICE_ATTRS = {"step_many", "synthesize", "synthesize_many", "apply"}


def _has_clock(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Call)
               and dotted_name(n.func) in _CLOCKS
               for n in ast.walk(node))


def _is_bare_capture(stmt: ast.AST) -> bool:
    """``t0 = time.monotonic()`` — the *start* of a timed region (an
    arbitrary clock-bearing statement may instead be the end of one)."""
    return (isinstance(stmt, ast.Assign)
            and isinstance(stmt.value, ast.Call)
            and dotted_name(stmt.value.func) in _CLOCKS)


def _classify_span(stmts: List[ast.AST]):
    """(device_call, sync_found) over a span of statements, nested
    defs included (a closure defined in the span runs inside it)."""
    device = sync = False
    for stmt in stmts:
        for n in ast.walk(stmt):
            if not isinstance(n, ast.Call):
                continue
            fname = dotted_name(n.func)
            if fname in _SYNC_CALLS:
                sync = True
            elif isinstance(n.func, ast.Attribute):
                if n.func.attr in _SYNC_ATTRS:
                    sync = True
                elif n.func.attr in _DEVICE_ATTRS:
                    device = True
    return device, sync


class UnsyncedTimingRule(Rule):
    id = "GL106"
    name = "unsynced-timing"
    severity = "warning"
    description = ("wall-clock timing around device work without "
                   "block_until_ready — measures dispatch, not compute")

    def _scan_block(self, ctx: ModuleContext, stmts: List[ast.AST],
                    module_ctx: ModuleContext):
        clock_idx = [i for i, s in enumerate(stmts) if _has_clock(s)]
        starts = [i for i in clock_idx if _is_bare_capture(stmts[i])]
        for a in starts:
            later = [i for i in clock_idx if i > a]
            if not later:
                continue
            b = later[0]
            span = stmts[a + 1:b]
            if not span:
                continue
            # jitted-binding calls inside the span count as device work
            device, sync = _classify_span(span)
            if not device:
                for stmt in span:
                    for n in ast.walk(stmt):
                        if (isinstance(n, ast.Call)
                                and isinstance(n.func, ast.Name)
                                and module_ctx.jit_site_for_callable_name(
                                    n.func.id, False) is not None):
                            device = True
            # the closing clock statement may carry its own sync:
            #   dt = time.monotonic() - t0  after  out = np.asarray(r)
            _, sync_tail = _classify_span([stmts[b]])
            if device and not (sync or sync_tail):
                yield self.finding(
                    ctx, stmts[b],
                    "wall-clock delta around device work without a "
                    "block_until_ready/fetch in the timed span — the "
                    "measurement stops at dispatch, not completion")

    def check(self, ctx: ModuleContext) -> Iterator:
        blocks: List[List[ast.AST]] = [ctx.tree.body]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                blocks.append(node.body)
            elif isinstance(node, (ast.For, ast.While, ast.With, ast.If,
                                   ast.Try)):
                blocks.append(node.body)
                orelse = getattr(node, "orelse", None)
                if orelse:
                    blocks.append(orelse)
                finalbody = getattr(node, "finalbody", None)
                if finalbody:
                    blocks.append(finalbody)
        for block in blocks:
            yield from self._scan_block(ctx, block, ctx)
