"""GL101: PRNG key reuse without an intervening split.

JAX keys are consumed, not streamed: passing the same key to two
``jax.random`` draws yields correlated samples (and passing a key to
``split`` then reusing the *parent* silently replays the child stream).
The rule tracks, per function scope, every plain-name key handed to a
consuming ``jax.random.*`` call; a second consumption of the same name
with no reassignment in between is flagged at the second call.

Sanctioned patterns stay silent:

    k1, k2 = jax.random.split(key)         # key reassigned? no — but
    jax.random.normal(k1, ...)             # key itself is never reused
    rng, k = jax.random.split(rng)         # carry update: rng re-stored
    jax.random.fold_in(key, i)             # fold_in derives, not draws
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Tuple

from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import ModuleContext

#: jax.random attrs that do NOT consume their key argument.
_NON_CONSUMING = {"PRNGKey", "key", "fold_in", "key_data",
                  "wrap_key_data", "key_impl", "clone", "default_prng_impl"}


class RngReuseRule(Rule):
    id = "GL101"
    name = "rng-key-reuse"
    severity = "error"
    description = ("the same PRNG key is consumed by two jax.random "
                   "calls without a split/reassignment in between")

    def _consuming_call_key(self, ctx: ModuleContext,
                            node: ast.Call) -> str:
        """The plain-name key argument of a consuming jax.random call,
        or '' when the call is not one."""
        if not isinstance(node.func, ast.Attribute):
            return ""
        from diff3d_tpu.analysis.rules.context import dotted_name
        base = dotted_name(node.func.value)
        if base not in ctx.random_aliases:
            return ""
        if node.func.attr in _NON_CONSUMING:
            return ""
        if not node.args:
            return ""
        first = node.args[0]
        return first.id if isinstance(first, ast.Name) else ""

    def check(self, ctx: ModuleContext) -> Iterator:
        # Group consuming calls + stores by enclosing function (None =
        # module scope), then scan each scope in source order.
        scopes: Dict[int, List[Tuple[Tuple[int, int], str, str,
                                     ast.AST]]] = {}

        def scope_key(node):
            fn = ctx.enclosing_function(node)
            return id(fn) if fn is not None else 0

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                key = self._consuming_call_key(ctx, node)
                if key:
                    scopes.setdefault(scope_key(node), []).append(
                        ((node.lineno, node.col_offset + 1), "consume",
                         key, node))
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Store):
                # Stores sort after same-line consumes (col bumped above)
                # only via assignment-target position; give stores a
                # line-end bias so `rng, k = split(rng)` re-arms rng.
                scopes.setdefault(scope_key(node), []).append(
                    ((node.lineno, 10_000), "store", node.id, node))

        for events in scopes.values():
            events.sort(key=lambda e: e[0])
            consumed_at: Dict[str, int] = {}
            for _, kind, name, node in events:
                if kind == "store":
                    consumed_at.pop(name, None)
                elif name in consumed_at:
                    yield self.finding(
                        ctx, node,
                        f"PRNG key '{name}' already consumed on line "
                        f"{consumed_at[name]} — split it (or reassign "
                        "the carry) before drawing again")
                    consumed_at[name] = node.lineno
                else:
                    consumed_at[name] = node.lineno
