"""GL101: PRNG key reuse without an intervening split.

JAX keys are consumed, not streamed: passing the same key to two
``jax.random`` draws yields correlated samples (and passing a key to
``split`` then reusing the *parent* silently replays the child stream).
The rule tracks, per function scope, every plain-name key handed to a
consuming ``jax.random.*`` call; a second consumption of the same name
with no reassignment in between is flagged at the second call.

Sanctioned patterns stay silent:

    k1, k2 = jax.random.split(key)         # key reassigned? no — but
    jax.random.normal(k1, ...)             # key itself is never reused
    rng, k = jax.random.split(rng)         # carry update: rng re-stored
    jax.random.fold_in(key, i)             # fold_in derives, not draws

Since ISSUE 15 the scan itself lives in ``analysis/rngflow.py`` and is
shared with rngcheck's interprocedural RC501/RC502: GL101 is the fast
single-scope alias (this pass stays pure-AST, no call graph), and the
cross-function cases — the same key handed to two functions that each
draw from it — are rngcheck's jurisdiction.  One scanner, disjoint
jurisdictions: the two tools cannot disagree on a shared case.
"""

from __future__ import annotations

from typing import Iterator

from diff3d_tpu.analysis.rngflow import NON_CONSUMING, linear_violations
from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import ModuleContext

#: Back-compat alias — the canonical set moved to rngflow.
_NON_CONSUMING = NON_CONSUMING


class RngReuseRule(Rule):
    id = "GL101"
    name = "rng-key-reuse"
    severity = "error"
    description = ("the same PRNG key is consumed by two jax.random "
                   "calls without a split/reassignment in between")

    def check(self, ctx: ModuleContext) -> Iterator:
        for v in linear_violations(ctx):
            yield self.finding(
                ctx, v.node,
                f"PRNG key '{v.name}' already consumed on line "
                f"{v.prev_line} — split it (or reassign the carry) "
                "before drawing again (cross-function lineage: "
                "rngcheck RC501)")
