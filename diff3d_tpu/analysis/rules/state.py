"""GL107: mutable state captured by (or leaking out of) traced code.

Two shapes:

  * a **mutable default argument** (``def f(x, cache={})``) on any
    function — in ordinary Python it is a shared-state footgun; on a
    function that ends up traced it is worse, because the default is
    evaluated once and then *baked into every compiled program* that
    closes over it;
  * a ``global`` declaration inside a traced function — writes from a
    traced body run once per TRACE, not once per call, so the global
    updates exactly when a retrace happens and never again: state that
    silently freezes after warmup.
"""

from __future__ import annotations

import ast
from typing import Iterator

from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import ModuleContext, dotted_name

_MUTABLE_CALLS = {"list", "dict", "set", "bytearray",
                  "collections.defaultdict", "defaultdict",
                  "collections.OrderedDict", "OrderedDict"}


def _is_mutable_literal(node: ast.AST) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return dotted_name(node.func) in _MUTABLE_CALLS
    return False


class MutableTraceStateRule(Rule):
    id = "GL107"
    name = "mutable-trace-state"
    severity = "warning"
    description = ("mutable default argument, or `global` mutation "
                   "inside a traced function")

    def check(self, ctx: ModuleContext) -> Iterator:
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defaults = (node.args.defaults
                            + [d for d in node.args.kw_defaults
                               if d is not None])
                for d in defaults:
                    if _is_mutable_literal(d):
                        yield self.finding(
                            ctx, d,
                            f"mutable default argument in "
                            f"'{node.name}' — evaluated once and "
                            "shared across calls (and baked into any "
                            "trace that captures it); default to None "
                            "and construct inside")
            if isinstance(node, ast.Global):
                fn = ctx.enclosing_function(node)
                if fn is not None and id(fn) in ctx.traced_functions:
                    yield self.finding(
                        ctx, node,
                        f"`global {', '.join(node.names)}` inside a "
                        "traced function — the write runs once per "
                        "trace, not per call; thread state through the "
                        "carry instead", severity="error")
