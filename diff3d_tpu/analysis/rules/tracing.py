"""GL102 + GL103: what must not happen inside a traced function.

GL102 — Python ``if``/``while`` on a traced value.  Inside a jitted (or
scan/vmap/grad) body, branching on a parameter raises
``TracerBoolConversionError`` at trace time *if you are lucky* — and
silently bakes one branch into the program if the value happens to be
concrete during tracing but traced in production.  Concrete-at-trace
tests stay silent: ``x is None``, ``isinstance(x, ...)``, and tests that
only touch ``x.shape`` / ``x.ndim`` / ``x.dtype`` / ``len(x)`` (shapes
are static under trace), plus parameters the jit site marks static.

GL103 — host sync inside a traced body.  ``.item()`` / ``.tolist()`` /
``float(param)`` / ``int(param)`` / ``np.asarray`` / ``np.array`` /
``jax.device_get`` force a device->host round trip; under jit they
either fail at trace time or, in op-by-op fallback paths, silently
serialize the pipeline — the exact class of hidden-transfer bug the
transfer-guard tests exist for, caught here before it runs.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import (ModuleContext, dotted_name,
                                               param_names)

_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size"}
_HOST_SYNC_ATTRS = {"item", "tolist", "to_py"}
_HOST_SYNC_CALLS = {"jax.device_get", "np.asarray", "np.array",
                    "numpy.asarray", "numpy.array", "onp.asarray"}


def _concrete_name_loads(test: ast.AST) -> Set[str]:
    """Names in ``test`` whose use is concrete at trace time (shape
    attrs, len(), isinstance, `is None` comparisons)."""
    concrete: Set[str] = set()
    for node in ast.walk(test):
        if isinstance(node, ast.Attribute) and node.attr in _SHAPE_ATTRS:
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name):
                    concrete.add(n.id)
        elif isinstance(node, ast.Call):
            fname = dotted_name(node.func)
            if fname in ("len", "isinstance", "callable", "hasattr",
                         "getattr", "type"):
                for arg in node.args:
                    for n in ast.walk(arg):
                        if isinstance(n, ast.Name):
                            concrete.add(n.id)
        elif isinstance(node, ast.Compare):
            comps = [node.left] + list(node.comparators)
            if any(isinstance(c, ast.Constant) and c.value is None
                   for c in comps):
                for c in comps:
                    if isinstance(c, ast.Name):
                        concrete.add(c.id)
    return concrete


def _own_statements(fn: ast.AST):
    """Nodes of ``fn``'s body excluding nested function bodies (those
    are traced contexts of their own and visited separately)."""
    body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
    stack = list(body) if isinstance(body, list) else [body]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


class TracedBranchRule(Rule):
    id = "GL102"
    name = "traced-python-branch"
    severity = "error"
    description = ("Python if/while on a traced parameter inside a "
                   "jit/scan/vmap body — use lax.cond/lax.select")

    def check(self, ctx: ModuleContext) -> Iterator:
        for fn in ctx.traced_nodes():
            params = set(param_names(fn)) - ctx.static_params_of(fn)
            if not params:
                continue
            for node in _own_statements(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                concrete = _concrete_name_loads(node.test)
                hot = sorted(
                    n.id for n in ast.walk(node.test)
                    if isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Load)
                    and n.id in params and n.id not in concrete)
                if hot:
                    kw = "while" if isinstance(node, ast.While) else "if"
                    yield self.finding(
                        ctx, node,
                        f"Python `{kw}` on traced parameter(s) "
                        f"{', '.join(hot)} inside a traced function — "
                        "branch with lax.cond/lax.select or mark the "
                        "argument static")


class HostSyncRule(Rule):
    id = "GL103"
    name = "host-sync-in-jit"
    severity = "error"
    description = ("host<->device sync (.item()/float()/np.asarray/"
                   "device_get) inside a traced body")

    def check(self, ctx: ModuleContext) -> Iterator:
        for fn in ctx.traced_nodes():
            params = set(param_names(fn)) - ctx.static_params_of(fn)
            for node in _own_statements(fn):
                if not isinstance(node, ast.Call):
                    continue
                fname = dotted_name(node.func)
                if fname in _HOST_SYNC_CALLS:
                    yield self.finding(
                        ctx, node,
                        f"{fname}() inside a traced body forces a host "
                        "sync — keep the value on device (jnp.*) or "
                        "move the conversion outside the jit boundary")
                elif (isinstance(node.func, ast.Attribute)
                        and node.func.attr in _HOST_SYNC_ATTRS
                        and not node.args):
                    yield self.finding(
                        ctx, node,
                        f".{node.func.attr}() inside a traced body is a "
                        "device->host sync — return the array and "
                        "convert outside the traced function")
                elif (fname in ("float", "int", "bool") and node.args
                        and isinstance(node.args[0], ast.Name)
                        and node.args[0].id in params):
                    yield self.finding(
                        ctx, node,
                        f"{fname}({node.args[0].id}) concretizes a "
                        "traced parameter — this fails under jit; use "
                        "astype / keep it traced")
