"""GL104: read of a donated buffer after the donating call.

``donate_argnums`` hands the argument's device buffer to XLA for in-place
reuse — after the call returns, the caller's handle points at freed (or
repurposed) memory.  jax raises on *device* access, but a numpy view or
a zero-copy alias keeps "working" against garbage: PR 3's latent heap
corruption was exactly this, and it surfaced hundreds of steps away from
the bug.

The rule tracks every module-local binding of a donating jit —
``f = jax.jit(g, donate_argnums=(1,))`` and
``self._f = jax.jit(...)`` alike — and, per function, walks statements
in evaluation order: a plain-name argument passed at a donated position
becomes ARMED; a later load of that name before a re-store is flagged.
Loops are scanned twice (a donation at the bottom of iteration N is live
at the top of iteration N+1); ``if``/``else`` branches fork the armed
set and only survive the join when neither branch re-stored the name.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import JitSite, ModuleContext


class _BlockScanner:
    def __init__(self, rule: "DonatedReuseRule", ctx: ModuleContext):
        self.rule = rule
        self.ctx = ctx
        self.findings: List = []
        self._seen: Set[Tuple[int, str]] = set()

    # -- event extraction ------------------------------------------------

    def _donating_site(self, call: ast.Call) -> JitSite:
        func = call.func
        if isinstance(func, ast.Name):
            site = self.ctx.jit_site_for_callable_name(func.id, False)
        elif isinstance(func, ast.Attribute):
            site = self.ctx.jit_site_for_callable_name(func.attr, True)
        else:
            site = None
        return site if site is not None and site.donate_argnums else None

    def _expr_events(self, node: ast.AST):
        """(loads, donations) of one expression, in source order."""
        loads: List[ast.Name] = []
        donations: List[Tuple[ast.Call, str]] = []
        for n in ast.walk(node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                loads.append(n)
            elif isinstance(n, ast.Call):
                site = self._donating_site(n)
                if site is None:
                    continue
                for i in site.donate_argnums:
                    if i < len(n.args) and isinstance(n.args[i],
                                                      ast.Name):
                        donations.append((n, n.args[i].id))
        loads.sort(key=lambda n: (n.lineno, n.col_offset))
        return loads, donations

    def _stores(self, node: ast.AST) -> List[str]:
        return [n.id for n in ast.walk(node)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, (ast.Store, ast.Del))]

    # -- armed-state interpreter ----------------------------------------

    def _flag(self, name_node: ast.Name, donated_line: int):
        key = (name_node.lineno, name_node.id)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(self.rule.finding(
            self.ctx, name_node,
            f"'{name_node.id}' was donated on line {donated_line} "
            f"(donate_argnums) and is read here — the buffer no longer "
            "belongs to the caller; use the returned carry instead"))

    def _eval(self, node: ast.AST, armed: Dict[str, int]) -> None:
        """Process one expression: loads fire against armed names, then
        donations arm."""
        # Loads are processed before this expression's donations arm, so
        # the arming call never flags its own argument — but a name still
        # armed from an EARLIER statement (or the previous loop pass)
        # fires even when this expression re-donates it: passing an
        # already-consumed buffer back into a donating call is as dead a
        # read as any other.
        loads, donations = self._expr_events(node)
        for n in loads:
            if n.id in armed:
                self._flag(n, armed[n.id])
                armed.pop(n.id, None)
        for call, name in donations:
            armed[name] = call.lineno

    def scan_block(self, stmts, armed: Dict[str, int]) -> Dict[str, int]:
        for stmt in stmts:
            armed = self._scan_stmt(stmt, armed)
        return armed

    def _scan_stmt(self, stmt, armed: Dict[str, int]) -> Dict[str, int]:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            return armed        # separate scope, scanned on its own
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                             ast.Return, ast.Expr, ast.Raise,
                             ast.Assert, ast.Delete)):
            value = getattr(stmt, "value", None)
            if isinstance(stmt, ast.AugAssign):
                # load of the target happens before the store
                if (isinstance(stmt.target, ast.Name)
                        and stmt.target.id in armed):
                    self._flag(stmt.target, armed[stmt.target.id])
                    armed.pop(stmt.target.id, None)
            if value is not None:
                self._eval(value, armed)
            if isinstance(stmt, ast.Assert) and stmt.test is not None:
                self._eval(stmt.test, armed)
            for name in self._stores(stmt):
                armed.pop(name, None)
            return armed
        if isinstance(stmt, ast.If):
            self._eval(stmt.test, armed)
            a1 = self.scan_block(stmt.body, dict(armed))
            a2 = self.scan_block(stmt.orelse, dict(armed))
            # survive the join only when no branch re-stored the name
            return {k: v for k, v in {**a1, **a2}.items()
                    if k in a1 and k in a2 or k not in armed}
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._eval(stmt.iter, armed)
            for name in self._stores(stmt.target):
                armed.pop(name, None)
            # twice: a donation at the bottom is live at the next top
            armed = self.scan_block(stmt.body, armed)
            armed = self.scan_block(stmt.body, armed)
            return self.scan_block(stmt.orelse, armed)
        if isinstance(stmt, ast.While):
            self._eval(stmt.test, armed)
            armed = self.scan_block(stmt.body, armed)
            self._eval(stmt.test, armed)
            armed = self.scan_block(stmt.body, armed)
            return self.scan_block(stmt.orelse, armed)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval(item.context_expr, armed)
                if item.optional_vars is not None:
                    for name in self._stores(item.optional_vars):
                        armed.pop(name, None)
            return self.scan_block(stmt.body, armed)
        if isinstance(stmt, ast.Try):
            armed = self.scan_block(stmt.body, armed)
            for handler in stmt.handlers:
                armed = self.scan_block(handler.body, dict(armed))
            armed = self.scan_block(stmt.orelse, armed)
            return self.scan_block(stmt.finalbody, armed)
        # fallthrough (pass, break, continue, global, import, ...)
        value = getattr(stmt, "value", None)
        if value is not None and isinstance(value, ast.AST):
            self._eval(value, armed)
        return armed


class DonatedReuseRule(Rule):
    id = "GL104"
    name = "donated-buffer-reuse"
    severity = "error"
    description = ("a variable passed at a donate_argnums position is "
                   "read after the donating call without reassignment")

    def check(self, ctx: ModuleContext) -> Iterator:
        if not any(site.donate_argnums for site in ctx.jit_sites):
            return
        scanner = _BlockScanner(self, ctx)
        # module body + every function body, each scanned independently
        scanner.scan_block(ctx.tree.body, {})
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scanner.scan_block(node.body, {})
        yield from scanner.findings
