"""graftlint rule registry.

Rule IDs are stable API (suppression comments and the baseline reference
them):

  GL001  parse-error            file does not parse (engine-emitted)
  GL002  reasonless-suppression suppression without a (reason)
  GL101  rng-key-reuse          PRNG key consumed twice without split
  GL102  traced-python-branch   Python if/while on a traced value
  GL103  host-sync-in-jit       .item()/np.asarray/device_get in trace
  GL104  donated-buffer-reuse   read after donate_argnums donation
  GL105  missing-static-argnums shape-like jit param left traced
  GL106  unsynced-timing        timing device work without sync
  GL107  mutable-trace-state    mutable defaults / global in trace
  GL108  half-specified-shardings jit on a mesh path missing in/out specs
  GL109  jit-closure-constant-capture jit closes over a local device array
"""

from diff3d_tpu.analysis.rules.donation import DonatedReuseRule
from diff3d_tpu.analysis.rules.jit_args import StaticShapeArgRule
from diff3d_tpu.analysis.rules.rng import RngReuseRule
from diff3d_tpu.analysis.rules.sharding import (ClosedOverArrayRule,
                                                ShardingSpecRule)
from diff3d_tpu.analysis.rules.state import MutableTraceStateRule
from diff3d_tpu.analysis.rules.timing import UnsyncedTimingRule
from diff3d_tpu.analysis.rules.tracing import HostSyncRule, TracedBranchRule

ALL_RULES = (
    RngReuseRule(),
    TracedBranchRule(),
    HostSyncRule(),
    DonatedReuseRule(),
    StaticShapeArgRule(),
    UnsyncedTimingRule(),
    MutableTraceStateRule(),
    ShardingSpecRule(),
    ClosedOverArrayRule(),
)

RULES_BY_ID = {r.id: r for r in ALL_RULES}

__all__ = ["ALL_RULES", "RULES_BY_ID"]
