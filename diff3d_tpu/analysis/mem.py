"""HLO-level memory & recompute analyzer (memcheck's engine).

shardcheck (``analysis/ir.py``) pins what XLA lowered on the *comms*
axis; this module pins the *memory* axis of the same compiled programs —
the three regressions that silently eat HBM or per-step FLOPs:

  * **peak footprint drift** — the compiled executable's memory analysis
    (argument / output / temp / generated-code bytes, aliased bytes
    counted once) moves because an optimisation boundary shifted, and a
    program that used to fit a replica slice no longer does.  The
    multi-replica router's admission control needs these numbers to be
    *pinned*, not re-measured per deploy.
  * **ineffective donation** — the Python layer requested
    ``donate_argnums`` but the donated buffer was never aliased to an
    output: either jax could not pair it at lowering time (no
    shape/dtype-matching output — the classic silent copy) or XLA
    declined the alias at compile time.  The buffer then lives twice.
  * **scan-invariant recompute** — ops inside a ``lax.scan`` /
    ``stablehlo.while`` body whose inputs never change across
    iterations: they re-run every step for the same answer.  The 3DiM
    sampler's conditioning branch (clean frame + pose rays, constant
    across all 256 denoise steps of a view) is the repo's canonical
    case — this pass turns "we recompute the conditioning" from a hunch
    into a pinned FLOPs/bytes number (hoist-vs-remat tradeoffs in the
    spirit of Chen et al., sublinear-memory training).

Extraction sources, mirroring ir.py's philosophy (parse what the
compiler actually said, not what the Python source hoped):

  * ``lowered.args_info`` — per-flattened-argument *requested* donation
    flags (survives even when lowering dropped the pairing);
  * the lowered StableHLO text — ``tf.aliasing_output`` /
    ``jax.buffer_donor`` arg attributes (what jax established) and the
    ``stablehlo.while`` regions for the loop-invariance dataflow pass;
  * ``compiled.memory_analysis()`` — the executable's byte accounting;
  * the compiled HLO module header's ``input_output_alias`` table —
    what XLA actually aliased.

``analysis/membudgets.py`` diffs :class:`MemoryReport`s against
committed manifests under ``runs/memcheck/`` (rules MC4xx);
``analysis/memcheck.py`` is the CLI over the shardcheck program
registry; ``bench.py`` and serving ``/stats`` embed
:func:`memory_summary` blocks next to the comms blocks.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

from diff3d_tpu.analysis.ir import _DTYPE_BYTES

#: Ops that move/reshape bytes without arithmetic — 0 FLOPs.
_MOVEMENT_OPS = frozenset({
    "reshape", "transpose", "broadcast_in_dim", "broadcast", "slice",
    "dynamic_slice", "dynamic_update_slice", "concatenate", "pad",
    "convert", "iota", "constant", "reverse", "gather", "scatter",
    "bitcast_convert", "get_tuple_element", "tuple", "copy",
    "optimization_barrier", "return", "custom_call", "after_all",
})

_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_VAR_RE = re.compile(r"%[\w.#]+")
# `%4:3 = stablehlo.while(` / `%8 = stablehlo.add` / `stablehlo.return`
# / generic-syntax region ops like `%88 = "stablehlo.scatter"(...) ({`
_STMT_RE = re.compile(
    r"^\s*(?:(%[\w.]+)(?::(\d+))?\s*=\s*)?"
    r"((?:\"stablehlo\.\w+\")"
    r"|(?:stablehlo\.\w+|func\.call|call|chlo\.\w+|return)\b)(.*)$")
_CALLEE_RE = re.compile(r"@([\w.\"]+)")
_FUNC_RE = re.compile(r"^\s*func\.func\s+(?:public|private)?\s*@([\w.\"]+)"
                      r"\((.*)$")
_CONTRACT_RE = re.compile(r"contracting_dims\s*=\s*\[([0-9, ]*)\]")
_KERNEL_O_RE = re.compile(r"x\[([^\]]*)\]->")
_ALIAS_RE = re.compile(
    r"\{([0-9, ]*)\}:\s*\((\d+),\s*\{[0-9, ]*\},\s*(may-alias|must-alias)\)")
_ALIAS_HEADER_RE = re.compile(r"input_output_alias=\{(.*?)\}(?:, |\n)",
                              re.DOTALL)
_ARG_ATTR_RE = re.compile(
    r"%arg(\d+):\s*tensor<([^>]*)>((?:\s*\{)?)")
_SHARDING_ATTR_RE = re.compile(r'mhlo\.sharding\s*=\s*"([^"]*)"')
_SHARDING_DEVICES_RE = re.compile(r"devices=\[([0-9,\s]+)\]")
_LAST_TILE_DIMS_RE = re.compile(r"last_tile_dims=\{([^}]*)\}")


def _tensor_numel_dtype(t: str) -> Tuple[int, str]:
    """``"8x4x8xf32"`` -> (256, "f32"); ``"i32"`` -> (1, "i32")."""
    parts = t.replace(" ", "").split("x")
    dims, dtype = parts[:-1], parts[-1]
    n = 1
    for d in dims:
        if d.isdigit():
            n *= int(d)
    return n, dtype


def _tensor_bytes(t: str) -> int:
    n, dtype = _tensor_numel_dtype(t)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _shard_count(sharding: Optional[str]) -> int:
    """How many shards an ``mhlo.sharding`` annotation splits a tensor
    into — the divisor that turns the global StableHLO tensor size into
    the per-device bytes ``memory_analysis()`` accounts in.

    ``{replicated}`` / ``{maximal device=k}`` / absent -> 1;
    ``{devices=[8,1,1]<=[8]}`` -> 8;
    ``{devices=[2,1,4]<=[8] last_tile_dim_replicate}`` -> 2 (the last
    tile dim replicates across 4 devices, it does not tile);
    ``last_tile_dims={...}`` subgroup dims likewise do not tile.
    """
    if not sharding:
        return 1
    m = _SHARDING_DEVICES_RE.search(sharding)
    if not m:
        return 1
    dims = [int(d) for d in m.group(1).replace(" ", "").split(",") if d]
    lm = _LAST_TILE_DIMS_RE.search(sharding)
    if lm:
        drop = len([e for e in lm.group(1).split(",") if e.strip()])
    elif "last_tile_dim_replicate" in sharding:
        drop = 1
    else:
        drop = 0
    tiles = 1
    for d in (dims[:len(dims) - drop] if drop else dims):
        tiles *= d
    return max(1, tiles)


# -- donation tables ---------------------------------------------------


@dataclasses.dataclass
class DonationEntry:
    """One flattened entry argument's donation story, end to end."""

    arg_index: int
    type: str                 # GLOBAL tensor type text, e.g. "8x4x8x8x3xf32"
    bytes: int                # PER-DEVICE bytes (global size / shard_count)
    #                           — the unit memory_analysis() accounts in
    requested: bool           # Python layer asked (donate_argnums/donor)
    lowered: bool             # jax established an alias / donor mark
    effective: bool           # XLA's compiled module aliases this param
    output_index: Optional[int] = None   # aliased output, when effective
    shard_count: int = 1      # from the arg's mhlo.sharding annotation

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def parse_arg_donations(stablehlo_text: str) -> Dict[int, dict]:
    """Per-arg donation attributes of ``@main``: ``tf.aliasing_output``
    (jax paired the donated arg with an output), ``jax.buffer_donor``
    (donated, pairing left to XLA), and the ``mhlo.sharding`` annotation
    (the tensor type is the GLOBAL shape; the sharding says how many
    devices split it)."""
    m = re.search(r"func\.func\s+public\s+@main\((.*)$",
                  stablehlo_text, re.MULTILINE)
    if not m:
        return {}
    sig = m.group(1)
    out: Dict[int, dict] = {}
    # Split the signature on argument starts; each chunk carries that
    # arg's type and (possibly) attribute dict.
    chunks = re.split(r"%arg(\d+):", sig)[1:]
    for idx_s, body in zip(chunks[0::2], chunks[1::2]):
        idx = int(idx_s)
        tm = _TENSOR_RE.search(body)
        ttype = tm.group(1) if tm else ""
        am = re.search(r"tf\.aliasing_output\s*=\s*(\d+)", body)
        donor = "jax.buffer_donor" in body
        sm = _SHARDING_ATTR_RE.search(body)
        out[idx] = {
            "type": ttype,
            "aliasing_output": int(am.group(1)) if am else None,
            "buffer_donor": donor,
            "sharding": sm.group(1) if sm else None,
        }
    return out


def parse_input_output_aliases(hlo_text: str) -> List[dict]:
    """The compiled module header's ``input_output_alias`` table —
    what XLA *actually* aliased, post-optimisation."""
    header = hlo_text.split("\n\n", 1)[0]
    pos = header.find("input_output_alias=")
    if pos < 0:
        return []
    out = []
    # The alias-entry shape `{o}: (p, {}, may-alias)` is distinctive
    # enough to findall directly; non-greedy brace matching trips over
    # the nested `{}` index field.
    for outidx, param, kind in _ALIAS_RE.findall(header[pos:]):
        first = outidx.split(",")[0].strip()
        out.append({"output_index": int(first) if first else 0,
                    "param": int(param), "kind": kind})
    return out


def donation_table(requested: Sequence[bool],
                   lowered_attrs: Dict[int, dict],
                   aliases: Sequence[dict]) -> List[DonationEntry]:
    """Join the three donation sources into one per-arg table.  Only args
    that were requested OR marked at lowering OR aliased appear."""
    aliased_params = {a["param"]: a for a in aliases}
    indices = sorted(
        set(i for i, r in enumerate(requested) if r)
        | set(i for i, a in lowered_attrs.items()
              if a["aliasing_output"] is not None or a["buffer_donor"])
        | set(aliased_params))
    table = []
    for i in indices:
        attrs = lowered_attrs.get(i, {})
        ttype = attrs.get("type", "")
        alias = aliased_params.get(i)
        # The StableHLO type is the GLOBAL shape; memory_analysis()
        # accounts per-device bytes.  Divide by the arg's shard count so
        # the two live in the same unit (MC402 messages, alias
        # discount) — on the 8-way fsdp mesh the difference is 8x.
        shards = _shard_count(attrs.get("sharding"))
        table.append(DonationEntry(
            arg_index=i,
            type=ttype,
            bytes=_tensor_bytes(ttype) // shards if ttype else 0,
            requested=bool(i < len(requested) and requested[i]),
            lowered=bool(attrs.get("aliasing_output") is not None
                         or attrs.get("buffer_donor")),
            effective=alias is not None,
            output_index=(alias["output_index"]
                          if alias is not None else None),
            shard_count=shards))
    return table


# -- StableHLO statement / function parsing ----------------------------


@dataclasses.dataclass
class _Stmt:
    lhs: Optional[str]            # "%8" (base name, no "#k" suffix)
    op: str                       # "stablehlo.add", "func.call", ...
    operands: List[str]           # RHS %-tokens, "#k" suffixes stripped
    result_types: List[str]       # tensor type texts
    callee: Optional[str]         # for func.call
    line: str
    body: Optional[List["_Stmt"]] = None   # while: the `do` region


@dataclasses.dataclass
class _Func:
    name: str
    args: List[str]               # "%arg0", ...
    stmts: List[_Stmt]
    ret: List[str]                # returned value tokens (base names)
    #: returned tokens with "#k" tuple suffixes intact — the invariance
    #: pass compares base names, but equiv's value-numbering needs the
    #: exact element (``%4#1`` vs ``%4#0`` are different values).
    ret_full: List[str] = dataclasses.field(default_factory=list)


def _base(tok: str) -> str:
    return tok.split("#")[0]


def _line_types(line: str) -> List[str]:
    """Result tensor types of an op line: after the LAST ``->`` if any,
    else after the final ``:``."""
    if "->" in line:
        seg = line.rsplit("->", 1)[1]
    elif ":" in line:
        seg = line.rsplit(":", 1)[1]
    else:
        return []
    return _TENSOR_RE.findall(seg)


def parse_functions(txt: str) -> Dict[str, _Func]:
    """Parse the pretty-printed StableHLO module into per-function
    statement lists; ``stablehlo.while`` statements carry their ``do``
    region as children (the ``cond`` region is parsed for trip counts
    separately).  Line-oriented and tolerant: anything unrecognised is
    skipped — this is an estimator, not a verifier."""
    funcs: Dict[str, _Func] = {}
    lines = txt.splitlines()
    i = 0
    n = len(lines)
    while i < n:
        m = _FUNC_RE.match(lines[i])
        if not m:
            i += 1
            continue
        fname = m.group(1).strip('"')
        args = [f"%arg{k}" for k in
                range(len(re.findall(r"%arg\d+:", lines[i])))]
        stmts, ret, ret_full, i = _parse_region(lines, i + 1,
                                                base_indent=None)
        funcs[fname] = _Func(fname, args, stmts, ret, ret_full)
    return funcs


def _parse_region(lines: List[str], i: int, base_indent) -> tuple:
    """Parse statements until the region's closing ``}``.  Returns
    ``(stmts, return_tokens, full_return_tokens, next_line_index)``."""
    stmts: List[_Stmt] = []
    ret: List[str] = []
    ret_full: List[str] = []
    n = len(lines)
    while i < n:
        raw = lines[i]
        s = raw.strip()
        if s == "}" or s.startswith("}"):
            return stmts, ret, ret_full, i + 1
        m = _STMT_RE.match(raw)
        if not m:
            i += 1
            continue
        lhs, _nres, op, rest = m.groups()
        op = op.strip('"')
        opname = op.split(".")[-1] if op.startswith("stablehlo.") else op
        if opname == "while":
            # operands: the iterArg bindings' RHS values.
            inits = [_base(t) for t in _VAR_RE.findall(rest)
                     if not t.startswith("%iterArg")]
            iter_args = [t for t in _VAR_RE.findall(rest)
                         if t.startswith("%iterArg")]
            types = _TENSOR_RE.findall(rest)
            # skip the cond region (capture for trip count), then body
            cond_lines: List[str] = []
            i += 1
            while i < n and "cond" not in lines[i]:
                i += 1
            i += 1
            while i < n and not lines[i].strip().startswith("} do"):
                cond_lines.append(lines[i])
                i += 1
            body, bret, bret_full, i = _parse_region(lines, i + 1, None)
            st = _Stmt(lhs=lhs, op="while", operands=inits,
                       result_types=types, callee=None, line=raw,
                       body=body)
            st.iter_args = iter_args            # type: ignore[attr-defined]
            st.body_ret = bret                  # type: ignore[attr-defined]
            st.body_ret_full = bret_full        # type: ignore[attr-defined]
            st.cond_lines = cond_lines          # type: ignore[attr-defined]
            stmts.append(st)
            continue
        if opname in ("return",):
            ret_full = list(_VAR_RE.findall(rest))
            ret = [_base(t) for t in ret_full]
            i += 1
            continue
        callee = None
        if opname in ("func.call", "call"):
            cm = _CALLEE_RE.search(rest)
            callee = cm.group(1).strip('"') if cm else None
        st = _Stmt(
            lhs=lhs, op=opname,
            operands=[_base(t) for t in _VAR_RE.findall(rest)],
            result_types=_line_types(raw), callee=callee, line=raw)
        stmts.append(st)
        i += 1
        # Generic-syntax region ops (`"stablehlo.scatter"(...) ({ ... })`)
        # carry an anonymous block whose `stablehlo.return` belongs to the
        # reducer/comparator, not to this region — consume through the
        # matching `})` so neither the block body nor its closer is taken
        # for region-level syntax.  The skipped lines ride on the stmt so
        # downstream analyzers can still fingerprint the block.
        if "({" in raw and raw.count("({") > raw.count("})"):
            depth_r = raw.count("({") - raw.count("})")
            region: List[str] = []
            while i < n and depth_r > 0:
                depth_r += lines[i].count("({") - lines[i].count("})")
                if depth_r > 0:
                    region.append(lines[i])
                i += 1
            st.region_lines = region            # type: ignore[attr-defined]
    return stmts, ret, ret_full, i


# -- FLOP estimation ---------------------------------------------------


def _stmt_flops(st: _Stmt) -> float:
    """Estimated FLOPs of one statement (dot/conv exact up to 2x
    convention, elementwise = numel, movement = 0)."""
    if not st.result_types:
        return 0.0
    out_numel = sum(_tensor_numel_dtype(t)[0] for t in st.result_types)
    if st.op in _MOVEMENT_OPS or st.op in ("while", "func.call", "call"):
        return 0.0
    operand_types = []
    if ":" in st.line and "(" in st.line.rsplit(":", 1)[-1]:
        sig = st.line.rsplit(":", 1)[-1].split("->")[0]
        operand_types = _TENSOR_RE.findall(sig)
    if st.op == "dot_general":
        contract = 1
        cm = _CONTRACT_RE.search(st.line)
        if cm and operand_types:
            lhs_dims = [d for d in
                        operand_types[0].replace(" ", "").split("x")[:-1]]
            for idx in cm.group(1).split(","):
                idx = idx.strip()
                if idx and int(idx) < len(lhs_dims):
                    contract *= int(lhs_dims[int(idx)])
        return 2.0 * out_numel * contract
    if st.op == "convolution":
        if len(operand_types) >= 2:
            k_numel, _ = _tensor_numel_dtype(operand_types[1])
            o_size = 1
            km = _KERNEL_O_RE.search(st.line)
            if km:
                spec = [x.strip() for x in km.group(1).split(",")]
                kdims = operand_types[1].replace(" ", "").split("x")[:-1]
                if "o" in spec and len(kdims) == len(spec):
                    o_size = int(kdims[spec.index("o")])
            return 2.0 * out_numel * (k_numel / max(1, o_size))
        return 2.0 * out_numel
    if st.op in ("reduce", "reduce_window"):
        in_numel = sum(_tensor_numel_dtype(t)[0] for t in operand_types)
        return float(max(in_numel, out_numel))
    return float(out_numel)


def _trip_count(st: _Stmt) -> Optional[int]:
    """Best-effort trip count from the canonical jax loop condition
    ``compare LT, %counter, constant`` (assumes a zero start)."""
    lines = getattr(st, "cond_lines", [])
    consts = {}
    for ln in lines:
        cm = re.match(r"\s*(%[\w.]+)\s*=\s*stablehlo\.constant\s+"
                      r"dense<(-?\d+)>", ln)
        if cm:
            consts[cm.group(1)] = int(cm.group(2))
    for ln in lines:
        if "compare" in ln and " LT," in ln:
            toks = _VAR_RE.findall(ln.split("=", 1)[-1])
            for t in toks:
                if _base(t) in consts:
                    return consts[_base(t)]
    return None


# -- the loop-invariance dataflow pass ---------------------------------


@dataclasses.dataclass
class ScanLoopReport:
    """One ``stablehlo.while``'s variant/invariant partition."""

    index: int                     # document order within @main
    trip_count: Optional[int]
    body_ops: int                  # statements analyzed (incl. callees)
    invariant_ops: int
    invariant_flops: float         # per iteration — the hoistable number
    invariant_bytes: int           # frontier bytes: invariant values
    #                                consumed by variant ops (what a
    #                                hoisted carry would have to hold)
    total_flops: float             # per iteration, whole body
    top_invariant: List[dict] = dataclasses.field(default_factory=list)

    @property
    def hoistable_flops_total(self) -> float:
        return self.invariant_flops * (self.trip_count or 1)

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["hoistable_flops_total"] = self.hoistable_flops_total
        return d


class _InvarianceAnalyzer:
    """Partitions while-body ops into loop-variant / loop-invariant and
    sums hoistable FLOPs/bytes, recursing through ``func.call``s with
    per-call-site operand variance masks (memoized)."""

    def __init__(self, functions: Dict[str, _Func]):
        self.functions = functions
        self._memo: Dict[tuple, tuple] = {}

    def analyze_while(self, st: _Stmt, variant_inits: set) -> dict:
        """``variant_inits``: indices of while operands whose *initial*
        values are already variant in the enclosing scope (rare — the
        dominant variance source is the loop itself)."""
        iter_args = list(getattr(st, "iter_args", []))
        body = st.body or []
        body_ret = list(getattr(st, "body_ret", []))
        # An iterArg is loop-variant unless the body returns it
        # unchanged (same SSA token at the same carry position).
        variant: set = set()
        for pos, ia in enumerate(iter_args):
            returned = body_ret[pos] if pos < len(body_ret) else None
            if returned != ia or pos in variant_inits:
                variant.add(ia)
        stats = self._walk(body, variant, depth=0)
        return stats

    def _walk(self, stmts: List[_Stmt], variant: set, depth: int) -> dict:
        inv_flops = 0.0
        inv_bytes = 0
        inv_ops = 0
        total_flops = 0.0
        n_ops = 0
        top: List[dict] = []
        inv_values: Dict[str, int] = {}     # invariant value -> bytes
        for st in stmts:
            n_ops += 1
            op_variant = any(o in variant for o in st.operands
                             if o.startswith("%"))
            if st.op in ("func.call", "call") and st.callee:
                sub = self._call(st, variant, depth)
                total_flops += sub["total_flops"]
                n_ops += sub["body_ops"]
                if not op_variant:
                    # Whole call is invariant: all its flops hoist.
                    inv_flops += sub["total_flops"]
                    inv_ops += sub["body_ops"]
                else:
                    inv_flops += sub["invariant_flops"]
                    inv_bytes += sub["invariant_bytes"]
                    inv_ops += sub["invariant_ops"]
                    top.extend(sub["top"])
                if sub["variant_out"] or op_variant:
                    if st.lhs:
                        variant.add(st.lhs)
                continue
            if st.op == "while":
                # Nested loop: opaque. Variant if any operand variant.
                if op_variant and st.lhs:
                    variant.add(st.lhs)
                continue
            f = _stmt_flops(st)
            total_flops += f
            if op_variant:
                if st.lhs:
                    variant.add(st.lhs)
                # Frontier: invariant operands feeding a variant op.
                for o in st.operands:
                    if o in inv_values:
                        inv_bytes += inv_values.pop(o)
            else:
                inv_ops += 1
                inv_flops += f
                if st.lhs:
                    b = sum(_tensor_bytes(t) for t in st.result_types)
                    inv_values[st.lhs] = b
                if f > 0:
                    top.append({"op": st.op, "flops": f,
                                "line": st.line.strip()[:160]})
        top.sort(key=lambda d: -d["flops"])
        return {"invariant_flops": inv_flops, "invariant_bytes": inv_bytes,
                "invariant_ops": inv_ops, "total_flops": total_flops,
                "body_ops": n_ops, "top": top[:5],
                "variant_out": True}

    def _call(self, st: _Stmt, variant: set, depth: int) -> dict:
        fn = self.functions.get(st.callee or "")
        operand_vals = [o for o in st.operands if o.startswith("%")]
        if fn is None or depth > 6:
            return {"invariant_flops": 0.0, "invariant_bytes": 0,
                    "invariant_ops": 0, "total_flops": 0.0,
                    "body_ops": 0, "top": [],
                    "variant_out": any(o in variant for o in operand_vals)}
        mask = tuple(
            (operand_vals[k] in variant) if k < len(operand_vals) else False
            for k in range(len(fn.args)))
        key = (fn.name, mask)
        if key in self._memo:
            return dict(self._memo[key])
        callee_variant = {a for a, v in zip(fn.args, mask) if v}
        sub = self._walk(list(fn.stmts), callee_variant, depth + 1)
        sub["variant_out"] = any(r in callee_variant for r in fn.ret) or \
            any(m for m in mask)
        # Conservative: if any arg is variant, outputs are variant unless
        # the return is a passthrough of invariant args only (checked
        # above via fn.ret membership — keep the stronger condition).
        sub["variant_out"] = any(r in callee_variant for r in fn.ret) \
            if fn.ret else any(mask)
        self._memo[key] = dict(sub)
        return sub


def analyze_scan_invariants(stablehlo_text: str) -> List[ScanLoopReport]:
    """The StableHLO ``while``-loop dataflow pass: for each while in
    ``@main``'s body (document order — jax lowers each ``lax.scan`` to
    one), partition the body into loop-variant vs loop-invariant
    subgraphs and quantify the recompute: FLOPs per step that a
    hoisted-carry restructuring would save, and the frontier bytes such
    a carry would have to hold."""
    functions = parse_functions(stablehlo_text)
    main = functions.get("main")
    if main is None:
        return []
    analyzer = _InvarianceAnalyzer(functions)
    out: List[ScanLoopReport] = []
    idx = 0
    for st in main.stmts:
        if st.op != "while":
            continue
        stats = analyzer.analyze_while(st, variant_inits=set())
        out.append(ScanLoopReport(
            index=idx,
            trip_count=_trip_count(st),
            body_ops=stats["body_ops"],
            invariant_ops=stats["invariant_ops"],
            invariant_flops=stats["invariant_flops"],
            invariant_bytes=stats["invariant_bytes"],
            total_flops=stats["total_flops"],
            top_invariant=stats["top"]))
        idx += 1
    return out


# -- report assembly ---------------------------------------------------


@dataclasses.dataclass
class MemoryReport:
    """Everything memcheck knows about one compiled program."""

    name: str
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    alias_bytes: int = 0
    available: bool = True          # memory_analysis() present
    donations: List[DonationEntry] = dataclasses.field(
        default_factory=list)
    scan_loops: List[ScanLoopReport] = dataclasses.field(
        default_factory=list)

    @property
    def peak_bytes(self) -> int:
        """Executable-footprint upper bound: arguments + outputs + temps
        + generated code, aliased bytes counted once (the donation
        discount).  The number the router's admission control budgets
        against."""
        return (self.argument_bytes + self.output_bytes + self.temp_bytes
                + self.generated_code_bytes - self.alias_bytes)

    @property
    def ineffective_donations(self) -> List[int]:
        """Arg indices whose donation was requested but never aliased —
        each one is a full silent buffer copy."""
        return [d.arg_index for d in self.donations
                if d.requested and not d.effective]

    @property
    def hoistable_flops_per_step(self) -> float:
        """Loop-invariant FLOPs re-executed per scan iteration, summed
        over ``@main``'s scan loops."""
        return sum(l.invariant_flops for l in self.scan_loops)

    @property
    def hoistable_flops_total(self) -> float:
        return sum(l.hoistable_flops_total for l in self.scan_loops)

    @property
    def hoistable_bytes(self) -> int:
        return sum(l.invariant_bytes for l in self.scan_loops)

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "available": self.available,
            "peak_bytes": self.peak_bytes,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "generated_code_bytes": self.generated_code_bytes,
            "alias_bytes": self.alias_bytes,
            "donations": [d.to_json() for d in self.donations],
            "ineffective_donations": self.ineffective_donations,
            "scan_loops": [l.to_json() for l in self.scan_loops],
            "hoistable_flops_per_step": self.hoistable_flops_per_step,
            "hoistable_flops_total": self.hoistable_flops_total,
            "hoistable_bytes": self.hoistable_bytes,
        }


def requested_donations(lowered) -> List[bool]:
    """Flattened per-argument donation flags the Python layer requested,
    from ``lowered.args_info`` (set even when lowering could not pair
    the donated buffer with an output — exactly the case MC402 hunts)."""
    import jax

    info = getattr(lowered, "args_info", None)
    if info is None:
        return []
    leaves = jax.tree_util.tree_leaves(
        info, is_leaf=lambda x: hasattr(x, "donated"))
    return [bool(getattr(l, "donated", False)) for l in leaves]


def compiled_memory_stats(compiled) -> Optional[dict]:
    """``compiled.memory_analysis()`` as a plain dict (None when the
    backend does not expose it)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    return {
        "argument_bytes": int(getattr(ma, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(ma, "output_size_in_bytes", 0)),
        "temp_bytes": int(getattr(ma, "temp_size_in_bytes", 0)),
        "generated_code_bytes": int(
            getattr(ma, "generated_code_size_in_bytes", 0)),
        "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
    }


def build_memory_report(name: str, stablehlo_text: str, compiled,
                        requested: Sequence[bool] = ()) -> MemoryReport:
    """Assemble a :class:`MemoryReport` from the lowered StableHLO text,
    the compiled executable, and the requested-donation flags."""
    stats = compiled_memory_stats(compiled)
    try:
        hlo_text = compiled.as_text()
    except Exception:
        hlo_text = ""
    report = MemoryReport(
        name=name,
        available=stats is not None,
        donations=donation_table(
            list(requested), parse_arg_donations(stablehlo_text),
            parse_input_output_aliases(hlo_text)),
        scan_loops=analyze_scan_invariants(stablehlo_text))
    if stats is not None:
        report.argument_bytes = stats["argument_bytes"]
        report.output_bytes = stats["output_bytes"]
        report.temp_bytes = stats["temp_bytes"]
        report.generated_code_bytes = stats["generated_code_bytes"]
        # memory_analysis() reports alias bytes only for freshly-compiled
        # executables — a persistent-compilation-cache hit deserializes
        # with the field zeroed, which would flap the peak pin by the
        # donation discount depending on cache state.  The compiled
        # header's alias table is cache-stable, so derive the discount
        # from the (already parsed) donation table when it is larger.
        # Both sides are per-device: donation bytes are the global
        # StableHLO size divided by the arg's shard count.
        report.alias_bytes = max(
            stats["alias_bytes"],
            sum(d.bytes for d in report.donations if d.effective))
    return report


def analyze_lowered_memory(name: str, lowered) -> MemoryReport:
    """Standalone entry point: lower -> compile -> memory report (the
    jit-cache makes re-compiling an already-built program cheap)."""
    return build_memory_report(
        name, lowered.as_text(), lowered.compile(),
        requested=requested_donations(lowered))


def memory_summary(report: MemoryReport) -> dict:
    """The compact block bench.py / serving stats embed next to each
    perf number (mirror of :func:`ir.comms_summary`)."""
    return {
        "peak_bytes": report.peak_bytes,
        "argument_bytes": report.argument_bytes,
        "output_bytes": report.output_bytes,
        "temp_bytes": report.temp_bytes,
        "donations": [d.to_json() for d in report.donations],
        "ineffective_donations": report.ineffective_donations,
        "hoistable_flops_per_step": report.hoistable_flops_per_step,
        "hoistable_flops_total": report.hoistable_flops_total,
        "hoistable_bytes": report.hoistable_bytes,
        "scan_loops": len(report.scan_loops),
    }
