"""pytest integration for the runtime invariant harness.

Loaded via ``addopts = "-p diff3d_tpu.analysis.pytest_plugin"`` in
``pyproject.toml`` (works from a checkout without installing the
package — pytest resolves the module off ``sys.path``).  Exposes:

  * ``@pytest.mark.compile_budget(n)`` — the test's tracked jitted
    callables may compile at most ``n`` programs.  The test requests the
    ``compile_sentinel`` fixture, registers the callables it exercises
    with :meth:`RecompilationSentinel.track`, and the budget is enforced
    at teardown (after the test body, so every dispatch is counted).
    A marked test that never tracks anything fails — a budget over zero
    callables would vacuously pass.
  * ``compile_sentinel`` — a fresh :class:`RecompilationSentinel` per
    test, usable with or without the marker.
  * ``@pytest.mark.comms_budget(...)`` — the test's analyzed programs
    (lowered pjit programs registered with the ``comms_check`` fixture)
    may not exceed the given collective/resharding/upcast/callback
    limits, aggregated over every registered report and enforced at
    teardown.  Keywords: collective opcodes with underscores
    (``all_gather=3``, ``all_reduce=2``, ...) bound instruction counts,
    ``total_bytes`` bounds the summed per-device collective bytes, and
    ``resharding_sites`` / ``dtype_upcasts`` / ``host_callbacks``
    (default-unbounded) bound those site counts.  Same vacuous-pass
    protection as ``compile_budget``: a marked test that never registers
    a report fails.
  * ``@pytest.mark.lock_witness`` — the test runs with the runtime
    lock-order witness installed (every ``threading.Lock``/``RLock``/
    ``Condition``/``Event`` created in the test body is wrapped); at
    teardown the test fails on any lock-order cycle or held-lock wait
    observed.  The test must request the ``lock_witness`` fixture, and
    a marked test under which no lock was ever acquired fails — the
    check would pass vacuously.
  * ``@pytest.mark.memory_budget(...)`` — the test's analyzed programs
    (memory reports registered with the ``mem_check`` fixture) may not
    exceed the given memory limits, aggregated over every registered
    report and enforced at teardown.  Keywords: ``peak_bytes`` /
    ``temp_bytes`` bound the summed byte footprints,
    ``hoistable_flops_per_step`` bounds the summed scan-invariant
    recompute, and ``ineffective_donations`` (default 0 when the marker
    is used) bounds the number of requested-but-unaliased donations.
    Same vacuous-pass protection: a marked test that never registers a
    report fails.
  * ``@pytest.mark.rng_lineage`` — the test runs with the RNG stream
    witness installed (every key-consuming ``jax.random`` entry point
    is wrapped; see ``analysis/rngflow.py``); at teardown the test
    fails on any key consumed more than once while the witness was
    live.  The test must request the ``rng_witness`` fixture, and a
    marked test under which no ``jax.random`` event was ever recorded
    fails — the check would pass vacuously.
  * ``@pytest.mark.semantic_pin`` — the test's analyzed programs
    (semantic reports registered with the ``equiv_check`` fixture) are
    diffed against the committed ``runs/equivcheck/`` manifests at
    teardown; any unsuppressed EQ6xx finding (fingerprint drift, dead
    output, duplicate subcomputation, missing manifest) fails the
    test.  Point ``equiv_check.manifest_dir`` somewhere else to pin
    against a test-local manifest set.  Same vacuous-pass protection:
    a marked test that never registers a report fails.
"""

from __future__ import annotations

import pytest

from diff3d_tpu.analysis.ir import COLLECTIVE_OPS, ProgramReport
from diff3d_tpu.analysis.runtime import RecompilationSentinel

#: comms_budget keyword -> how it is enforced.  Collective opcodes use
#: underscores (valid Python keywords); None-valued limits are unset.
_COMMS_KEYS = tuple(op.replace("-", "_") for op in COLLECTIVE_OPS) + (
    "total_bytes", "resharding_sites", "dtype_upcasts", "host_callbacks")


class CommsCheck:
    """Accumulates :class:`ProgramReport`s for the ``comms_budget``
    marker.  ``add`` takes a ready report; ``analyze`` lowers+analyzes
    in place (thin wrapper over :func:`analyze_lowered`)."""

    def __init__(self):
        self.reports = []

    def add(self, report: ProgramReport) -> ProgramReport:
        self.reports.append(report)
        return report

    def analyze(self, name: str, lowered, **kw) -> ProgramReport:
        from diff3d_tpu.analysis.ir import analyze_lowered

        return self.add(analyze_lowered(name, lowered, **kw))

    def violations(self, limits: dict) -> list:
        """Human-readable budget breaches, aggregated over reports."""
        counts = {op: 0 for op in COLLECTIVE_OPS}
        total_bytes = 0
        sites = upcasts = callbacks = 0
        for r in self.reports:
            for op, stat in r.collectives.items():
                counts[op] = counts.get(op, 0) + stat.count
            total_bytes += r.total_collective_bytes
            sites += len(r.resharding_sites)
            upcasts += sum(r.dtype_upcasts.values())
            callbacks += len(r.host_callbacks)
        out = []
        for op in COLLECTIVE_OPS:
            limit = limits.get(op.replace("-", "_"))
            if limit is not None and counts[op] > limit:
                out.append(f"{op}: {counts[op]} instruction(s) > "
                           f"budget {limit}")
        for key, got in (("total_bytes", total_bytes),
                         ("resharding_sites", sites),
                         ("dtype_upcasts", upcasts),
                         ("host_callbacks", callbacks)):
            limit = limits.get(key)
            if limit is not None and got > limit:
                out.append(f"{key}: {got} > budget {limit}")
        return out


_MEMORY_KEYS = ("peak_bytes", "temp_bytes", "hoistable_flops_per_step",
                "ineffective_donations")


class MemCheck:
    """Accumulates :class:`~diff3d_tpu.analysis.mem.MemoryReport`s for
    the ``memory_budget`` marker.  ``add`` takes a ready report;
    ``analyze`` lowers+compiles+analyzes in place."""

    def __init__(self):
        self.reports = []

    def add(self, report):
        self.reports.append(report)
        return report

    def analyze(self, name: str, lowered):
        from diff3d_tpu.analysis.mem import analyze_lowered_memory

        return self.add(analyze_lowered_memory(name, lowered))

    def violations(self, limits: dict) -> list:
        """Human-readable budget breaches, aggregated over reports."""
        peak = sum(r.peak_bytes for r in self.reports)
        temp = sum(r.temp_bytes for r in self.reports)
        hoist = sum(r.hoistable_flops_per_step for r in self.reports)
        ineff = sum(len(r.ineffective_donations) for r in self.reports)
        out = []
        for key, got in (("peak_bytes", peak), ("temp_bytes", temp),
                         ("hoistable_flops_per_step", hoist)):
            limit = limits.get(key)
            if limit is not None and got > limit:
                out.append(f"{key}: {got:g} > budget {limit:g}")
        # Ineffective donations default to forbidden under the marker:
        # requesting a donation that silently copies is always a bug
        # unless the test explicitly budgets for it.
        limit = limits.get("ineffective_donations", 0)
        if ineff > limit:
            args = [f"{r.name} arg {i}" for r in self.reports
                    for i in r.ineffective_donations]
            out.append(f"ineffective_donations: {ineff} > budget {limit}"
                       f" ({', '.join(args)})")
        return out


class EquivCheck:
    """Accumulates :class:`~diff3d_tpu.analysis.equiv.SemanticReport`s
    for the ``semantic_pin`` marker.  ``add`` takes a ready report;
    ``analyze`` canonicalizes a lowered program (or raw StableHLO text)
    in place.  ``manifest_dir`` defaults to the repo's committed
    ``runs/equivcheck/`` and is overridable per test."""

    def __init__(self):
        self.reports = []
        self.manifest_dir = None

    def add(self, report):
        self.reports.append(report)
        return report

    def analyze(self, name: str, lowered):
        from diff3d_tpu.analysis.equiv import build_semantic_report

        text = lowered if isinstance(lowered, str) else lowered.as_text()
        return self.add(build_semantic_report(name, text))

    def findings(self) -> list:
        """Unsuppressed EQ6xx findings over every registered report,
        diffed against ``manifest_dir``."""
        from diff3d_tpu.analysis import equivcheck as equivcheck_lib

        d = self.manifest_dir or equivcheck_lib.default_manifest_dir()
        out = []
        for r in self.reports:
            out.extend(equivcheck_lib.check_report_against_dir(r, d))
        return [f for f in out if not f.suppressed]


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "compile_budget(n): the test's callables tracked via the "
        "compile_sentinel fixture may compile at most n programs "
        "(enforced at teardown)")
    config.addinivalue_line(
        "markers",
        "comms_budget(all_gather=n, ..., total_bytes=n, "
        "resharding_sites=n, dtype_upcasts=n, host_callbacks=n): the "
        "programs analyzed via the comms_check fixture may not exceed "
        "these collective/resharding/upcast/callback limits "
        "(aggregated; enforced at teardown)")
    config.addinivalue_line(
        "markers",
        "lock_witness: run the test with the runtime lock-order witness "
        "installed (via the lock_witness fixture); fails at teardown on "
        "any lock-order cycle or held-lock wait")
    config.addinivalue_line(
        "markers",
        "memory_budget(peak_bytes=n, temp_bytes=n, "
        "hoistable_flops_per_step=n, ineffective_donations=n): the "
        "programs analyzed via the mem_check fixture may not exceed "
        "these memory/recompute limits (aggregated; enforced at "
        "teardown; ineffective donations forbidden unless budgeted)")
    config.addinivalue_line(
        "markers",
        "rng_lineage: run the test with the RNG stream witness "
        "installed (via the rng_witness fixture); fails at teardown "
        "on any jax.random key consumed more than once")
    config.addinivalue_line(
        "markers",
        "semantic_pin: the programs analyzed via the equiv_check "
        "fixture are diffed against the committed equivcheck "
        "manifests at teardown; any unsuppressed EQ6xx finding fails "
        "the test")


@pytest.hookimpl(tryfirst=True)
def pytest_runtest_setup(item):
    marker = item.get_closest_marker("compile_budget")
    if marker is not None:
        if not marker.args or not isinstance(marker.args[0], int):
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.compile_budget needs an "
                "integer budget, e.g. compile_budget(1)", pytrace=False)
        if "compile_sentinel" not in item.fixturenames:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.compile_budget requires "
                "the compile_sentinel fixture — request it and track "
                "the jitted callables under test", pytrace=False)

    marker = item.get_closest_marker("comms_budget")
    if marker is not None:
        if marker.args:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.comms_budget takes only "
                f"keywords ({', '.join(_COMMS_KEYS)}), e.g. "
                "comms_budget(all_gather=3, resharding_sites=0)",
                pytrace=False)
        bad = sorted(set(marker.kwargs) - set(_COMMS_KEYS))
        if bad or not marker.kwargs:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.comms_budget got "
                f"{'unknown keys ' + ', '.join(bad) if bad else 'no limits'}"
                f" — valid keys: {', '.join(_COMMS_KEYS)}",
                pytrace=False)
        if "comms_check" not in item.fixturenames:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.comms_budget requires the "
                "comms_check fixture — request it and analyze the "
                "lowered programs under test", pytrace=False)

    marker = item.get_closest_marker("memory_budget")
    if marker is not None:
        if marker.args:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.memory_budget takes only "
                f"keywords ({', '.join(_MEMORY_KEYS)}), e.g. "
                "memory_budget(peak_bytes=2**30)", pytrace=False)
        bad = sorted(set(marker.kwargs) - set(_MEMORY_KEYS))
        if bad or not marker.kwargs:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.memory_budget got "
                f"{'unknown keys ' + ', '.join(bad) if bad else 'no limits'}"
                f" — valid keys: {', '.join(_MEMORY_KEYS)}",
                pytrace=False)
        if "mem_check" not in item.fixturenames:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.memory_budget requires "
                "the mem_check fixture — request it and analyze the "
                "lowered programs under test", pytrace=False)

    marker = item.get_closest_marker("lock_witness")
    if marker is not None:
        if marker.args or marker.kwargs:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.lock_witness takes no "
                "arguments", pytrace=False)
        if "lock_witness" not in item.fixturenames:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.lock_witness requires the "
                "lock_witness fixture — request it so the witness is "
                "installed around the test body", pytrace=False)

    marker = item.get_closest_marker("rng_lineage")
    if marker is not None:
        if marker.args or marker.kwargs:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.rng_lineage takes no "
                "arguments", pytrace=False)
        if "rng_witness" not in item.fixturenames:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.rng_lineage requires the "
                "rng_witness fixture — request it so the witness is "
                "installed around the test body", pytrace=False)

    marker = item.get_closest_marker("semantic_pin")
    if marker is not None:
        if marker.args or marker.kwargs:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.semantic_pin takes no "
                "arguments", pytrace=False)
        if "equiv_check" not in item.fixturenames:
            pytest.fail(
                f"{item.nodeid}: @pytest.mark.semantic_pin requires "
                "the equiv_check fixture — request it and analyze the "
                "lowered programs under test", pytrace=False)


@pytest.fixture
def compile_sentinel(request):
    sentinel = RecompilationSentinel()
    yield sentinel
    marker = request.node.get_closest_marker("compile_budget")
    if marker is None:
        return
    if not sentinel.counts() and marker.args[0] >= 0:
        pytest.fail(
            f"{request.node.nodeid}: compile_budget({marker.args[0]}) "
            "but the sentinel tracked no callables — the budget would "
            "pass vacuously; call compile_sentinel.track(...)",
            pytrace=False)
    sentinel.assert_budget(marker.args[0])


@pytest.fixture
def comms_check(request):
    check = CommsCheck()
    yield check
    marker = request.node.get_closest_marker("comms_budget")
    if marker is None:
        return
    if not check.reports:
        pytest.fail(
            f"{request.node.nodeid}: comms_budget(...) but no program "
            "was analyzed — the budget would pass vacuously; call "
            "comms_check.analyze(name, lowered) or comms_check.add(r)",
            pytrace=False)
    violations = check.violations(marker.kwargs)
    if violations:
        names = ", ".join(r.name for r in check.reports)
        pytest.fail(
            f"{request.node.nodeid}: comms budget exceeded over "
            f"[{names}]:\n  " + "\n  ".join(violations), pytrace=False)


@pytest.fixture
def mem_check(request):
    check = MemCheck()
    yield check
    marker = request.node.get_closest_marker("memory_budget")
    if marker is None:
        return
    if not check.reports:
        pytest.fail(
            f"{request.node.nodeid}: memory_budget(...) but no program "
            "was analyzed — the budget would pass vacuously; call "
            "mem_check.analyze(name, lowered) or mem_check.add(r)",
            pytrace=False)
    violations = check.violations(marker.kwargs)
    if violations:
        names = ", ".join(r.name for r in check.reports)
        pytest.fail(
            f"{request.node.nodeid}: memory budget exceeded over "
            f"[{names}]:\n  " + "\n  ".join(violations), pytrace=False)


@pytest.fixture
def equiv_check(request):
    check = EquivCheck()
    yield check
    marker = request.node.get_closest_marker("semantic_pin")
    if marker is None:
        return
    if not check.reports:
        pytest.fail(
            f"{request.node.nodeid}: @pytest.mark.semantic_pin but no "
            "program was analyzed — the pin would pass vacuously; call "
            "equiv_check.analyze(name, lowered) or equiv_check.add(r)",
            pytrace=False)
    findings = check.findings()
    if findings:
        pytest.fail(
            f"{request.node.nodeid}: semantic pin violated "
            f"({len(findings)} finding(s)):\n  "
            + "\n  ".join(f.render() for f in findings), pytrace=False)


@pytest.fixture
def lock_witness(request):
    from diff3d_tpu.analysis.witness import install_witness

    witness, uninstall = install_witness()
    try:
        yield witness
    finally:
        uninstall()
    marker = request.node.get_closest_marker("lock_witness")
    if marker is None:
        return
    if witness.acquisitions == 0:
        pytest.fail(
            f"{request.node.nodeid}: @pytest.mark.lock_witness but no "
            "witnessed lock was ever acquired — the check would pass "
            "vacuously; the code under test must create and use its "
            "locks while the witness is installed", pytrace=False)
    violations = witness.violations()
    if violations:
        pytest.fail(
            f"{request.node.nodeid}: lock witness found "
            f"{len(violations)} violation(s):\n"
            + "\n".join(violations), pytrace=False)


@pytest.fixture
def rng_witness(request):
    from diff3d_tpu.analysis.rngflow import install_rng_witness

    witness, uninstall = install_rng_witness()
    try:
        yield witness
    finally:
        uninstall()
    marker = request.node.get_closest_marker("rng_lineage")
    if marker is None:
        return
    if not witness.events:
        pytest.fail(
            f"{request.node.nodeid}: @pytest.mark.rng_lineage but no "
            "jax.random event was ever witnessed — the check would "
            "pass vacuously; the code under test must derive/consume "
            "keys while the witness is installed", pytrace=False)
    violations = witness.violations()
    if violations:
        pytest.fail(
            f"{request.node.nodeid}: rng witness found "
            f"{len(violations)} violation(s):\n"
            + "\n".join(violations), pytrace=False)
