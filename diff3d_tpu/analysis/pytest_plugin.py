"""pytest integration for the runtime invariant harness.

Loaded via ``addopts = "-p diff3d_tpu.analysis.pytest_plugin"`` in
``pyproject.toml`` (works from a checkout without installing the
package — pytest resolves the module off ``sys.path``).  Exposes:

  * ``@pytest.mark.compile_budget(n)`` — the test's tracked jitted
    callables may compile at most ``n`` programs.  The test requests the
    ``compile_sentinel`` fixture, registers the callables it exercises
    with :meth:`RecompilationSentinel.track`, and the budget is enforced
    at teardown (after the test body, so every dispatch is counted).
    A marked test that never tracks anything fails — a budget over zero
    callables would vacuously pass.
  * ``compile_sentinel`` — a fresh :class:`RecompilationSentinel` per
    test, usable with or without the marker.
"""

from __future__ import annotations

import pytest

from diff3d_tpu.analysis.runtime import RecompilationSentinel


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "compile_budget(n): the test's callables tracked via the "
        "compile_sentinel fixture may compile at most n programs "
        "(enforced at teardown)")


@pytest.hookimpl(tryfirst=True)
def pytest_runtest_setup(item):
    marker = item.get_closest_marker("compile_budget")
    if marker is None:
        return
    if not marker.args or not isinstance(marker.args[0], int):
        pytest.fail(
            f"{item.nodeid}: @pytest.mark.compile_budget needs an "
            "integer budget, e.g. compile_budget(1)", pytrace=False)
    if "compile_sentinel" not in item.fixturenames:
        pytest.fail(
            f"{item.nodeid}: @pytest.mark.compile_budget requires the "
            "compile_sentinel fixture — request it and track the "
            "jitted callables under test", pytrace=False)


@pytest.fixture
def compile_sentinel(request):
    sentinel = RecompilationSentinel()
    yield sentinel
    marker = request.node.get_closest_marker("compile_budget")
    if marker is None:
        return
    if not sentinel.counts() and marker.args[0] >= 0:
        pytest.fail(
            f"{request.node.nodeid}: compile_budget({marker.args[0]}) "
            "but the sentinel tracked no callables — the budget would "
            "pass vacuously; call compile_sentinel.track(...)",
            pytrace=False)
    sentinel.assert_budget(marker.args[0])
