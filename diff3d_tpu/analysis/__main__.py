"""``python -m diff3d_tpu.analysis`` — run graftlint (DESIGN.md §9)."""

import sys

from diff3d_tpu.analysis.lint import main

if __name__ == "__main__":
    sys.exit(main())
