"""equiv — StableHLO canonicalizer & semantic-equivalence engine.

The sixth analysis pillar's core (docs/DESIGN.md §18).  The five
existing pillars pin *resources* (AST idioms, collectives, locks,
bytes, RNG streams); none can answer the question a deep refactor
raises: **is this compiled program still the same computation?**  This
module answers it structurally, to the extent a text-level analyzer
can, and backs the structural answer with a concrete one:

  * :func:`canonicalize` rewrites a pretty-printed StableHLO module
    into a **canonical form** that is invariant under the transforms a
    semantics-preserving refactor is allowed to make:

      - alpha-renaming — SSA names never appear in the output; values
        are numbered by first definition in a deterministic walk;
      - commutative-operand order — ``add``/``mul``/``min``/``max``/
        bitwise operands are sorted by value hash;
      - identity movement — no-op ``reshape``/``convert``/
        ``broadcast_in_dim`` (operand type == result type) fold away;
      - outlining — ``func.call`` callees are inlined (the same model
        jitted with or without an outlined helper canonicalizes
        identically), reusing :mod:`diff3d_tpu.analysis.mem`'s parser;
      - duplicate subcomputations — value numbering is Merkle-style
        (an op's hash covers its operands' hashes), so a recomputed
        value collapses onto its first definition.

    The sha256 of the canonical lines is the program's **semantic
    fingerprint** — equal fingerprints mean structurally-equal
    computations; a changed fingerprint is a *reviewable diff*, not
    just a hash flip, because the lines are kept.

  * :func:`structural_diff` names the first divergent canonical op
    between two programs, with surrounding context from both sides —
    the EQ601 message body.

  * :func:`verify_hoist` certifies a scan-hoist refactor: every
    non-trivial computation the hoisted program performs outside the
    loop must match (by canonical value hash) an *in-loop ancestor*
    of the original — loop-invariant values hash identically whether
    computed inside or outside the loop, because invariant iterArgs
    resolve to their init hashes — and both callables must agree on
    randomized tiny-shape concrete inputs.  A hoist that reorders
    non-commutative operands loses its ancestor (structural EQ602); a
    hoist that drops a dependency diverges numerically (concrete
    EQ602).

The canonicalizer is an *equivalence estimator*, not a theorem prover:
it never claims two different-looking programs are equal beyond the
rewrites above, and the concrete cross-check is randomized testing,
not exhaustive.  Its job is the contract in ROADMAP item 1: a
conditioning-branch hoist merges EQ-certified or not at all.
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import Dict, List, Optional, Sequence, Tuple

from diff3d_tpu.analysis.lint import Finding, SEVERITY_ERROR
from diff3d_tpu.analysis.mem import (_MOVEMENT_OPS, _TENSOR_RE, _Func,
                                     _Stmt, _stmt_flops, _trip_count,
                                     parse_functions)

#: Elementwise/bitwise ops whose two operands commute — sorted by value
#: hash so ``a*b`` and ``b*a`` canonicalize identically.
_COMMUTATIVE = frozenset({"add", "multiply", "maximum", "minimum",
                          "and", "or", "xor"})
#: Single-operand movement ops folded away when operand type == result
#: type (and, for broadcast_in_dim, the dims are the identity map).
_FOLDABLE = frozenset({"reshape", "convert", "broadcast_in_dim"})

_TOK_RE = re.compile(r"%[\w.]+(?:#\d+)?")
_LHS_RE = re.compile(r"^\s*%[\w.]+(?::\d+)?\s*=\s*")
_NRES_RE = re.compile(r"^\s*%[\w.]+:(\d+)\s*=")
_DIMS_RE = re.compile(r"dims\s*=\s*\[([0-9, ]*)\]")
_WS_RE = re.compile(r"\s+")

#: func.call inlining recursion cap — past this the call stays opaque.
_INLINE_DEPTH = 8


def _h(*parts) -> str:
    return hashlib.sha256(
        "\x1f".join(str(p) for p in parts).encode()).hexdigest()[:16]


def _attr_text(line: str) -> str:
    """A statement line with the lhs assignment removed and every SSA
    token replaced by ``_`` — the name-free attribute/type payload that
    goes into the value hash (literals, dims, enums, signatures)."""
    s = _LHS_RE.sub("", line.strip())
    s = _TOK_RE.sub("_", s)
    return _WS_RE.sub(" ", s).strip()


def _rhs_tokens(line: str) -> List[str]:
    """Operand tokens of a statement line, ``#k`` suffixes intact."""
    return _TOK_RE.findall(_LHS_RE.sub("", line))


def _sig_types(line: str) -> Tuple[List[str], List[str]]:
    """``(operand_types, result_types)`` from the trailing signature;
    the single-type shorthand (``: tensor<f32>``) yields both equal."""
    if "->" in line:
        head, tail = line.rsplit("->", 1)
        ins = (_TENSOR_RE.findall(head.rsplit(":", 1)[-1])
               if ":" in head else [])
        return ins, _TENSOR_RE.findall(tail)
    if ":" in line:
        t = _TENSOR_RE.findall(line.rsplit(":", 1)[-1])
        return t, t
    return [], []


def _is_identity(st: _Stmt) -> bool:
    if st.op not in _FOLDABLE:
        return False
    ins, outs = _sig_types(st.line)
    if not (len(ins) == 1 and ins == outs):
        return False
    if st.op == "broadcast_in_dim":
        m = _DIMS_RE.search(st.line)
        if not m:
            return False
        dims = [int(x) for x in m.group(1).replace(" ", "").split(",")
                if x]
        rank = len(ins[0].replace(" ", "").split("x")) - 1
        return dims == list(range(rank))
    return True


# -- report dataclasses ------------------------------------------------


@dataclasses.dataclass
class WhileLoopInfo:
    """One ``stablehlo.while`` in the canonical walk (depth 0 = a
    direct loop of ``@main``, i.e. a ``lax.scan``)."""

    index: int
    depth: int
    trip_count: Optional[int]
    body_ops: int                  # statements processed (calls inlined)
    invariant_ops: int
    invariant_flops: float         # per iteration — the hoistable number
    total_flops: float

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class DeadOp:
    """A computed (non-movement, flops>0) value unreachable from the
    program's outputs — compute XLA will DCE but the traced program
    asked for (EQ603)."""

    op: str
    canonical: str
    flops: float

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class DuplicateGroup:
    """One value computed by more than one statement (same canonical
    value hash) — the static CSE-duplicate precursor of memcheck's
    MC404 recompute rule (EQ604)."""

    op: str
    count: int
    flops_each: float
    redundant_flops: float         # (count - 1) * flops_each
    canonical: str                 # the canonical line of the value

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class SemanticReport:
    """Everything equivcheck knows about one lowered program."""

    name: str
    available: bool = True
    digest: str = ""
    n_ops: int = 0                 # emitted canonical ops
    lines: List[str] = dataclasses.field(default_factory=list)
    while_loops: List[WhileLoopInfo] = dataclasses.field(
        default_factory=list)
    dead_ops: List[DeadOp] = dataclasses.field(default_factory=list)
    duplicates: List[DuplicateGroup] = dataclasses.field(
        default_factory=list)
    error: Optional[str] = None
    #: value hash -> canonical line, for ops a hoist may legally move
    #: out of a loop: everything already outside plus loop-invariant
    #: body ops (hashed loop-insensitively).  Verifier-facing; not
    #: serialized.
    ancestor_hashes: Dict[str, str] = dataclasses.field(
        default_factory=dict, repr=False)
    #: value hash -> canonical line of non-movement ops outside every
    #: loop (the hoisted side's obligation list).  Not serialized.
    outside_hashes: Dict[str, str] = dataclasses.field(
        default_factory=dict, repr=False)

    @property
    def cse_duplicate_flops(self) -> float:
        return sum(g.redundant_flops for g in self.duplicates)

    @property
    def hoistable_flops_per_step(self) -> float:
        """Loop-invariant FLOPs re-executed per scan iteration, summed
        over ``@main``'s direct loops — the number that must agree
        (within estimator slack) with memcheck's MC404 pin."""
        return sum(w.invariant_flops for w in self.while_loops
                   if w.depth == 0)

    @property
    def duplicate_flops(self) -> float:
        """Total statically-detectable redundant compute: CSE
        duplicates plus loop-invariant recompute across iterations
        (``invariant_flops * (trip - 1)`` per loop)."""
        loop = sum(w.invariant_flops * (max(w.trip_count or 1, 1) - 1)
                   for w in self.while_loops if w.depth == 0)
        return self.cse_duplicate_flops + loop

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "available": self.available,
            "digest": self.digest,
            "n_ops": self.n_ops,
            "n_lines": len(self.lines),
            "lines": list(self.lines),
            "while_loops": [w.to_json() for w in self.while_loops],
            "dead_ops": [d.to_json() for d in self.dead_ops],
            "duplicates": [g.to_json() for g in self.duplicates],
            "cse_duplicate_flops": self.cse_duplicate_flops,
            "hoistable_flops_per_step": self.hoistable_flops_per_step,
            "duplicate_flops": self.duplicate_flops,
            "error": self.error,
        }


# -- the canonicalizer -------------------------------------------------


class _Canonicalizer:
    """One canonicalization pass over a parsed module.  Hashing and
    emission are a single recursive walk; while-loop invariance is a
    small fixpoint of hash-only walks before the body's emit walk."""

    def __init__(self, functions: Dict[str, _Func]):
        self.functions = functions
        self.lines: List[str] = []
        self.ids: Dict[str, str] = {}        # value hash -> canonical id
        self.records: List[dict] = []        # emit-walk op records
        self.opaque: Dict[str, str] = {}     # unresolved token -> hash
        self.while_infos: List[WhileLoopInfo] = []
        self.n_ops = 0
        self._next_id = 0

    # - small helpers -

    def _define(self, h: str) -> str:
        cid = self.ids.get(h)
        if cid is None:
            cid = f"%v{self._next_id}"
            self._next_id += 1
            self.ids[h] = cid
        return cid

    def _show(self, h: str) -> str:
        return self.ids.get(h, f"%?{h[:8]}")

    def _resolve(self, tok: str, env: Dict[str, str]) -> str:
        got = env.get(tok)
        if got is None and "#" in tok:
            got = env.get(tok.split("#")[0])
        if got is None:
            # Parser gap (an op form we never emit in practice): a
            # stable opaque value, keyed by first-encounter order so
            # renaming alone cannot change it.
            got = self.opaque.get(tok)
            if got is None:
                got = self.opaque[tok] = _h("opaque", len(self.opaque))
        return got

    def _emit(self, text: str, indent: int) -> None:
        self.lines.append("  " * indent + text)

    # - the walk -

    def walk(self, stmts: List[_Stmt], env: Dict[str, str],
             variant: set, emit: bool, indent: int, depth: int,
             call_depth: int, records: Optional[List[dict]],
             flops_out: Optional[dict]) -> None:
        """Process a statement region.  ``env`` maps raw SSA tokens to
        value hashes (mutated); ``variant`` is the set of loop-variant
        hashes (mutated); ``flops_out`` accumulates the enclosing while
        body's totals; ``records`` collects liveness/duplicate records
        when emitting."""
        for st in stmts:
            if st.op == "while":
                self._while(st, env, variant, emit, indent, depth,
                            call_depth, records, flops_out)
                continue
            if st.op in ("func.call", "call") and st.callee:
                fn = self.functions.get(st.callee)
                if fn is not None and call_depth < _INLINE_DEPTH:
                    self._inline(st, fn, env, variant, emit, indent,
                                 depth, call_depth, records, flops_out)
                    continue
            operands = _rhs_tokens(st.line)
            attr = _attr_text(st.line)
            # Anonymous-region ops (scatter/sort reducers): the block
            # body is part of the op's semantics — fold it into the
            # attribute text so reducer edits move the fingerprint.
            region = getattr(st, "region_lines", None)
            if region:
                attr += " region=" + _h(*[_attr_text(l) for l in region])
            opnd_h = [self._resolve(t, env) for t in operands]
            if _is_identity(st) and opnd_h:
                # Fold: the statement defines nothing new.
                if st.lhs:
                    env[st.lhs] = opnd_h[0]
                continue
            if st.op in _COMMUTATIVE and len(opnd_h) == 2:
                opnd_h = sorted(opnd_h)
            # Multi-result assignments print as ``%N:k = ...`` in MLIR;
            # a bare lhs is single-result regardless of how many types
            # the shorthand signature lists (e.g. select's pred type).
            m = _NRES_RE.match(st.line)
            n_res = int(m.group(1)) if m else 1
            res_h = [_h(st.op, attr, *opnd_h) if n_res == 1
                     else _h(st.op, attr, j, *opnd_h)
                     for j in range(n_res)]
            is_variant = any(h in variant for h in opnd_h)
            if st.lhs:
                for j, h in enumerate(res_h):
                    env[f"{st.lhs}#{j}"] = h
                if res_h:
                    env[st.lhs] = res_h[0]
            if is_variant:
                variant.update(res_h)
            flops = _stmt_flops(st)
            movement = st.op in _MOVEMENT_OPS
            if flops_out is not None:
                flops_out["body_ops"] += 1
                flops_out["total_flops"] += flops
                if not is_variant:
                    flops_out["invariant_ops"] += 1
                    flops_out["invariant_flops"] += flops
            if not emit:
                continue
            known = all(h in self.ids for h in res_h)
            line_text = None
            if not known:
                ids = [self._define(h) for h in res_h]
                shown = [self._show(h) for h in opnd_h]
                line_text = (f"{', '.join(ids)} = {st.op}"
                             f"{' ' + ', '.join(shown) if shown else ''}"
                             f" ; {attr}")
                self._emit(line_text, indent)
                self.n_ops += 1
            if records is not None:
                records.append({
                    "op": st.op, "results": res_h, "operands": opnd_h,
                    "flops": flops, "movement": movement,
                    "outside": depth == 0,
                    "invariant": not is_variant,
                    "canonical": line_text, "body": None})

    def _while(self, st: _Stmt, env, variant, emit, indent, depth,
               call_depth, records, flops_out) -> None:
        iter_args = list(getattr(st, "iter_args", []))
        body_ret = list(getattr(st, "body_ret_full",
                                getattr(st, "body_ret", [])))
        body = st.body or []
        attr = _attr_text(st.line)
        inits = [t for t in _rhs_tokens(st.line)
                 if not t.startswith("%iterArg")]
        k = min(len(iter_args), len(inits))
        init_h = [self._resolve(inits[j], env) for j in range(k)]
        trip = _trip_count(st)
        cond_digest = _h(*[_attr_text(l) for l in
                           getattr(st, "cond_lines", [])])

        # Fixpoint: optimistically bind every iterArg to its init hash
        # (invariant); demote any carry position whose body return
        # does not hash back to its binding.  Demotion is monotone, so
        # this converges in <= k+1 hash-only walks.
        invariant = [True] * k
        ret_h: List[str] = []
        for _ in range(k + 1):
            benv = dict(env)
            bvar = set(variant)
            for j in range(k):
                if invariant[j]:
                    benv[iter_args[j]] = init_h[j]
                else:
                    ih = _h("iterarg", j, attr, cond_digest, *init_h)
                    benv[iter_args[j]] = ih
                    bvar.add(ih)
            self.walk(body, benv, bvar, emit=False, indent=0,
                      depth=depth + 1, call_depth=call_depth,
                      records=None, flops_out=None)
            ret_h = [self._resolve(t, benv)
                     for t in body_ret[:k]] + [""] * (k - len(body_ret))
            new_inv = [invariant[j] and ret_h[j] == benv[iter_args[j]]
                       for j in range(k)]
            if new_inv == invariant:
                break
            invariant = new_inv

        # Result hashes: an invariant carry's result IS its init value;
        # a variant result hashes the loop structure.
        res_h = [init_h[j] if invariant[j]
                 else _h("while", j, attr, trip, cond_digest,
                         *(init_h + ret_h))
                 for j in range(k)]
        if st.lhs:
            for j, h in enumerate(res_h):
                env[f"{st.lhs}#{j}"] = h
            if res_h:
                env[st.lhs] = res_h[0]
        if any(h in variant for h in init_h):
            variant.update(res_h)

        if not emit:
            return

        # Final walk, emitting the body region.
        stats = {"body_ops": 0, "invariant_ops": 0,
                 "invariant_flops": 0.0, "total_flops": 0.0}
        res_ids = [self._define(h) for h in res_h]
        self._emit(f"{', '.join(res_ids)} = while "
                   f"{', '.join(self._show(h) for h in init_h)} ; "
                   f"trip={trip} cond={cond_digest[:8]}", indent)
        self.n_ops += 1
        benv = dict(env)
        bvar = set(variant)
        body_records: List[dict] = []
        for j in range(k):
            if invariant[j]:
                benv[iter_args[j]] = init_h[j]
            else:
                ih = _h("iterarg", j, attr, cond_digest, *init_h)
                benv[iter_args[j]] = ih
                bvar.add(ih)
                self._emit(f"{self._define(ih)} = iterarg {j}",
                           indent + 1)
        self.walk(body, benv, bvar, emit=True, indent=indent + 1,
                  depth=depth + 1, call_depth=call_depth,
                  records=body_records, flops_out=stats)
        final_ret = [self._resolve(t, benv) for t in body_ret[:k]]
        self._emit("yield " + ", ".join(self._show(h)
                                        for h in final_ret), indent + 1)
        self.while_infos.append(WhileLoopInfo(
            index=len(self.while_infos), depth=depth, trip_count=trip,
            body_ops=stats["body_ops"],
            invariant_ops=stats["invariant_ops"],
            invariant_flops=stats["invariant_flops"],
            total_flops=stats["total_flops"]))
        if records is not None:
            records.append({
                "op": "while", "results": res_h, "operands": init_h,
                "flops": 0.0, "movement": False, "outside": depth == 0,
                "invariant": not any(h in variant for h in init_h),
                "canonical": None, "body": body_records,
                "body_roots": final_ret})

    def _inline(self, st: _Stmt, fn: _Func, env, variant, emit, indent,
                depth, call_depth, records, flops_out) -> None:
        operands = [t for t in _rhs_tokens(st.line)]
        fenv: Dict[str, str] = {}
        for j, a in enumerate(fn.args):
            fenv[a] = (self._resolve(operands[j], env)
                       if j < len(operands)
                       else _h("missing-arg", fn.name, j))
        self.walk(fn.stmts, fenv, variant, emit=emit, indent=indent,
                  depth=depth, call_depth=call_depth + 1,
                  records=records, flops_out=flops_out)
        rets = fn.ret_full or fn.ret
        res_h = [self._resolve(t, fenv) for t in rets]
        if st.lhs:
            for j, h in enumerate(res_h):
                env[f"{st.lhs}#{j}"] = h
            if res_h:
                env[st.lhs] = res_h[0]


def _collect_live(records: List[dict], roots: set) -> set:
    """Backward liveness over emit-walk records (regions recursed at
    their position in the reversed scan)."""
    live = set(roots)
    for rec in reversed(records):
        if any(h in live for h in rec["results"]):
            live.update(rec["operands"])
            if rec["body"] is not None:
                live.update(rec.get("body_roots", []))
                live |= _collect_live(rec["body"],
                                      set(rec.get("body_roots", []))
                                      | live)
    return live


def _iter_records(records: List[dict]):
    for rec in records:
        yield rec
        if rec["body"] is not None:
            yield from _iter_records(rec["body"])


def canonicalize(name: str, stablehlo_text: str,
                 entry: str = "main") -> SemanticReport:
    """Canonicalize one pretty-printed StableHLO module (see module
    docstring for the invariances) and derive the semantic report."""
    functions = parse_functions(stablehlo_text)
    fn = functions.get(entry)
    if fn is None and functions:
        fn = next(iter(functions.values()))
    if fn is None:
        raise ValueError(f"{name}: no parseable func.func in module")

    canon = _Canonicalizer(functions)
    env: Dict[str, str] = {}
    # Argument types from the signature line make signature changes
    # part of the fingerprint.
    sig_line = next((l for l in stablehlo_text.splitlines()
                     if f"@{fn.name}(" in l or f'@"{fn.name}"(' in l),
                    "")
    arg_types = _TENSOR_RE.findall(sig_line)
    for j, a in enumerate(fn.args):
        h = _h("arg", j)
        env[a] = h
        t = f" ; tensor<{arg_types[j]}>" if j < len(arg_types) else ""
        canon.ids[h] = f"%a{j}"
        canon.lines.append(f"%a{j} = arg {j}{t}")
    records: List[dict] = []
    canon.walk(fn.stmts, env, variant=set(), emit=True, indent=0,
               depth=0, call_depth=0, records=records, flops_out=None)
    rets = fn.ret_full or fn.ret
    ret_h = [canon._resolve(t, env) for t in rets]
    canon.lines.append("return " + ", ".join(canon._show(h)
                                             for h in ret_h))

    live = _collect_live(records, set(ret_h))
    dead: List[DeadOp] = []
    groups: Dict[str, List[dict]] = {}
    for rec in _iter_records(records):
        if rec["movement"] or rec["op"] == "while" or rec["flops"] <= 0:
            continue
        if not any(h in live for h in rec["results"]):
            dead.append(DeadOp(
                op=rec["op"], flops=rec["flops"],
                canonical=(rec["canonical"] or rec["op"])[:200]))
        groups.setdefault(rec["results"][0], []).append(rec)
    dups = [DuplicateGroup(
                op=recs[0]["op"], count=len(recs),
                flops_each=recs[0]["flops"],
                redundant_flops=(len(recs) - 1) * recs[0]["flops"],
                canonical=next((r["canonical"] for r in recs
                                if r["canonical"]), recs[0]["op"])[:200])
            for recs in groups.values() if len(recs) > 1]
    dups.sort(key=lambda g: -g.redundant_flops)

    outside: Dict[str, str] = {}
    ancestors: Dict[str, str] = {}
    for rec in _iter_records(records):
        if rec["movement"] or rec["op"] == "while" or rec["flops"] <= 0:
            continue
        line = (rec["canonical"]
                or canon.ids.get(rec["results"][0], rec["op"]))
        if rec["outside"]:
            outside[rec["results"][0]] = line
            ancestors[rec["results"][0]] = line
        elif rec["invariant"]:
            ancestors[rec["results"][0]] = line

    digest = hashlib.sha256(
        "\n".join(canon.lines).encode()).hexdigest()
    return SemanticReport(
        name=name, available=True, digest=digest, n_ops=canon.n_ops,
        lines=list(canon.lines), while_loops=canon.while_infos,
        dead_ops=dead, duplicates=dups,
        ancestor_hashes=ancestors, outside_hashes=outside)


def build_semantic_report(name: str,
                          stablehlo_text: str) -> SemanticReport:
    """Tolerant entry point: an analyzer failure yields an
    ``available=False`` report, never an exception (this rides every
    ``ir.analyze_lowered`` pass)."""
    try:
        return canonicalize(name, stablehlo_text)
    except Exception as e:  # estimator, not a verifier
        return SemanticReport(name=name, available=False,
                              error=f"{type(e).__name__}: {e}")


# -- the structural differ ---------------------------------------------


def structural_diff(committed: Sequence[str], observed: Sequence[str],
                    context: int = 2) -> Optional[str]:
    """Name the first divergent canonical op between two programs,
    with each side's surrounding lines — the EQ601 message body.
    Returns None when the line lists are identical."""
    committed = list(committed)
    observed = list(observed)
    if committed == observed:
        return None

    def window(lines: Sequence[str], i: int) -> str:
        lo, hi = max(0, i - context), min(len(lines), i + context + 1)
        return " | ".join(f"{k}: {lines[k].strip()}"
                          for k in range(lo, hi))

    n = min(len(committed), len(observed))
    for i in range(n):
        if committed[i] != observed[i]:
            return (f"first divergent op at canonical line {i}: "
                    f"committed {committed[i].strip()!r} vs observed "
                    f"{observed[i].strip()!r} — committed context "
                    f"[{window(committed, i)}]; observed context "
                    f"[{window(observed, i)}]")
    longer = "observed" if len(observed) > len(committed) else "committed"
    extra = (observed if len(observed) > len(committed)
             else committed)[n]
    return (f"programs agree for {n} canonical line(s), then the "
            f"{longer} side continues with {extra.strip()!r}")


# -- the scan-hoist verifier -------------------------------------------


@dataclasses.dataclass
class HoistVerdict:
    """Result of :func:`verify_hoist`.  ``equivalent`` means every
    hoisted computation matched an in-loop ancestor AND the concrete
    cross-check agreed on every trial."""

    equivalent: bool
    findings: List[Finding]
    matched: int                   # hoisted ops with an ancestor
    unmatched: List[str]           # canonical lines without one
    trials: int
    max_abs_diff: float


def _hoist_finding(name: str, key: str, message: str) -> Finding:
    return Finding(
        path=f"<equivcheck:{name}>", rule="EQ602", line=0, col=0,
        severity=SEVERITY_ERROR, message=message,
        fingerprint_data=f"{name}\x00EQ602\x00{key}")


def _randomized_args(example_args, rng):
    """Fresh concrete inputs with the example's shapes/dtypes: floats
    and complex are redrawn, integers/bools keep the example values
    (they are schedule indices/counters — randomizing them changes
    which program runs, not whether two programs agree)."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(example_args)
    out = []
    for leaf in leaves:
        a = np.asarray(leaf)
        if np.issubdtype(a.dtype, np.floating):
            out.append(rng.standard_normal(a.shape).astype(a.dtype))
        elif np.issubdtype(a.dtype, np.complexfloating):
            out.append((rng.standard_normal(a.shape)
                        + 1j * rng.standard_normal(a.shape)
                        ).astype(a.dtype))
        else:
            out.append(a)
    return jax.tree.unflatten(treedef, out)


def verify_hoist(original, hoisted, example_args, *, name: str = "hoist",
                 seed: int = 0, trials: int = 2, rtol: float = 1e-4,
                 atol: float = 1e-5) -> HoistVerdict:
    """Certify that ``hoisted`` is a semantics-preserving scan-hoist of
    ``original`` (EQ602 on every way it can fail).

    Structural half: lower both on the example shapes; every
    non-trivial computation the hoisted program performs outside its
    loops must hash-match an ancestor in the original (an op already
    outside, or a loop-invariant body op — invariant values hash the
    same in both positions).  Wrong operand order or changed inputs
    lose the ancestor.

    Concrete half: run both callables on ``trials`` randomized
    tiny-shape inputs derived from ``example_args`` and require
    allclose agreement — catches dropped dependencies and anything the
    text-level matcher cannot see.
    """
    import jax
    import numpy as np

    from diff3d_tpu.analysis import ir as ir_lib

    jo = original if hasattr(original, "lower") else jax.jit(original)
    jh = hoisted if hasattr(hoisted, "lower") else jax.jit(hoisted)
    example_args = tuple(example_args)
    abstract = ir_lib.abstractify(example_args)

    findings: List[Finding] = []
    orig = build_semantic_report(
        f"{name}:original", jo.lower(*abstract).as_text())
    hois = build_semantic_report(
        f"{name}:hoisted", jh.lower(*abstract).as_text())
    matched = 0
    unmatched: List[str] = []
    if not (orig.available and hois.available):
        bad = orig if not orig.available else hois
        findings.append(_hoist_finding(
            name, "unanalyzable",
            f"hoist of '{name}' is unverifiable: canonicalization "
            f"failed for {bad.name} ({bad.error})"))
    else:
        for h, line in hois.outside_hashes.items():
            if h in orig.ancestor_hashes:
                matched += 1
            else:
                unmatched.append(line)
                findings.append(_hoist_finding(
                    name, f"ancestor:{h[:12]}",
                    f"hoisted computation `{line.strip()}` has no "
                    f"ancestor in the original program — no op outside "
                    f"the loop and no loop-invariant body op computes "
                    f"this value (operand order or inputs changed)"))

    max_diff = 0.0
    for t in range(trials):
        rng = np.random.default_rng(seed * 1000003 + t)
        args = _randomized_args(example_args, rng)
        out_o = jo(*args)
        out_h = jh(*args)
        lo, to = jax.tree.flatten(out_o)
        lh, th = jax.tree.flatten(out_h)
        if to != th:
            findings.append(_hoist_finding(
                name, f"structure:{t}",
                f"trial {t}: output trees differ ({to} vs {th})"))
            continue
        for i, (a, b) in enumerate(zip(lo, lh)):
            a = np.asarray(a)
            b = np.asarray(b)
            if a.shape != b.shape or a.dtype != b.dtype:
                findings.append(_hoist_finding(
                    name, f"output:{i}",
                    f"trial {t}: output {i} shape/dtype differs "
                    f"({a.shape}/{a.dtype} vs {b.shape}/{b.dtype})"))
                continue
            if np.issubdtype(a.dtype, np.inexact):
                diff = float(np.max(np.abs(
                    a.astype(np.float64) - b.astype(np.float64)))) \
                    if a.size else 0.0
                max_diff = max(max_diff, diff)
                ok = np.allclose(a, b, rtol=rtol, atol=atol)
            else:
                ok = bool(np.array_equal(a, b))
            if not ok:
                findings.append(_hoist_finding(
                    name, f"output:{i}",
                    f"trial {t}: concrete cross-check diverged at "
                    f"output {i} (max |delta| = {max_diff:.3g}, rtol="
                    f"{rtol}, atol={atol}) — the hoisted program is "
                    f"NOT the same computation"))

    return HoistVerdict(
        equivalent=not findings, findings=findings, matched=matched,
        unmatched=unmatched, trials=trials, max_abs_diff=max_diff)


def semantic_summary(report: SemanticReport) -> dict:
    """The compact block bench.py embeds next to each perf number."""
    return {
        "available": report.available,
        "digest": report.digest or None,
        "n_ops": report.n_ops,
        "hoistable_flops_per_step": report.hoistable_flops_per_step,
        "duplicate_flops": report.duplicate_flops,
        "dead_ops": len(report.dead_ops),
    }
