"""graftlint: AST-based TPU/JAX tracer-hygiene linter.

The worst bugs this codebase has shipped were *silent JAX-semantics
violations* — a donated buffer read after donation (latent heap
corruption), a hidden host sync inside a jit body, a shape-like argument
left traced (recompilation storm).  None of them fail loudly at the call
site; all of them are visible in the AST.  This module is the engine:
rule discovery, per-file analysis, inline suppressions, a repo baseline,
and the CLI that tier 1 runs as a gate.

Vocabulary:

  * A **finding** is one (rule, file, line) violation with a severity.
  * An inline comment ``# graftlint: disable=GL104(reason)`` suppresses
    that rule on its line; ``disable-next-line=`` suppresses on the line
    below; ``disable-file=`` at any point suppresses for the whole file.
    Reasons are part of the contract — a suppression without one is
    itself reported (severity warning, rule GL002).
  * The **baseline** (``--baseline``/``--update-baseline``) is a JSON
    set of finding fingerprints that are tolerated — the adoption path
    for a legacy tree.  This repo's baseline is EMPTY by policy: every
    finding is either fixed or carries an inline reason.

Exit codes: 0 clean, 1 unsuppressed findings, 2 bad invocation.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import hashlib
import json
import os
import re
import sys
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from diff3d_tpu.analysis.rules import ALL_RULES
from diff3d_tpu.analysis.rules.context import ModuleContext

SEVERITY_ERROR = "error"
SEVERITY_WARNING = "warning"

#: Default lint targets, relative to the repo root (ISSUE 8 gate scope).
DEFAULT_TARGETS = ("diff3d_tpu", "tools", "bench.py")
DEFAULT_BASELINE = ".graftlint-baseline.json"

_RULE_HEAD_RE = re.compile(r"\s*,?\s*([A-Za-z]+\d+|all)")


def _suppress_re(tool: str) -> "re.Pattern[str]":
    """The inline-suppression comment grammar, parameterised on the tool
    tag so sibling analyzers (lockcheck) reuse the exact grammar under
    their own namespace: ``# <tool>: disable[-next-line|-file]=RULE(r)``."""
    return re.compile(
        rf"#\s*{re.escape(tool)}:\s*(disable|disable-next-line|disable-file)"
        r"\s*=\s*(.*)$")


_SUPPRESS_RE = _suppress_re("graftlint")


def _parse_rule_tokens(spec: str):
    """``GL104(reason),GL106`` -> [(rule, reason|None), ...].

    Reasons are free-form text in balanced parens (nested parens fine);
    parsing consumes rule tokens from the start and stops at the first
    thing that is not one — so prose in a reason can never be mistaken
    for another rule id.
    """
    out = []
    pos = 0
    while pos < len(spec):
        m = _RULE_HEAD_RE.match(spec, pos)
        if not m:
            break
        rule = m.group(1)
        pos = m.end()
        reason = None
        if pos < len(spec) and spec[pos] == "(":
            depth, start = 0, pos + 1
            for i in range(pos, len(spec)):
                if spec[i] == "(":
                    depth += 1
                elif spec[i] == ")":
                    depth -= 1
                    if depth == 0:
                        reason = spec[start:i].strip() or None
                        pos = i + 1
                        break
            else:
                reason = spec[start:].strip() or None
                pos = len(spec)
        out.append((rule, reason))
    return out


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation."""

    path: str
    rule: str
    line: int
    col: int
    severity: str
    message: str
    suppressed: bool = False
    suppress_reason: Optional[str] = None
    #: IR-level findings (shardcheck SC2xx) have no source line to hash;
    #: they set this to a stable key (program + rule + subject) instead,
    #: so AST and IR findings share one fingerprint-baseline format.
    fingerprint_data: Optional[str] = None

    def fingerprint(self, root: str) -> str:
        """Location-independent identity for baseline matching: file +
        rule + the violating source line's text (so pure line-number
        drift does not invalidate a baseline entry).  IR findings hash
        their ``fingerprint_data`` key instead of a source line."""
        rel = os.path.relpath(self.path, root)
        if self.fingerprint_data is not None:
            text = self.fingerprint_data
        else:
            try:
                with open(self.path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
                text = lines[self.line - 1].strip() if self.line <= len(
                    lines) else ""
            except OSError:
                text = ""
        h = hashlib.sha256(
            f"{rel}\x00{self.rule}\x00{text}".encode()).hexdigest()
        return h[:20]

    def render(self) -> str:
        tag = " [suppressed]" if self.suppressed else ""
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.severity}] {self.message}{tag}")


@dataclasses.dataclass
class Suppression:
    line: int          # the line the suppression applies to
    rules: Set[str]    # rule ids, or {"all"}
    reasons: Dict[str, str]
    declared_line: int

    def covers(self, rule: str) -> bool:
        return "all" in self.rules or rule in self.rules


def _parse_suppressions(
        lines: Sequence[str],
        suppress_re: "re.Pattern[str]" = _SUPPRESS_RE,
) -> Tuple[List[Suppression], List[Suppression], List[Tuple[int, str]]]:
    """-> (line-scoped, file-scoped, reasonless (line, rule) pairs)."""
    line_scoped: List[Suppression] = []
    file_scoped: List[Suppression] = []
    missing_reason: List[Tuple[int, str]] = []
    for i, text in enumerate(lines, start=1):
        m = suppress_re.search(text)
        if not m:
            continue
        kind, spec = m.group(1), m.group(2)
        rules: Set[str] = set()
        reasons: Dict[str, str] = {}
        for rule, reason in _parse_rule_tokens(spec):
            rules.add(rule)
            if reason:
                reasons[rule] = reason
            else:
                missing_reason.append((i, rule))
        if not rules:
            continue
        target = i + 1 if kind == "disable-next-line" else i
        supp = Suppression(line=target, rules=rules, reasons=reasons,
                           declared_line=i)
        (file_scoped if kind == "disable-file" else line_scoped).append(
            supp)
    return line_scoped, file_scoped, missing_reason


def lint_source(path: str, source: str,
                rules: Optional[Sequence] = None, *,
                tool: str = "graftlint",
                parse_rule: str = "GL001",
                reasonless_rule: str = "GL002") -> List[Finding]:
    """Lint one file's source text.  Returns ALL findings, suppressed
    ones included (marked), so callers can report both sides.

    ``tool`` selects the suppression-comment namespace (and the ids the
    engine-emitted parse/reasonless findings carry) — graftlint by
    default; lockcheck passes its own so the two analyzers' suppressions
    never shadow each other on a shared line."""
    rules = ALL_RULES if rules is None else rules
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path=path, rule=parse_rule, line=e.lineno or 1,
                        col=e.offset or 0, severity=SEVERITY_ERROR,
                        message=f"file does not parse: {e.msg}")]
    ctx = ModuleContext(path, source, tree)
    raw: List[Finding] = []
    for rule in rules:
        for f in rule.check(ctx):
            raw.append(f)

    line_scoped, file_scoped, missing_reason = _parse_suppressions(
        ctx.lines, _suppress_re(tool) if tool != "graftlint"
        else _SUPPRESS_RE)
    out: List[Finding] = []
    for f in sorted(raw, key=lambda f: (f.line, f.col, f.rule)):
        reason = None
        suppressed = False
        for supp in file_scoped:
            if supp.covers(f.rule):
                suppressed = True
                reason = supp.reasons.get(f.rule) or supp.reasons.get(
                    "all")
        if not suppressed:
            for supp in line_scoped:
                if supp.line == f.line and supp.covers(f.rule):
                    suppressed = True
                    reason = supp.reasons.get(f.rule) or supp.reasons.get(
                        "all")
        out.append(dataclasses.replace(f, suppressed=suppressed,
                                       suppress_reason=reason))
    # A suppression without a reason is a policy violation of its own —
    # the inline comment is the audit trail.
    for line, rule in missing_reason:
        out.append(Finding(
            path=path, rule=reasonless_rule, line=line, col=0,
            severity=SEVERITY_WARNING,
            message=f"suppression of {rule} has no (reason) — write "
                    f"'# {tool}: disable={rule}(why it is safe)'"))
    return out


def iter_python_files(targets: Iterable[str]) -> List[str]:
    files: List[str] = []
    for target in targets:
        if os.path.isfile(target):
            files.append(target)
            continue
        for dirpath, dirnames, filenames in os.walk(target):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__", ".git"))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    return files


def lint_paths(targets: Sequence[str],
               rules: Optional[Sequence] = None, *,
               tool: str = "graftlint",
               parse_rule: str = "GL001",
               reasonless_rule: str = "GL002") -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(targets):
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            findings.append(Finding(
                path=path, rule=parse_rule, line=1, col=0,
                severity=SEVERITY_ERROR,
                message=f"unreadable: {e}"))
            continue
        findings.extend(lint_source(path, source, rules, tool=tool,
                                    parse_rule=parse_rule,
                                    reasonless_rule=reasonless_rule))
    return findings


# -- baseline ----------------------------------------------------------


def load_baseline(path: str) -> Set[str]:
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != 1:
        raise ValueError(f"{path}: not a graftlint baseline (version 1)")
    return set(data.get("entries", []))


def write_baseline(path: str, findings: Sequence[Finding],
                   root: str, tool: str = "graftlint") -> int:
    entries = sorted({f.fingerprint(root) for f in findings
                      if not f.suppressed})
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "tool": tool,
                   "entries": entries}, f, indent=1, sort_keys=True)
        f.write("\n")
    return len(entries)


def apply_baseline(findings: Sequence[Finding], baseline: Set[str],
                   root: str) -> List[Finding]:
    """Mark baseline-matched findings as suppressed (reason=baseline)."""
    if not baseline:
        return list(findings)
    out = []
    for f in findings:
        if not f.suppressed and f.fingerprint(root) in baseline:
            f = dataclasses.replace(f, suppressed=True,
                                    suppress_reason="baseline")
        out.append(f)
    return out


# -- CLI ---------------------------------------------------------------


def _find_root(start: str) -> str:
    cur = os.path.abspath(start)
    while True:
        if os.path.exists(os.path.join(cur, "pyproject.toml")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            return os.path.abspath(start)
        cur = parent


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="TPU tracer-hygiene linter (rules GL1xx; see "
                    "docs/DESIGN.md §9)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: diff3d_tpu, "
                        "tools, bench.py under the repo root)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default <root>/"
                        f"{DEFAULT_BASELINE} when present)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write current unsuppressed findings to the "
                        "baseline and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.id}  {rule.name:24s} [{rule.severity}] "
                  f"{rule.description}")
        return 0

    root = _find_root(os.getcwd())
    if args.paths:
        targets = list(args.paths)
    else:
        targets = [os.path.join(root, t) for t in DEFAULT_TARGETS]
        targets = [t for t in targets if os.path.exists(t)]
        if not targets:
            print("graftlint: no default targets found under "
                  f"{root}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    findings = lint_paths(targets)

    if args.update_baseline:
        n = write_baseline(baseline_path, findings, root)
        print(f"graftlint: baseline written to {baseline_path} "
              f"({n} entries)")
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2
    findings = apply_baseline(findings, baseline, root)

    live = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if args.format == "json":
        print(json.dumps({
            "findings": [dataclasses.asdict(f) for f in findings],
            "unsuppressed": len(live),
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        shown = findings if args.show_suppressed else live
        for f in shown:
            print(f.render())
        print(f"graftlint: {len(live)} finding(s), "
              f"{len(suppressed)} suppressed, "
              f"{len(iter_python_files(targets))} file(s)")
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
