"""shardcheck: the program registry + CLI over the IR analyzer.

``analysis/ir.py`` knows how to turn one lowered pjit program into a
:class:`~diff3d_tpu.analysis.ir.ProgramReport`; ``analysis/budgets.py``
knows how to diff a report against a committed manifest.  This module
knows WHICH programs the repo ships: every registered entry builds the
real production program — the mesh-sharded train step, the distill
step, the sampler's ``step_many`` per schedule, a serving-warmup
program routed through :class:`~diff3d_tpu.serving.cache.ProgramCache`
— on tiny test-config shapes over an 8-virtual-CPU-device fsdp mesh,
lowers it on ABSTRACT args (nothing executes; XLA still runs the full
GSPMD partitioner, so the collectives are the real ones), and analyzes.

CLI (also installed as the ``shardcheck`` console script)::

    shardcheck                       # check every program vs manifests
    shardcheck --program train_step  # one program
    shardcheck --update              # re-pin manifests from observed
    shardcheck --list                # registry contents

Exit codes match graftlint: 0 clean, 1 unsuppressed findings, 2 bad
invocation.  ``tools/lint.py`` runs this as the second half of the
tier-1 static-analysis gate (``--programs-tier1`` keeps that fast).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import Callable, Dict, List, Optional, Sequence

from diff3d_tpu.analysis import budgets as budgets_lib
from diff3d_tpu.analysis import ir
from diff3d_tpu.analysis.lint import Finding

#: Virtual device count the registry's mesh expects (matches the test
#: suite's conftest).
MESH_DEVICES = 8


@dataclasses.dataclass(frozen=True)
class ProgramSpec:
    """One registered pjit program."""

    name: str
    description: str
    build: Callable[[], "ir.ProgramReport"]
    #: tier-1 programs are cheap enough for the always-on gate (the
    #: repo-clean test and ``tools/lint.py``); the rest ride the
    #: ``slow``-marked full sweep and the standalone CLI.
    tier1: bool = False


def ensure_cpu_mesh_devices(n: int = MESH_DEVICES) -> None:
    """Force ``n`` virtual CPU devices, tolerating an already-imported
    jax: ``XLA_FLAGS`` is read at backend *initialisation* (lazy), so
    setting it plus ``jax_platforms`` works as long as no backend has
    been created yet.  Under pytest the conftest has already done the
    same thing; a backend initialised with fewer devices is an error."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    have = len(jax.devices())
    if have < n:
        raise RuntimeError(
            f"shardcheck needs {n} CPU devices, backend has {have} — "
            "jax was initialised before shardcheck could set "
            "--xla_force_host_platform_device_count")


def _fsdp_mesh():
    import jax

    from diff3d_tpu.config import MeshConfig
    from diff3d_tpu.parallel import make_mesh

    return make_mesh(
        MeshConfig(data_parallel=MESH_DEVICES, model_parallel=1,
                   param_sharding="fsdp"),
        devices=jax.devices()[:MESH_DEVICES])


def _abstract_state(model, cfg):
    """Abstract TrainState template (shapes via ``eval_shape`` — no
    param buffers are ever materialised)."""
    import jax

    from diff3d_tpu.train import create_train_state
    from diff3d_tpu.train.trainer import init_params

    def build(rng):
        return create_train_state(init_params(model, cfg, rng), cfg.train)

    return jax.eval_shape(build, jax.random.PRNGKey(0))


def _abstract_batch(cfg):
    import jax
    import jax.numpy as jnp

    B = cfg.train.global_batch
    H = cfg.model.H
    sds = jax.ShapeDtypeStruct
    return {"imgs": sds((B, 2, H, H, 3), jnp.uint8),
            "R": sds((B, 2, 3, 3), jnp.float32),
            "T": sds((B, 2, 3), jnp.float32),
            "K": sds((B, 3, 3), jnp.float32)}


def _train_cfg():
    from diff3d_tpu.config import test_config

    return test_config(imgsize=16, ch=8, shallow=True)


def build_train_step_report(name: str = "train_step") -> "ir.ProgramReport":
    import jax
    import jax.numpy as jnp

    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train import make_train_step

    cfg = _train_cfg()
    env = _fsdp_mesh()
    model = XUNet(cfg.model)
    state = _abstract_state(model, cfg)
    batch = _abstract_batch(cfg)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    step = make_train_step(model, cfg, env, donate=False)
    lowered = step.lower(state, batch, rng)
    return ir.analyze_lowered(
        name, lowered, params_template=state.params,
        params_argnum=lambda sh: sh[0].params,
        expected_param_shardings=env.params(state.params))


def build_distill_step_report(
        name: str = "distill_step") -> "ir.ProgramReport":
    import jax
    import jax.numpy as jnp

    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train.distill import make_distill_step

    cfg = _train_cfg()
    env = _fsdp_mesh()
    model = XUNet(cfg.model)
    state = _abstract_state(model, cfg)
    batch = _abstract_batch(cfg)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    k = jax.ShapeDtypeStruct((), jnp.int32)
    step = make_distill_step(model, cfg, env, donate=False)
    lowered = step.lower(state, state.params, batch, rng, k)
    return ir.analyze_lowered(
        name, lowered, params_template=state.params,
        params_argnum=lambda sh: sh[0].params,
        expected_param_shardings=env.params(state.params))


def _sampler(sampler_kind: str = "ancestral",
             steps: Optional[int] = None,
             kernels: Optional[str] = None):
    import dataclasses

    import jax

    from diff3d_tpu.config import test_config
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.sampling import Sampler
    from diff3d_tpu.train.trainer import init_params

    cfg = test_config(imgsize=8, ch=8)
    if kernels is not None:
        cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, kernels=kernels))
    env = _fsdp_mesh()
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    return Sampler(model, params, cfg, mesh=env,
                   sampler_kind=sampler_kind, steps=steps), env


def build_step_many_report(name: str = "step_many") -> "ir.ProgramReport":
    sampler, env = _sampler()
    lowered = sampler.lower_step_many(lanes=MESH_DEVICES, capacity=4)
    return ir.analyze_lowered(
        name, lowered, params_template=sampler.params,
        params_argnum=0,
        expected_param_shardings=env.params(sampler.params))


def build_step_many_pallas_report(
        name: str = "step_many_pallas") -> "ir.ProgramReport":
    """step_many with the fused GroupNorm->FiLM/SiLU Pallas kernels
    (interpret-mode lowering on the CPU mesh).  Not tier-1 — the
    interpret-mode pallas_call lowering is several times slower to trace
    than the XLA path, so the lint gate pins it out-of-band."""
    sampler, env = _sampler(kernels="pallas")
    lowered = sampler.lower_step_many(lanes=MESH_DEVICES, capacity=4)
    return ir.analyze_lowered(
        name, lowered, params_template=sampler.params,
        params_argnum=0,
        expected_param_shardings=env.params(sampler.params))


def build_step_many_ddim_report(
        name: str = "step_many_ddim") -> "ir.ProgramReport":
    sampler, env = _sampler(sampler_kind="ddim", steps=2)
    lowered = sampler.lower_step_many(lanes=MESH_DEVICES, capacity=4)
    return ir.analyze_lowered(
        name, lowered, params_template=sampler.params,
        params_argnum=0,
        expected_param_shardings=env.params(sampler.params))


def build_serving_warmup_report(
        name: str = "serving_warmup") -> "ir.ProgramReport":
    from diff3d_tpu.serving.cache import ProgramCache

    sampler, env = _sampler()
    cache = ProgramCache(sampler)
    H = sampler.cfg.model.H
    lowered = cache.lower((H, H, 4), lanes=MESH_DEVICES)
    return ir.analyze_lowered(
        name, lowered, params_template=sampler.params,
        params_argnum=0,
        expected_param_shardings=env.params(sampler.params))


def _cascade():
    """The cascade pair at analysis scale: a tiny 16² refine model whose
    draft phase is the resolution-adapted 8² student — the same
    construction serving uses, so the lowered programs carry the real
    extra ``draft`` operand and truncated grid."""
    import jax

    from diff3d_tpu.cascade import CascadePlan, CascadeSampler
    from diff3d_tpu.config import test_config
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train.trainer import init_params

    cfg = test_config(imgsize=16, ch=8)
    env = _fsdp_mesh()
    model = XUNet(cfg.model)
    params = init_params(model, cfg, jax.random.PRNGKey(0))
    plan = CascadePlan.parse("draft=8:ddim:2,refine=16:ancestral:2@t0.5")
    return CascadeSampler(model, params, cfg, plan, mesh=env), env


def build_step_many_cascade_draft_report(
        name: str = "step_many_cascade_draft") -> "ir.ProgramReport":
    cascade, env = _cascade()
    s = cascade.draft
    lowered = s.lower_step_many(lanes=MESH_DEVICES, capacity=4)
    return ir.analyze_lowered(
        name, lowered, params_template=s.params,
        params_argnum=0,
        expected_param_shardings=env.params(s.params))


def build_step_many_cascade_refine_report(
        name: str = "step_many_cascade_refine") -> "ir.ProgramReport":
    cascade, env = _cascade()
    s = cascade.refine
    lowered = s.lower_step_many(lanes=MESH_DEVICES, capacity=4)
    return ir.analyze_lowered(
        name, lowered, params_template=s.params,
        params_argnum=0,
        expected_param_shardings=env.params(s.params))


REGISTRY: Dict[str, ProgramSpec] = {
    spec.name: spec for spec in (
        ProgramSpec(
            "train_step",
            "mesh-sharded train step (tiny shallow config, fsdp x8)",
            build_train_step_report, tier1=True),
        ProgramSpec(
            "step_many",
            "sharded sampler step_many, ancestral full grid "
            "(8 lanes, capacity 4)",
            build_step_many_report, tier1=True),
        ProgramSpec(
            "step_many_pallas",
            "sharded sampler step_many with fused GroupNorm Pallas "
            "kernels (interpret-mode lowering)",
            build_step_many_pallas_report),
        ProgramSpec(
            "distill_step",
            "mesh-sharded progressive-distillation step",
            build_distill_step_report),
        ProgramSpec(
            "step_many_ddim",
            "sharded sampler step_many, deterministic DDIM few-step",
            build_step_many_ddim_report),
        ProgramSpec(
            "serving_warmup",
            "serving-warmup view-step program routed via ProgramCache",
            build_serving_warmup_report),
        ProgramSpec(
            "step_many_cascade_draft",
            "cascade draft phase: resolution-adapted student, few-step "
            "DDIM at the draft resolution",
            build_step_many_cascade_draft_report, tier1=True),
        ProgramSpec(
            "step_many_cascade_refine",
            "cascade refine phase: start_t-truncated scan with the "
            "upsampled-draft operand",
            build_step_many_cascade_refine_report, tier1=True),
    )
}

TIER1_PROGRAMS = tuple(s.name for s in REGISTRY.values() if s.tier1)


def default_manifest_dir(root: Optional[str] = None) -> str:
    if root is None:
        root = _find_root()
    return os.path.join(root, budgets_lib.DEFAULT_MANIFEST_DIR)


def _find_root() -> str:
    cur = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return cur


#: In-process report cache.  Lowering is deterministic for a fixed tree
#: (the whole premise of the manifest gates), and the shardcheck and
#: memcheck pillars analyze the SAME programs — one build feeds both
#: when they run in one process (tools/lint.py, the tier-1 pytest run).
#: Keyed by (name, builder) so a test that monkeypatches a REGISTRY
#: entry's ``build`` never sees a stale cached report.
_REPORT_CACHE: Dict[tuple, "ir.ProgramReport"] = {}


def build_report(name: str) -> "ir.ProgramReport":
    """Build (or fetch the cached) :class:`ir.ProgramReport` for a
    registered program."""
    spec = REGISTRY[name]
    key = (name, spec.build)
    report = _REPORT_CACHE.get(key)
    if report is None:
        report = _REPORT_CACHE[key] = spec.build()
    return report


def check_programs(names: Sequence[str], manifest_dir: str,
                   reports_out: Optional[list] = None) -> List[Finding]:
    """Build + analyze each named program and diff against its manifest.
    Returns ALL findings (suppressed marked), ``lint_source``-style."""
    findings: List[Finding] = []
    for nm in names:
        report = build_report(nm)
        if reports_out is not None:
            reports_out.append(report)
        findings.extend(
            budgets_lib.check_report_against_dir(report, manifest_dir))
    return findings


def update_manifests(names: Sequence[str], manifest_dir: str) -> List[str]:
    """Re-pin each named program's manifest from its current report,
    PRESERVING any suppressions the committed manifest carries (they are
    reviewed policy, not observations)."""
    from diff3d_tpu.analysis import manifests as manifests_lib
    written = []
    for nm in names:
        report = build_report(nm)
        path = budgets_lib.manifest_path(nm, manifest_dir)
        supps = manifests_lib.carry_suppressions(
            path, budgets_lib.load_manifest)
        budgets_lib.write_manifest(
            path, budgets_lib.manifest_from_report(report, supps))
        written.append(path)
    return written


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="shardcheck",
        description="IR-level sharding/communication analyzer over the "
                    "repo's pjit programs (rules SC2xx; see "
                    "docs/DESIGN.md §10)")
    p.add_argument("--program", action="append", default=None,
                   choices=sorted(REGISTRY), dest="programs",
                   help="check one program (repeatable; default: all)")
    p.add_argument("--programs-tier1", action="store_true",
                   help=f"check only the tier-1 set {TIER1_PROGRAMS}")
    p.add_argument("--manifest-dir", default=None,
                   help="manifest directory (default <root>/"
                        f"{budgets_lib.DEFAULT_MANIFEST_DIR})")
    p.add_argument("--update", action="store_true",
                   help="write manifests pinned to the current reports "
                        "(keeps existing suppressions) and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--show-suppressed", action="store_true")
    p.add_argument("--list", action="store_true", dest="list_programs",
                   help="list registered programs")
    args = p.parse_args(argv)

    if args.list_programs:
        for spec in REGISTRY.values():
            tag = " [tier1]" if spec.tier1 else ""
            print(f"{spec.name:18s} {spec.description}{tag}")
        return 0

    if args.programs and args.programs_tier1:
        print("shardcheck: --program and --programs-tier1 are exclusive",
              file=sys.stderr)
        return 2
    names = (args.programs or
             (list(TIER1_PROGRAMS) if args.programs_tier1
              else sorted(REGISTRY)))
    manifest_dir = args.manifest_dir or default_manifest_dir()

    ensure_cpu_mesh_devices()

    if args.update:
        for path in update_manifests(names, manifest_dir):
            print(f"shardcheck: wrote {path}")
        return 0

    reports: list = []
    findings = check_programs(names, manifest_dir, reports_out=reports)
    live = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if args.format == "json":
        print(json.dumps({
            "reports": [r.to_json() for r in reports],
            "findings": [dataclasses.asdict(f) for f in findings],
            "unsuppressed": len(live),
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        shown = findings if args.show_suppressed else live
        for f in shown:
            print(f.render())
        print(f"shardcheck: {len(live)} finding(s), "
              f"{len(suppressed)} suppressed, "
              f"{len(names)} program(s)")
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
