"""Static analysis + runtime invariants for the TPU hot paths.

Four layers, one contract (DESIGN.md §9–12):

  * ``analysis.lint`` — graftlint, the AST tracer-hygiene linter
    (``python -m diff3d_tpu.analysis`` walks diff3d_tpu/, tools/ and
    bench.py and exits nonzero on unsuppressed findings; tier 1 runs it
    as a gate);
  * ``analysis.ir`` / ``analysis.budgets`` / ``analysis.shardcheck`` —
    the IR-level sharding & communication analyzer: per-program
    collective/dtype/param-placement reports over lowered StableHLO and
    compiled HLO, diffed against committed budget manifests under
    ``runs/shardcheck/`` (``shardcheck`` console script; tools/lint.py
    runs both passes as one gate);
  * ``analysis.lockcheck`` / ``analysis.rules.concurrency`` — lockcheck,
    the concurrency linter for the threaded serving/checkpoint runtime:
    per-class lock-order graphs, ``# guarded-by:`` discipline, blocking
    calls and callback invocation under locks (rules LC3xx; ``lockcheck``
    console script, third leg of the tools/lint.py gate);
  * ``analysis.runtime`` / ``analysis.witness`` — the recompilation
    sentinel, transfer/donation guards and the runtime lock-order
    witness, surfaced as the ``compile_budget``/``comms_budget``/
    ``lock_witness`` pytest markers that enforce the same invariants on
    running code.
"""

from diff3d_tpu.analysis.ir import (ProgramReport, analyze_jitted,
                                    analyze_lowered, comms_summary,
                                    cost_summary)
from diff3d_tpu.analysis.lint import (Finding, lint_paths, lint_source,
                                      main)
from diff3d_tpu.analysis.lockcheck import lockcheck_paths, lockcheck_source
from diff3d_tpu.analysis.runtime import (CompileBudgetExceeded,
                                         RecompilationSentinel,
                                         assert_consumed, assert_live,
                                         compile_budget,
                                         no_host_transfers, owned)
from diff3d_tpu.analysis.witness import (LockWitness, WitnessViolation,
                                         install_witness)

__all__ = [
    "Finding", "lint_paths", "lint_source", "main",
    "lockcheck_paths", "lockcheck_source",
    "ProgramReport", "analyze_lowered", "analyze_jitted",
    "comms_summary", "cost_summary",
    "RecompilationSentinel", "CompileBudgetExceeded", "compile_budget",
    "no_host_transfers", "assert_consumed", "assert_live", "owned",
    "LockWitness", "WitnessViolation", "install_witness",
]
