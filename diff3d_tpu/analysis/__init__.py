"""Static analysis + runtime invariants for the TPU hot paths.

Two halves, one contract (DESIGN.md §9):

  * ``analysis.lint`` — graftlint, the AST tracer-hygiene linter
    (``python -m diff3d_tpu.analysis`` walks diff3d_tpu/, tools/ and
    bench.py and exits nonzero on unsuppressed findings; tier 1 runs it
    as a gate);
  * ``analysis.runtime`` — the recompilation sentinel, transfer/donation
    guards and the ``compile_budget`` pytest marker that enforce the
    same invariants on running code.
"""

from diff3d_tpu.analysis.lint import (Finding, lint_paths, lint_source,
                                      main)
from diff3d_tpu.analysis.runtime import (CompileBudgetExceeded,
                                         RecompilationSentinel,
                                         assert_consumed, assert_live,
                                         compile_budget,
                                         no_host_transfers, owned)

__all__ = [
    "Finding", "lint_paths", "lint_source", "main",
    "RecompilationSentinel", "CompileBudgetExceeded", "compile_budget",
    "no_host_transfers", "assert_consumed", "assert_live", "owned",
]
