"""lockcheck: concurrency static analysis for the threaded runtime.

The third analysis pillar (DESIGN.md §12), next to graftlint (AST
tracer hygiene) and shardcheck (IR sharding/communication): an AST
analyzer for the *threaded* parts of the codebase — the serving engine,
the async checkpointer, the prefetch loader and the native-library
loader.  It shares graftlint's engine wholesale (`analysis/lint.py`):
the same Finding type, fingerprints, JSON baseline format and
inline-suppression grammar, namespaced under its own tool tag so the
two analyzers never shadow each other on a shared line:

    # lockcheck: disable=LC303(queue is unbounded; put never blocks)

Rules (docs/DESIGN.md §12 for the full contract):

  LC001  parse-error              file does not parse (engine-emitted)
  LC002  reasonless-suppression   suppression without a (reason)
  LC301  lock-order-cycle         A->B and B->A acquisition orders
  LC302  unguarded-access         '# guarded-by:' state touched unlocked
  LC303  blocking-under-lock      wait/get/put/sleep/sync under a lock
  LC304  wait-without-predicate   Condition.wait outside a while loop
  LC305  thread-leak              Thread neither daemon nor joined
  LC306  callback-under-lock      user callback invoked under the lock
  LC307  double-acquire           non-reentrant Lock re-acquired
  LC308  unguarded-global-mutation thread target writes a bare global

The static half is deliberately conservative (unknown receivers stay
silent); its blind spots — cross-class orders, locks passed by
argument — are covered at runtime by ``analysis/witness.py`` and the
``@pytest.mark.lock_witness`` marker.

Exit codes: 0 clean, 1 unsuppressed findings, 2 bad invocation.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import List, Optional, Sequence

from diff3d_tpu.analysis.lint import (DEFAULT_TARGETS, Finding,
                                      _find_root, apply_baseline,
                                      iter_python_files, lint_paths,
                                      lint_source, load_baseline,
                                      write_baseline)
from diff3d_tpu.analysis.rules.concurrency import LC_RULES

DEFAULT_BASELINE = ".lockcheck-baseline.json"

TOOL = "lockcheck"
PARSE_RULE = "LC001"
REASONLESS_RULE = "LC002"


def lockcheck_source(path: str, source: str,
                     rules: Optional[Sequence] = None) -> List[Finding]:
    """Lint one file's source with the LC rule pack."""
    return lint_source(path, source, LC_RULES if rules is None else rules,
                       tool=TOOL, parse_rule=PARSE_RULE,
                       reasonless_rule=REASONLESS_RULE)


def lockcheck_paths(targets: Sequence[str],
                    rules: Optional[Sequence] = None) -> List[Finding]:
    return lint_paths(targets, LC_RULES if rules is None else rules,
                      tool=TOOL, parse_rule=PARSE_RULE,
                      reasonless_rule=REASONLESS_RULE)


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="lockcheck",
        description="concurrency static analyzer (rules LC3xx; see "
                    "docs/DESIGN.md §12)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to check (default: diff3d_tpu, "
                        "tools, bench.py under the repo root)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default <root>/"
                        f"{DEFAULT_BASELINE} when present)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write current unsuppressed findings to the "
                        "baseline and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also print suppressed findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in LC_RULES:
            print(f"{rule.id}  {rule.name:28s} [{rule.severity}] "
                  f"{rule.description}")
        return 0

    root = _find_root(os.getcwd())
    if args.paths:
        targets = list(args.paths)
    else:
        targets = [os.path.join(root, t) for t in DEFAULT_TARGETS]
        targets = [t for t in targets if os.path.exists(t)]
        if not targets:
            print("lockcheck: no default targets found under "
                  f"{root}", file=sys.stderr)
            return 2

    baseline_path = args.baseline or os.path.join(root, DEFAULT_BASELINE)
    findings = lockcheck_paths(targets)

    if args.update_baseline:
        n = write_baseline(baseline_path, findings, root, tool=TOOL)
        print(f"lockcheck: baseline written to {baseline_path} "
              f"({n} entries)")
        return 0

    try:
        baseline = load_baseline(baseline_path)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"lockcheck: {e}", file=sys.stderr)
        return 2
    findings = apply_baseline(findings, baseline, root)

    live = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if args.format == "json":
        print(json.dumps({
            "findings": [dataclasses.asdict(f) for f in findings],
            "unsuppressed": len(live),
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        shown = findings if args.show_suppressed else live
        for f in shown:
            print(f.render())
        print(f"lockcheck: {len(live)} finding(s), "
              f"{len(suppressed)} suppressed, "
              f"{len(iter_python_files(targets))} file(s)")
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
