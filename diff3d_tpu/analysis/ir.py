"""IR-level sharding & communication analyzer (shardcheck's engine).

graftlint (``analysis/lint.py``) audits the Python AST; this module
audits what XLA actually *lowered* — the layer where the regressions
that cost chips live.  An fsdp param that silently compiled as fully
replicated, an implicit resharding all-gather inside the 256-step scan,
a bf16 model upcasting to f32 mid-graph: none of these are visible in
source, all of them are visible in the StableHLO / compiled-HLO text of
a pjit program (GSPMD propagates sharding decisions at the IR level, so
that is where they must be checked).

One :class:`ProgramReport` per compiled program, extracted from three
places:

  * the **lowered StableHLO** (``lowered.as_text()``) — source-level
    facts that survive verbatim: explicit resharding sites
    (``custom_call @Sharding`` from ``with_sharding_constraint``),
    dtype upcasts (``stablehlo.convert`` widening a float or landing in
    f64), and host callbacks (``@xla_python_cpu_callback`` and
    friends) inside the traced body;
  * the **compiled (post-SPMD-partitioning) HLO**
    (``compiled.as_text()``) — the collectives GSPMD inserted:
    all-gather / all-reduce / reduce-scatter / collective-permute /
    all-to-all, with instruction counts and per-device result bytes;
  * the **compiled input shardings** — the parameter-sharding table,
    diffed against the mesh policy's intent
    (:meth:`~diff3d_tpu.parallel.MeshEnv.params`) so an fsdp-policy
    param that lowered replicated is flagged by name.

``analysis/budgets.py`` checks reports against committed per-program
budget manifests; ``analysis/shardcheck.py`` is the program registry +
CLI; ``tools/flops_report.py`` and ``bench.py`` consume
:func:`cost_summary` / :func:`comms_summary` so perf numbers and comms
counts come from one extraction path.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Tuple

#: Collective opcodes tracked in compiled HLO (async ``-start`` forms
#: are folded into the base opcode; ``-done`` halves are skipped).
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}
_FLOAT_BYTES = {"f8e4m3fn": 1, "f8e5m2": 1, "f16": 2, "bf16": 2,
                "f32": 4, "f64": 8}

# ``f32[16,8]{1,0}`` / ``pred[]`` tokens inside an HLO result type.
_HLO_SHAPE_RE = re.compile(r"\b([a-z]\d*[a-z0-9]*)\[([0-9,]*)\]")
# ``%name = <result-type> <opcode>(`` — the instruction head.
_HLO_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%[\w.\-]+\s*=\s*(?P<result>.*?)\s+"
    r"(?P<op>[a-z][a-z0-9\-]*)\(")
_HLO_CONVERT_RE = re.compile(
    r"=\s*([a-z]\d*[a-z0-9]*)\[[0-9,]*\][^ ]*\s+convert\("
    r"\s*([a-z]\d*[a-z0-9]*)\[")
# stablehlo.convert %x : (tensor<16x8xbf16>) -> tensor<16x8xf32>
_SHLO_CONVERT_RE = re.compile(
    r"stablehlo\.convert\s+%\S+\s*:\s*\(tensor<([^>]*)>\)\s*->\s*"
    r"tensor<([^>]*)>")
_SHLO_SHARDING_RE = re.compile(
    r"stablehlo\.custom_call\s+@Sharding\b[^\n]*?"
    r"mhlo\.sharding\s*=\s*\"([^\"]*)\"")
_SHLO_CALLBACK_RE = re.compile(
    r"stablehlo\.custom_call\s+@([\w.]*callback[\w.]*)")
_HLO_CALLBACK_RE = re.compile(
    r"custom_call_target=\"([^\"]*callback[^\"]*)\"")


def _tensor_dtype(tensor_type: str) -> str:
    """``"16x8xbf16"`` / ``"f32"`` -> element dtype."""
    return tensor_type.split("x")[-1].strip()


def _shape_bytes(dtype: str, dims: str) -> int:
    n = 1
    for d in dims.split(","):
        d = d.strip()
        if d:
            n *= int(d)
    return n * _DTYPE_BYTES.get(dtype, 4)


def _is_upcast(src: str, dst: str) -> bool:
    """Widening float conversion, or anything landing in f64."""
    if dst == "f64" and src != "f64":
        return True
    if src in _FLOAT_BYTES and dst in _FLOAT_BYTES:
        return _FLOAT_BYTES[dst] > _FLOAT_BYTES[src]
    return False


@dataclasses.dataclass
class CollectiveStat:
    """One collective opcode's footprint in a compiled program."""

    op: str
    count: int = 0
    bytes: int = 0     # per-device result bytes, summed over instructions

    def to_json(self) -> dict:
        return {"count": self.count, "bytes": self.bytes}


@dataclasses.dataclass
class ReshardingSite:
    """One explicit sharding constraint in the lowered program."""

    sharding: str      # the mhlo.sharding annotation text

    def to_json(self) -> dict:
        return {"sharding": self.sharding}


@dataclasses.dataclass
class ParamShardingEntry:
    """One parameter leaf: lowered spec vs the policy's intended spec."""

    path: str
    shape: Tuple[int, ...]
    dtype: str
    actual: str        # str(PartitionSpec) as lowered
    expected: Optional[str]   # policy intent; None when no mesh/policy
    flagged: bool = False     # expected sharded, lowered replicated

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ProgramReport:
    """Everything shardcheck knows about one lowered pjit program."""

    name: str
    mesh_shape: Dict[str, int]
    collectives: Dict[str, CollectiveStat]
    resharding_sites: List[ReshardingSite]
    dtype_upcasts: Dict[str, int]         # "bf16->f32" -> count
    host_callbacks: List[str]             # custom-call target names
    param_table: List[ParamShardingEntry]
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    #: memcheck's :class:`~diff3d_tpu.analysis.mem.MemoryReport` for the
    #: same compiled program (None when analysis was skipped).
    memory: Optional[object] = None
    #: equivcheck's :class:`~diff3d_tpu.analysis.equiv.SemanticReport`
    #: for the same lowering (None when analysis was skipped).  Kept out
    #: of :meth:`to_json` — equivcheck pins its own manifests under
    #: ``runs/equivcheck/``; shardcheck manifests stay unchanged.
    semantic: Optional[object] = None

    @property
    def total_collective_bytes(self) -> int:
        return sum(c.bytes for c in self.collectives.values())

    @property
    def total_collective_count(self) -> int:
        return sum(c.count for c in self.collectives.values())

    @property
    def replicated_policy_params(self) -> List[str]:
        """Paths of params the policy wanted sharded but lowered
        replicated — the silent-replication regression."""
        return [e.path for e in self.param_table if e.flagged]

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "mesh": dict(self.mesh_shape),
            "collectives": {op: c.to_json()
                            for op, c in sorted(self.collectives.items())},
            "total_collective_bytes": self.total_collective_bytes,
            "resharding_sites": [s.to_json()
                                 for s in self.resharding_sites],
            "dtype_upcasts": dict(sorted(self.dtype_upcasts.items())),
            "host_callbacks": list(self.host_callbacks),
            "replicated_policy_params": self.replicated_policy_params,
            "num_params": len(self.param_table),
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "memory": (self.memory.to_json()
                       if self.memory is not None else None),
        }


# -- text parsers ------------------------------------------------------


def parse_compiled_collectives(hlo_text: str) -> Dict[str, CollectiveStat]:
    """Collective instructions of a compiled (partitioned) HLO module.

    ``bytes`` is the instruction's *result* size as printed — the
    per-device buffer the collective materialises (tuple results, e.g.
    variadic all-reduce, sum their elements).  Async pairs count once:
    ``-start`` carries the stats, ``-done`` is skipped.
    """
    out: Dict[str, CollectiveStat] = {}
    for line in hlo_text.splitlines():
        m = _HLO_OP_RE.match(line)
        if not m:
            continue
        op = m.group("op")
        if op.endswith("-done"):
            continue
        base = op[:-6] if op.endswith("-start") else op
        if base not in COLLECTIVE_OPS:
            continue
        stat = out.setdefault(base, CollectiveStat(op=base))
        stat.count += 1
        stat.bytes += sum(_shape_bytes(d, dims) for d, dims
                          in _HLO_SHAPE_RE.findall(m.group("result")))
    return out


def parse_compiled_upcasts(hlo_text: str) -> Dict[str, int]:
    """``convert`` instructions that widen a float (or land in f64) in
    the compiled module — includes converts XLA itself introduced."""
    out: Dict[str, int] = {}
    for dst, src in _HLO_CONVERT_RE.findall(hlo_text):
        if _is_upcast(src, dst):
            key = f"{src}->{dst}"
            out[key] = out.get(key, 0) + 1
    return out


def parse_stablehlo(txt: str) -> dict:
    """Source-level facts from the lowered (pre-partitioning) StableHLO:
    upcasts the *program asked for*, explicit sharding-constraint sites,
    and host callbacks in the traced body."""
    upcasts: Dict[str, int] = {}
    for src_t, dst_t in _SHLO_CONVERT_RE.findall(txt):
        src, dst = _tensor_dtype(src_t), _tensor_dtype(dst_t)
        if _is_upcast(src, dst):
            key = f"{src}->{dst}"
            upcasts[key] = upcasts.get(key, 0) + 1
    sites = [ReshardingSite(sharding=s)
             for s in _SHLO_SHARDING_RE.findall(txt)]
    callbacks = sorted(set(_SHLO_CALLBACK_RE.findall(txt)))
    return {"dtype_upcasts": upcasts, "resharding_sites": sites,
            "host_callbacks": callbacks}


# -- param-sharding table ----------------------------------------------


def _spec_str(sharding) -> str:
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return str(sharding)
    return str(tuple(spec))


def _is_replicated(sharding) -> bool:
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return False
    return all(axis is None for axis in tuple(spec))


def param_sharding_table(params_template, actual_shardings,
                         expected_shardings=None
                         ) -> List[ParamShardingEntry]:
    """Per-leaf table of lowered vs intended placement.

    ``params_template`` is the params pytree (arrays or
    ``ShapeDtypeStruct``s), ``actual_shardings`` the matching pytree of
    lowered shardings (``compiled.input_shardings`` for the params
    argument), ``expected_shardings`` the policy pytree
    (``MeshEnv.params(template)``).  A leaf is *flagged* when the policy
    wanted it sharded but it lowered fully replicated.
    """
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(params_template)[0]
    actual = jax.tree_util.tree_leaves(
        actual_shardings, is_leaf=lambda x: hasattr(x, "spec"))
    expected = (jax.tree_util.tree_leaves(
        expected_shardings, is_leaf=lambda x: hasattr(x, "spec"))
        if expected_shardings is not None else [None] * len(leaves))
    if not (len(leaves) == len(actual) == len(expected)):
        raise ValueError(
            f"param table arity mismatch: {len(leaves)} leaves, "
            f"{len(actual)} actual shardings, {len(expected)} expected")
    table = []
    for (path, leaf), act, exp in zip(leaves, actual, expected):
        flagged = (exp is not None
                   and not _is_replicated(exp)
                   and _is_replicated(act))
        table.append(ParamShardingEntry(
            path=jax.tree_util.keystr(path),
            shape=tuple(getattr(leaf, "shape", ())),
            dtype=str(getattr(leaf, "dtype", "?")),
            actual=_spec_str(act),
            expected=None if exp is None else _spec_str(exp),
            flagged=flagged))
    return table


# -- report assembly ---------------------------------------------------


def cost_summary(compiled) -> Dict[str, Optional[float]]:
    """``{"flops", "bytes_accessed"}`` from XLA cost analysis — the one
    extraction path shared by flops_report, bench, and the manifests."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {"flops": None, "bytes_accessed": None}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not ca:
        return {"flops": None, "bytes_accessed": None}
    return {"flops": ca.get("flops"),
            "bytes_accessed": ca.get("bytes accessed")}


def _mesh_shape_of(shardings) -> Dict[str, int]:
    import jax

    for sh in jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "mesh")):
        mesh = getattr(sh, "mesh", None)
        if mesh is not None:
            return {str(k): int(v) for k, v in mesh.shape.items()}
    return {}


def analyze_lowered(name: str, lowered, *, params_template=None,
                    params_argnum: int = 0,
                    expected_param_shardings=None) -> ProgramReport:
    """Build a :class:`ProgramReport` from a ``jax.stages.Lowered``.

    Compiles the lowered program (the persistent compilation cache makes
    re-analysis of an already-built program cheap) and merges the
    StableHLO-level facts with the partitioned-HLO collectives and the
    input-sharding table.  ``params_template``/``params_argnum`` locate
    the parameter pytree among the program's positional arguments;
    ``expected_param_shardings`` is the policy pytree to diff against
    (both optional — without them the param table is empty).
    """
    stablehlo_text = lowered.as_text()
    shlo = parse_stablehlo(stablehlo_text)
    compiled = lowered.compile()
    hlo_text = compiled.as_text()
    collectives = parse_compiled_collectives(hlo_text)
    for target in _HLO_CALLBACK_RE.findall(hlo_text):
        if target not in shlo["host_callbacks"]:
            shlo["host_callbacks"].append(target)

    table: List[ParamShardingEntry] = []
    mesh_shape: Dict[str, int] = {}
    try:
        in_shardings = compiled.input_shardings[0]
        mesh_shape = _mesh_shape_of(in_shardings)
        if params_template is not None:
            # params_argnum: positional index of the params pytree, or a
            # callable extracting it (e.g. the train step's params live
            # inside the state at argnum 0: ``lambda sh: sh[0].params``).
            actual = (params_argnum(in_shardings)
                      if callable(params_argnum)
                      else in_shardings[params_argnum])
            table = param_sharding_table(params_template, actual,
                                         expected_param_shardings)
    except Exception:
        # Shardings are advisory for the report: a backend that does not
        # expose them still yields the comms/dtype/callback sections.
        table = table or []

    cost = cost_summary(compiled)
    # memcheck rides the same lower+compile pass (lazy import: mem
    # depends on this module for the dtype table).
    from diff3d_tpu.analysis import mem as _mem

    memory = _mem.build_memory_report(
        name, stablehlo_text, compiled,
        requested=_mem.requested_donations(lowered))
    # equivcheck rides it too: the canonical semantic fingerprint is a
    # pure function of the StableHLO text already in hand.
    from diff3d_tpu.analysis import equiv as _equiv

    semantic = _equiv.build_semantic_report(name, stablehlo_text)
    return ProgramReport(
        name=name, mesh_shape=mesh_shape, collectives=collectives,
        resharding_sites=shlo["resharding_sites"],
        dtype_upcasts=shlo["dtype_upcasts"],
        host_callbacks=sorted(shlo["host_callbacks"]),
        param_table=table, flops=cost["flops"],
        bytes_accessed=cost["bytes_accessed"], memory=memory,
        semantic=semantic)


def analyze_jitted(name: str, fn, *abstract_args, params_template=None,
                   params_argnum: int = 0,
                   expected_param_shardings=None) -> ProgramReport:
    """Lower ``fn`` (anything with ``.lower`` — a jitted callable or the
    sharded train/distill step wrappers) on abstract args and analyze."""
    lowered = fn.lower(*abstract_args)
    return analyze_lowered(
        name, lowered, params_template=params_template,
        params_argnum=params_argnum,
        expected_param_shardings=expected_param_shardings)


def abstractify(tree):
    """Pytree of arrays -> matching ``ShapeDtypeStruct`` pytree (lower
    programs without staging real buffers through the dev tunnel)."""
    import jax

    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(jax.numpy.shape(x), x.dtype), tree)


def comms_summary(report: ProgramReport) -> dict:
    """The compact block bench.py embeds next to each perf number."""
    return {
        "collectives": {op: c.to_json()
                        for op, c in sorted(report.collectives.items())},
        "total_collective_bytes": report.total_collective_bytes,
        "resharding_sites": len(report.resharding_sites),
        "dtype_upcasts": dict(sorted(report.dtype_upcasts.items())),
        "host_callbacks": len(report.host_callbacks),
        "replicated_policy_params": report.replicated_policy_params,
    }
