"""Runtime lock-order witness for the threaded serving/checkpoint runtime.

lockcheck (``analysis/rules/concurrency.py``) proves lock discipline
*statically*, but it is deliberately conservative: aliased locks, locks
passed across modules and orderings that only exist at runtime are
outside its model.  This module covers that blind spot by *watching* a
live run: every ``threading.Lock``/``RLock``/``Condition``/``Event``
created while the witness is installed is wrapped, each thread's stack
of held locks is tracked, and every "acquired B while holding A" pair
becomes an edge in a global lock-order graph.  At check time:

  * a **cycle** in the graph means two code paths acquire the same locks
    in opposite orders — a latent deadlock, reported with the stacks
    that created each edge, even if the interleaving that would deadlock
    never happened in this run;
  * a **held-lock wait** (``Event.wait`` holding any witness lock, or
    ``Condition.wait`` holding locks *other than* the condition's own)
    is the runtime mirror of static rule LC303.

Usage — direct::

    witness, uninstall = install_witness()
    try:
        ...  # construct + exercise the threaded system under test
    finally:
        uninstall()
    witness.check()   # raises WitnessViolation on cycles / bad waits

or via pytest (``analysis/pytest_plugin.py``)::

    @pytest.mark.lock_witness
    def test_engine_shutdown(lock_witness):
        ...  # locks created in the test body are witnessed

Only locks **created while installed** are witnessed (the wrappers are
handed out by the patched factories); module-level locks created at
import time are invisible to the witness — keep those on the static
side via ``# guarded-by:`` annotations.
"""

from __future__ import annotations

import threading
import traceback
from typing import Dict, List, Optional, Tuple

# Originals captured at import time: the wrappers and the witness's own
# bookkeeping must never route through the patched factories.
_OrigLock = threading.Lock
_OrigRLock = threading.RLock
_OrigCondition = threading.Condition

_THIS_FILE = __file__
_THREADING_FILE = threading.__file__


class WitnessViolation(AssertionError):
    """Raised by :meth:`LockWitness.check` on a lock-order cycle or a
    held-lock wait."""


def _site_name(kind: str, seq: int) -> str:
    """``Lock#3@engine.py:88`` — creation site of the wrapper, skipping
    witness/threading internals so the name points at user code."""
    for frame in reversed(traceback.extract_stack()):
        if frame.filename in (_THIS_FILE, _THREADING_FILE):
            continue
        short = frame.filename.rsplit("/", 1)[-1]
        return f"{kind}#{seq}@{short}:{frame.lineno}"
    return f"{kind}#{seq}"


def _stack_summary(limit: int = 6) -> Tuple[str, ...]:
    frames = [f for f in traceback.extract_stack()
              if f.filename not in (_THIS_FILE, _THREADING_FILE)]
    return tuple(f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno} "
                 f"in {f.name}" for f in frames[-limit:])


class LockWitness:
    """Global lock-order DAG + per-thread held stacks.

    All mutable state is guarded by ``_reg`` (an *original* lock), except
    the per-thread held stacks which live in a ``threading.local`` and
    are only touched by their owning thread.
    """

    def __init__(self):
        self._reg = _OrigLock()
        # (held_key, acquired_key) -> example stack at the acquire
        self._edges: Dict[Tuple[int, int], Tuple[str, ...]] = {}
        self._names: Dict[int, str] = {}
        self._acquisitions = 0
        self._wait_violations: List[str] = []
        self._seq = 0
        self._tls = threading.local()

    # -- registration ---------------------------------------------------

    def _register(self, kind: str) -> Tuple[int, str]:
        with self._reg:
            self._seq += 1
            seq = self._seq
        name = _site_name(kind, seq)
        with self._reg:
            self._names[seq] = name
        return seq, name

    def _held(self) -> List[int]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    # -- event hooks (called by the wrappers) ---------------------------

    def _note_acquire(self, key: int, reentrant: bool) -> None:
        held = self._held()
        first = key not in held
        if first:
            stack = _stack_summary()
            with self._reg:
                self._acquisitions += 1
                for h in held:
                    if h != key and (h, key) not in self._edges:
                        self._edges[(h, key)] = stack
        elif not reentrant:
            # Re-acquiring a non-reentrant Lock the thread already holds
            # would deadlock for real; the raw acquire already succeeded
            # here only if another thread released it in between (i.e.
            # the wrapper is shared in a way the witness can't model), so
            # just count it.
            with self._reg:
                self._acquisitions += 1
        held.append(key)

    def _note_release(self, key: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] == key:
                del held[i]
                return

    def _drop_for_wait(self, key: int) -> int:
        """Remove every recursion level of ``key`` from the held stack
        (``Condition.wait`` fully releases the underlying lock); returns
        the count so the wake path can restore it."""
        held = self._held()
        n = held.count(key)
        self._tls.stack = [h for h in held if h != key]
        return n

    def _restore_after_wait(self, key: int, n: int) -> None:
        self._held().extend([key] * n)

    def _note_wait(self, kind: str, own_key: Optional[int]) -> None:
        held = [h for h in self._held() if h != own_key]
        if not held:
            return
        with self._reg:
            names = ", ".join(self._names.get(h, str(h)) for h in held)
            site = "; ".join(_stack_summary(3))
            self._wait_violations.append(
                f"{kind} in thread {threading.current_thread().name!r} "
                f"while holding [{names}] ({site})")

    # -- results --------------------------------------------------------

    @property
    def acquisitions(self) -> int:
        with self._reg:
            return self._acquisitions

    @property
    def wait_violations(self) -> List[str]:
        with self._reg:
            return list(self._wait_violations)

    def cycles(self) -> List[List[str]]:
        """Every distinct cycle in the lock-order graph, as lists of
        lock names (first node repeated at the end)."""
        with self._reg:
            edges = dict(self._edges)
            names = dict(self._names)
        adj: Dict[int, List[int]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)
        out: List[List[str]] = []
        seen_sets = set()

        def dfs(node: int, path: List[int], on_path: set) -> None:
            for nxt in adj.get(node, ()):
                if nxt in on_path:
                    cyc = path[path.index(nxt):] + [nxt]
                    key = frozenset(cyc)
                    if key not in seen_sets:
                        seen_sets.add(key)
                        out.append([names.get(k, str(k)) for k in cyc])
                elif nxt not in visited:
                    visited.add(nxt)
                    on_path.add(nxt)
                    dfs(nxt, path + [nxt], on_path)
                    on_path.discard(nxt)

        visited: set = set()
        for start in sorted(adj):
            if start not in visited:
                visited.add(start)
                dfs(start, [start], {start})
        return out

    def violations(self) -> List[str]:
        msgs = []
        for cyc in self.cycles():
            chain = " -> ".join(cyc)
            with self._reg:
                detail = []
                # Attach the acquire stack of one edge per cycle so the
                # report points at code, not just lock names.
                name_to_key = {v: k for k, v in self._names.items()}
                for a, b in zip(cyc, cyc[1:]):
                    stack = self._edges.get(
                        (name_to_key.get(a), name_to_key.get(b)))
                    if stack:
                        detail.append(f"  {a} -> {b} acquired at: "
                                      + " <- ".join(reversed(stack)))
            msgs.append("lock-order cycle: " + chain
                        + ("\n" + "\n".join(detail) if detail else ""))
        msgs.extend(f"held-lock wait: {v}" for v in self.wait_violations)
        return msgs

    def check(self) -> None:
        """Raise :class:`WitnessViolation` if any cycle or held-lock
        wait was observed."""
        msgs = self.violations()
        if msgs:
            raise WitnessViolation(
                f"lock witness found {len(msgs)} violation(s):\n"
                + "\n".join(msgs))

    def report(self) -> str:
        with self._reg:
            n_locks, n_edges = len(self._names), len(self._edges)
        msgs = self.violations()
        head = (f"lock witness: {n_locks} lock(s), "
                f"{self.acquisitions} acquisition(s), {n_edges} order "
                f"edge(s), {len(msgs)} violation(s)")
        return head + ("\n" + "\n".join(msgs) if msgs else "")

    def reset(self) -> None:
        with self._reg:
            self._edges.clear()
            self._wait_violations.clear()
            self._acquisitions = 0


class WitnessLock:
    """Drop-in ``threading.Lock`` recording acquisition order."""

    _KIND = "Lock"
    _REENTRANT = False

    def __init__(self, witness: LockWitness, raw=None):
        self._witness = witness
        self._raw = raw if raw is not None else _OrigLock()
        self._key, self._name = witness._register(self._KIND)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._raw.acquire(blocking, timeout)
        if ok:
            self._witness._note_acquire(self._key, self._REENTRANT)
        return ok

    def release(self) -> None:
        self._raw.release()
        self._witness._note_release(self._key)

    def locked(self) -> bool:
        return self._raw.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"<{type(self).__name__} {self._name}>"


class WitnessRLock(WitnessLock):
    """Drop-in ``threading.RLock``; re-acquisition by the owning thread
    adds no order edges (same node)."""

    _KIND = "RLock"
    _REENTRANT = True

    def __init__(self, witness: LockWitness, raw=None):
        super().__init__(witness, raw if raw is not None else _OrigRLock())

    def _is_owned(self) -> bool:
        # threading.Condition probes this on user-supplied locks.
        return self._raw._is_owned()


class WitnessCondition:
    """Drop-in ``threading.Condition``.  ``wait``/``wait_for`` release
    the witnessed lock (held-stack updated accordingly) and flag a
    violation if *other* witnessed locks are still held across the wait.
    """

    def __init__(self, witness: LockWitness, lock=None):
        self._witness = witness
        if lock is None:
            lock = WitnessRLock(witness)
        if isinstance(lock, WitnessLock):
            self._wlock = lock
            self._cond = _OrigCondition(lock._raw)
        else:
            # A raw/pre-install lock: witness can't track it, but waits
            # are still checked against the locks it does track.
            self._wlock = None
            self._cond = _OrigCondition(lock)

    def acquire(self, *args, **kwargs):
        if self._wlock is not None:
            return self._wlock.acquire(*args, **kwargs)
        return self._cond.acquire(*args, **kwargs)

    def release(self) -> None:
        if self._wlock is not None:
            self._wlock.release()
        else:
            self._cond.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        key = self._wlock._key if self._wlock is not None else None
        self._witness._note_wait("Condition.wait", key)
        n = self._witness._drop_for_wait(key) if key is not None else 0
        try:
            return self._cond.wait(timeout)
        finally:
            if key is not None:
                self._witness._restore_after_wait(key, n)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        # Reimplemented over self.wait so held-stack accounting and the
        # wait-violation check apply to every underlying wait.
        import time as _time
        end = None if timeout is None else _time.monotonic() + timeout
        result = predicate()
        while not result:
            remaining = None
            if end is not None:
                remaining = end - _time.monotonic()
                if remaining <= 0:
                    break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


class WitnessEvent:
    """Drop-in ``threading.Event``; ``wait`` while holding any witnessed
    lock is a violation (the setter may need that lock — LC303's runtime
    mirror).

    Implemented directly over original primitives rather than wrapping
    ``threading.Event``: while the witness is installed, the stock Event
    would build its internal condition from the *patched* module globals,
    double-reporting every wait and registering phantom locks for
    threading-internal events (``Thread._started``)."""

    def __init__(self, witness: LockWitness):
        self._witness = witness
        self._cond = _OrigCondition(_OrigLock())
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        with self._cond:
            self._flag = True
            self._cond.notify_all()

    def clear(self) -> None:
        with self._cond:
            self._flag = False

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._witness._note_wait("Event.wait", None)
        with self._cond:
            return self._cond.wait_for(lambda: self._flag, timeout)


def install_witness(witness: Optional[LockWitness] = None):
    """Monkeypatch ``threading.Lock``/``RLock``/``Condition``/``Event``
    so every lock created while installed is witnessed.  Returns
    ``(witness, uninstall)``; call ``uninstall()`` (idempotent) to
    restore whatever the factories were before this install.

    Wrappers survive uninstall — locks created under the witness keep
    reporting to it for their lifetime.
    """
    w = witness if witness is not None else LockWitness()
    prior = (threading.Lock, threading.RLock, threading.Condition,
             threading.Event)

    def _lock():
        return WitnessLock(w)

    def _rlock():
        return WitnessRLock(w)

    def _condition(lock=None):
        return WitnessCondition(w, lock)

    def _event():
        return WitnessEvent(w)

    threading.Lock = _lock
    threading.RLock = _rlock
    threading.Condition = _condition
    threading.Event = _event

    done = []

    def uninstall() -> None:
        if done:
            return
        done.append(True)
        (threading.Lock, threading.RLock, threading.Condition,
         threading.Event) = prior

    return w, uninstall
