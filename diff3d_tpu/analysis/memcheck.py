"""memcheck — memory/recompute gate over the repo's pjit programs.

The fourth analysis pillar (graftlint AST, shardcheck IR/comms,
lockcheck concurrency, **memcheck memory**).  It deliberately has no
program registry of its own: the programs whose comms footprint
shardcheck pins are exactly the programs whose memory footprint matters
(sharded train step, distill step, step_many ancestral + DDIM, serving
warmup), so this module reuses
:data:`~diff3d_tpu.analysis.shardcheck.REGISTRY` and rides the same
lower+compile pass — ``ir.analyze_lowered`` attaches a
:class:`~diff3d_tpu.analysis.mem.MemoryReport` to every
:class:`~diff3d_tpu.analysis.ir.ProgramReport` it builds, and this CLI
diffs those against manifests under ``runs/memcheck/`` (rules MC4xx,
``docs/DESIGN.md`` §13).

Workflow mirrors shardcheck::

    memcheck                      # check all programs vs manifests
    memcheck --programs-tier1     # the tier-1 gate (tools/lint.py)
    memcheck --update             # re-pin manifests, keep suppressions
    memcheck --program step_many --format json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import List, Optional, Sequence

from diff3d_tpu.analysis import membudgets as membudgets_lib
from diff3d_tpu.analysis import shardcheck as shardcheck_lib
from diff3d_tpu.analysis.lint import Finding
from diff3d_tpu.analysis.mem import MemoryReport, memory_summary
from diff3d_tpu.analysis.shardcheck import (REGISTRY, TIER1_PROGRAMS,
                                            ensure_cpu_mesh_devices)


def default_manifest_dir(root: Optional[str] = None) -> str:
    if root is None:
        root = shardcheck_lib._find_root()
    return os.path.join(root, membudgets_lib.DEFAULT_MANIFEST_DIR)


def memory_report_for(name: str) -> MemoryReport:
    """Build the registered program (through shardcheck's in-process
    report cache — both pillars analyze the same compiled programs)
    and return its memory report."""
    report = shardcheck_lib.build_report(name)
    mem = report.memory
    if mem is None:
        # analyze_lowered always attaches one; a None here means an
        # out-of-band builder — treat as an empty (nothing-observed)
        # report so budget checks still run.
        mem = MemoryReport(name=name, available=False)
    return mem


def check_programs(names: Sequence[str], manifest_dir: str,
                   reports_out: Optional[list] = None) -> List[Finding]:
    """Build + analyze each named program and diff its memory report
    against the committed manifest.  Returns ALL findings (suppressed
    marked), ``lint_source``-style."""
    findings: List[Finding] = []
    for nm in names:
        mem = memory_report_for(nm)
        if reports_out is not None:
            reports_out.append(mem)
        findings.extend(
            membudgets_lib.check_report_against_dir(mem, manifest_dir))
    return findings


def update_manifests(names: Sequence[str], manifest_dir: str) -> List[str]:
    """Re-pin each named program's manifest from its current memory
    report, PRESERVING any suppressions the committed manifest carries
    (they are reviewed policy, not observations)."""
    from diff3d_tpu.analysis import manifests as manifests_lib
    written = []
    for nm in names:
        mem = memory_report_for(nm)
        path = membudgets_lib.manifest_path(nm, manifest_dir)
        supps = manifests_lib.carry_suppressions(
            path, membudgets_lib.load_manifest)
        membudgets_lib.write_manifest(
            path, membudgets_lib.manifest_from_report(mem, supps))
        written.append(path)
    return written


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="memcheck",
        description="HLO-level memory & recompute analyzer over the "
                    "repo's pjit programs (rules MC4xx; see "
                    "docs/DESIGN.md §13)")
    p.add_argument("--program", action="append", default=None,
                   choices=sorted(REGISTRY), dest="programs",
                   help="check one program (repeatable; default: all)")
    p.add_argument("--programs-tier1", action="store_true",
                   help=f"check only the tier-1 set {TIER1_PROGRAMS}")
    p.add_argument("--manifest-dir", default=None,
                   help="manifest directory (default <root>/"
                        f"{membudgets_lib.DEFAULT_MANIFEST_DIR})")
    p.add_argument("--update", action="store_true",
                   help="write manifests pinned to the current reports "
                        "(keeps existing suppressions) and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--show-suppressed", action="store_true")
    p.add_argument("--list", action="store_true", dest="list_programs",
                   help="list registered programs")
    args = p.parse_args(argv)

    if args.list_programs:
        for spec in REGISTRY.values():
            tag = " [tier1]" if spec.tier1 else ""
            print(f"{spec.name:18s} {spec.description}{tag}")
        return 0

    if args.programs and args.programs_tier1:
        print("memcheck: --program and --programs-tier1 are exclusive",
              file=sys.stderr)
        return 2
    names = (args.programs or
             (list(TIER1_PROGRAMS) if args.programs_tier1
              else sorted(REGISTRY)))
    manifest_dir = args.manifest_dir or default_manifest_dir()

    ensure_cpu_mesh_devices()

    if args.update:
        for path in update_manifests(names, manifest_dir):
            print(f"memcheck: wrote {path}")
        return 0

    reports: list = []
    findings = check_programs(names, manifest_dir, reports_out=reports)
    live = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if args.format == "json":
        print(json.dumps({
            "reports": [r.to_json() for r in reports],
            "summaries": {r.name: memory_summary(r) for r in reports},
            "findings": [dataclasses.asdict(f) for f in findings],
            "unsuppressed": len(live),
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        shown = findings if args.show_suppressed else live
        for f in shown:
            print(f.render())
        print(f"memcheck: {len(live)} finding(s), "
              f"{len(suppressed)} suppressed, "
              f"{len(names)} program(s)")
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
