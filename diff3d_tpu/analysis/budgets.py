"""Per-program comms budget manifests for shardcheck.

A **manifest** pins one pjit program's communication/dtype footprint:
how many of each collective (and how many bytes), how many explicit
resharding sites, which dtype upcasts, how many host callbacks, and
whether the param-sharding policy must hold.  Manifests are JSON files
committed under ``runs/shardcheck/`` — one per registered program — so
a PR that makes the train step start all-gathering its fsdp params
shows up as a *diff against a committed file*, reviewable like any
other regression.

Checking a :class:`~diff3d_tpu.analysis.ir.ProgramReport` against its
manifest yields graftlint-compatible :class:`Finding`s (rules SC2xx,
fingerprinted via ``fingerprint_data`` so they share the baseline
format).  Suppressions follow the same reason-mandatory discipline as
graftlint's inline comments, but live in the manifest itself::

    "suppressions": [
      {"rule": "SC204", "key": "bf16->f32",
       "reason": "loss accumulates in f32 by design"}
    ]

``key`` scopes the suppression to one subject (a collective op, an
upcast pair, a param path); ``"*"`` covers the whole rule.  A
suppression without a reason is itself reported (SC002, mirroring
graftlint's GL002).

Rules:

  SC002  manifest suppression without a reason        (warning)
  SC201  fsdp/tp-policy param lowered fully replicated (error)
  SC202  collective instruction count over budget      (error)
  SC203  collective bytes over budget                  (error)
  SC204  dtype upcast not in budget / over count       (error)
  SC205  host callback not in budget                   (error)
  SC206  resharding sites over budget                  (error)
  SC207  program has no committed manifest             (error)
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

from diff3d_tpu.analysis import manifests as manifests_lib
from diff3d_tpu.analysis.ir import ProgramReport
from diff3d_tpu.analysis.lint import (Finding, SEVERITY_ERROR,
                                      SEVERITY_WARNING)
from diff3d_tpu.analysis.manifests import Suppression, manifest_path  # noqa: F401 (re-exported API)

#: Default manifest directory, relative to the repo root.
DEFAULT_MANIFEST_DIR = os.path.join("runs", "shardcheck")

MANIFEST_VERSION = 1
MANIFEST_TOOL = "shardcheck"


@dataclasses.dataclass
class Budget:
    """The limits a manifest imposes.  ``collectives`` maps opcode to
    ``{"count": n, "bytes": n}`` ceilings; ``dtype_upcasts`` maps
    ``"src->dst"`` to a count ceiling (absent pair = forbidden);
    ``host_callbacks`` is a list of *allowed* custom-call targets."""

    collectives: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    resharding_sites: int = 0
    dtype_upcasts: Dict[str, int] = dataclasses.field(default_factory=dict)
    host_callbacks: List[str] = dataclasses.field(default_factory=list)
    require_param_policy: bool = True


@dataclasses.dataclass
class Manifest:
    program: str
    mesh: Dict[str, int]
    budgets: Budget
    observed: dict = dataclasses.field(default_factory=dict)
    suppressions: List[Suppression] = dataclasses.field(
        default_factory=list)

    def to_json(self) -> dict:
        return {
            "version": MANIFEST_VERSION,
            "tool": MANIFEST_TOOL,
            "program": self.program,
            "mesh": dict(self.mesh),
            "budgets": dataclasses.asdict(self.budgets),
            "observed": self.observed,
            "suppressions": [dataclasses.asdict(s)
                             for s in self.suppressions],
        }


def load_manifest(path: str) -> Manifest:
    data = manifests_lib.load_manifest_data(
        path, MANIFEST_TOOL, MANIFEST_VERSION, "shardcheck manifest")
    b = data.get("budgets", {})
    budgets = Budget(
        collectives={str(k): {"count": int(v.get("count", 0)),
                              "bytes": int(v.get("bytes", 0))}
                     for k, v in b.get("collectives", {}).items()},
        resharding_sites=int(b.get("resharding_sites", 0)),
        dtype_upcasts={str(k): int(v)
                       for k, v in b.get("dtype_upcasts", {}).items()},
        host_callbacks=[str(x) for x in b.get("host_callbacks", [])],
        require_param_policy=bool(b.get("require_param_policy", True)))
    supps = manifests_lib.parse_suppressions(data.get("suppressions", []))
    return Manifest(program=str(data.get("program", "")),
                    mesh={str(k): int(v)
                          for k, v in data.get("mesh", {}).items()},
                    budgets=budgets,
                    observed=data.get("observed", {}),
                    suppressions=supps)


def write_manifest(path: str, manifest: Manifest) -> None:
    manifests_lib.write_manifest_data(path, manifest.to_json())


def manifest_from_report(report: ProgramReport,
                         suppressions: Optional[
                             Sequence[Suppression]] = None) -> Manifest:
    """Pin a report as the budget: observed counts become the ceilings.

    Lowering is deterministic for fixed shapes/mesh, so exact pins are
    the right default — any drift is a diff a human reviews (and either
    accepts by re-pinning with ``--update`` or fixes).
    """
    budgets = Budget(
        collectives={op: c.to_json()
                     for op, c in sorted(report.collectives.items())},
        resharding_sites=len(report.resharding_sites),
        dtype_upcasts=dict(sorted(report.dtype_upcasts.items())),
        host_callbacks=list(report.host_callbacks),
        require_param_policy=True)
    return Manifest(program=report.name, mesh=dict(report.mesh_shape),
                    budgets=budgets, observed=report.to_json(),
                    suppressions=list(suppressions or []))


# -- checking ----------------------------------------------------------


def _finding(manifest_file: str, rule: str, program: str, key: str,
             message: str, severity: str = SEVERITY_ERROR) -> Finding:
    return Finding(
        path=manifest_file, rule=rule, line=1, col=0, severity=severity,
        message=f"[{program}] {message}",
        fingerprint_data=f"{program}\x00{rule}\x00{key}")


def check_report(report: ProgramReport, manifest: Manifest,
                 manifest_file: str) -> List[Finding]:
    """Diff a program report against its manifest.  Returns ALL findings
    (suppressed ones marked), same contract as ``lint_source``."""
    raw: List[Finding] = []
    b = manifest.budgets
    prog = report.name

    if b.require_param_policy:
        for path in report.replicated_policy_params:
            raw.append(_finding(
                manifest_file, "SC201", prog, path,
                f"param {path} lowered fully replicated but the mesh "
                f"policy shards it — silent replication (check "
                f"param_sharding thresholds / divisibility)"))

    for op, stat in sorted(report.collectives.items()):
        limit = b.collectives.get(op)
        if limit is None:
            raw.append(_finding(
                manifest_file, "SC202", prog, op,
                f"unbudgeted collective {op}: {stat.count} instruction(s)"
                f", {stat.bytes} bytes (manifest has no entry)"))
            continue
        if stat.count > limit["count"]:
            raw.append(_finding(
                manifest_file, "SC202", prog, op,
                f"{op} count {stat.count} exceeds budget "
                f"{limit['count']}"))
        if stat.bytes > limit["bytes"]:
            raw.append(_finding(
                manifest_file, "SC203", prog, op,
                f"{op} bytes {stat.bytes} exceed budget "
                f"{limit['bytes']}"))

    for pair, count in sorted(report.dtype_upcasts.items()):
        limit = b.dtype_upcasts.get(pair)
        if limit is None:
            raw.append(_finding(
                manifest_file, "SC204", prog, pair,
                f"unbudgeted dtype upcast {pair}: {count} site(s)"))
        elif count > limit:
            raw.append(_finding(
                manifest_file, "SC204", prog, pair,
                f"dtype upcast {pair}: {count} site(s) exceed budget "
                f"{limit}"))

    for target in report.host_callbacks:
        if target not in b.host_callbacks:
            raw.append(_finding(
                manifest_file, "SC205", prog, target,
                f"host callback {target} in traced body is not in the "
                f"manifest's allowed list"))

    n_sites = len(report.resharding_sites)
    if n_sites > b.resharding_sites:
        raw.append(_finding(
            manifest_file, "SC206", prog, "resharding_sites",
            f"{n_sites} resharding site(s) exceed budget "
            f"{b.resharding_sites}"))

    return _apply_suppressions(raw, manifest, manifest_file, prog)


def _apply_suppressions(raw: Sequence[Finding], manifest: Manifest,
                        manifest_file: str, prog: str) -> List[Finding]:
    # Reason-mandatory, like graftlint inline suppressions (GL002).
    return manifests_lib.apply_suppressions(
        raw, manifest.suppressions,
        lambda s: _finding(
            manifest_file, "SC002", prog, f"{s.rule}:{s.key}",
            f"manifest suppression of {s.rule} (key={s.key!r}) has "
            f"no reason — every suppression documents why it is "
            f"safe", severity=SEVERITY_WARNING))


def missing_manifest_finding(program: str,
                             manifest_dir: str) -> Finding:
    path = manifest_path(program, manifest_dir)
    return _finding(
        path, "SC207", program, "missing",
        f"no committed manifest at {path} — run "
        f"'shardcheck --update --program {program}' and commit the "
        f"result")


def check_report_against_dir(report: ProgramReport,
                             manifest_dir: str) -> List[Finding]:
    """Load ``<dir>/<program>.json`` and check; a missing or unreadable
    manifest is itself a finding (SC207)."""
    path = manifest_path(report.name, manifest_dir)
    if not os.path.exists(path):
        return [missing_manifest_finding(report.name, manifest_dir)]
    try:
        manifest = load_manifest(path)
    except (ValueError, json.JSONDecodeError) as e:
        return [_finding(path, "SC207", report.name, "unreadable",
                         f"manifest unreadable: {e}")]
    return check_report(report, manifest, path)
