"""Runtime invariant harness: what the linter can't prove, measure.

Static analysis (``analysis/lint.py``) catches the patterns visible in
the AST; this module pins the same invariants at *run* time, replacing
the ad-hoc copies that used to live inline in
``tests/test_sampling_sharded.py`` and ``tests/test_serving.py``:

  * :class:`RecompilationSentinel` — counts compiled-program cache
    growth per tracked jitted callable and asserts a budget.  A retrace
    is invisible (jax just... compiles again); under a compile budget it
    is a hard failure with the per-callable counts in the message.
  * :func:`no_host_transfers` — scoped ``jax.transfer_guard("disallow")``:
    any implicit host<->device transfer inside the block faults.
  * :func:`assert_consumed` / :func:`assert_live` — donation guards: a
    donated input buffer must actually be deleted (the update happened
    in place), the returned carry must not be.
  * :func:`owned` — copy a host array into an XLA-owned device buffer
    before handing it to a donating program.  ``jnp.asarray`` may
    zero-copy alias aligned numpy memory (CPU backend); donating such an
    alias frees memory the XLA allocator does not own — the PR 3 heap
    corruption.  This is the same contract ``Sampler._owned`` enforces
    for the public step API, exported so tests and tools build donated
    operands one way.

The pytest side (``analysis/pytest_plugin.py``) exposes the sentinel as
the ``compile_sentinel`` fixture and enforces
``@pytest.mark.compile_budget(n)`` at teardown.
"""

from __future__ import annotations

import contextlib
from typing import Dict, Optional

import jax
import jax.numpy as jnp


class CompileBudgetExceeded(AssertionError):
    """A tracked callable compiled more programs than its budget."""


class RecompilationSentinel:
    """Per-callable compiled-program counter with budget assertions.

    Tracked callables must expose the jitted-function cache probe
    (``_cache_size``), which counts distinct compiled programs — it is
    immune to the persistent on-disk compilation cache (a disk hit still
    mints a new in-memory program entry), so budgets hold regardless of
    cache warmth.

        sentinel = RecompilationSentinel()
        sentinel.track("view_step", sampler._run_view_many)
        ... run workload ...
        sentinel.assert_budget(1)     # one program, ever

    ``track`` records the callable's CURRENT cache size as the zero
    point, so tracking an already-warm function counts only growth.
    """

    def __init__(self):
        self._fns: Dict[str, object] = {}
        self._base: Dict[str, int] = {}

    @staticmethod
    def _cache_size(fn) -> int:
        probe = getattr(fn, "_cache_size", None)
        if probe is None:
            raise TypeError(
                f"{fn!r} has no _cache_size probe — track jitted "
                "callables (jax.jit/pjit results), not plain functions")
        return int(probe())

    def track(self, name: str, fn):
        """Start counting ``fn``'s compiles under ``name``; returns
        ``fn`` so call sites can track inline."""
        self._base[name] = self._cache_size(fn)
        self._fns[name] = fn
        return fn

    def counts(self) -> Dict[str, int]:
        """Programs compiled per tracked callable since ``track``."""
        return {name: self._cache_size(fn) - self._base[name]
                for name, fn in self._fns.items()}

    def total(self) -> int:
        return sum(self.counts().values())

    def reset(self) -> None:
        for name, fn in self._fns.items():
            self._base[name] = self._cache_size(fn)

    def assert_budget(self, budget: int,
                      name: Optional[str] = None) -> None:
        """Fail if compiles exceed ``budget`` (for one callable, or the
        total across all tracked callables when ``name`` is None)."""
        counts = self.counts()
        spent = counts[name] if name is not None else sum(counts.values())
        if spent > budget:
            raise CompileBudgetExceeded(
                f"compile budget exceeded: {spent} > {budget} "
                f"({'callable ' + name if name else 'total'}; "
                f"per-callable: {counts}) — an input shape/dtype or a "
                "Python-level closure changed between calls")


@contextlib.contextmanager
def compile_budget(budget: int, **fns):
    """Scoped budget over named jitted callables::

        with compile_budget(1, view_step=sampler._run_view_many):
            sampler.synthesize_many(...)
    """
    sentinel = RecompilationSentinel()
    for name, fn in fns.items():
        sentinel.track(name, fn)
    yield sentinel
    sentinel.assert_budget(budget)


@contextlib.contextmanager
def no_host_transfers():
    """Fault on any implicit host<->device transfer inside the block.

    Wraps ``jax.transfer_guard("disallow")``: device-resident code runs
    clean, anything that silently re-stages host memory (or fetches to
    host) raises at the transfer point.  Stage all operands on device
    *before* entering the block; explicit ``jax.device_put`` inside it
    faults too — that is the point."""
    with jax.transfer_guard("disallow"):
        yield


def assert_consumed(*buffers) -> None:
    """Donation guard: every buffer must have been deleted by a donating
    call — i.e. the program reused its memory in place.  A live buffer
    here means donation silently degraded to a copy (wrong in_shardings,
    a captured reference, or a backend that refused the alias)."""
    for i, buf in enumerate(buffers):
        if not buf.is_deleted():
            raise AssertionError(
                f"donation guard: buffer {i} is still live after a "
                "donating call — the in-place update degraded to a "
                "copy (check donate_argnums and sharding specs)")


def assert_live(*buffers) -> None:
    """The returned carry of a donating call must NOT be deleted."""
    for i, buf in enumerate(buffers):
        if buf.is_deleted():
            raise AssertionError(
                f"donation guard: returned carry {i} is deleted — the "
                "caller is holding a donated input instead of the "
                "returned buffer")


def owned(x) -> jax.Array:
    """Copy ``x`` into an XLA-owned device buffer safe to donate.

    Device arrays pass through untouched (already XLA-owned); host
    arrays are uploaded and copied so no zero-copy alias of caller
    memory can be donated.
    """
    if isinstance(x, jax.Array):
        return x
    return jnp.copy(jnp.asarray(x))
