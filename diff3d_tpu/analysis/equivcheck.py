"""equivcheck — StableHLO semantic-equivalence gate over the repo's
pjit programs.

The sixth analysis pillar (graftlint AST, shardcheck IR/comms,
lockcheck concurrency, memcheck memory, rngcheck RNG lineage,
**equivcheck semantics**).  Like memcheck it has no program registry of
its own: it rides :data:`~diff3d_tpu.analysis.shardcheck.REGISTRY` and
the same lower+compile pass — ``ir.analyze_lowered`` attaches a
:class:`~diff3d_tpu.analysis.equiv.SemanticReport` to every
:class:`~diff3d_tpu.analysis.ir.ProgramReport` it builds, and this CLI
diffs those against manifests under ``runs/equivcheck/`` (rules EQ6xx,
``docs/DESIGN.md`` §18).

A **manifest** pins one program's canonical semantic form: the
fingerprint digest, the canonical line list (so EQ601 can name the
first divergent op, not just "something changed"), and ceilings for
dead outputs and duplicate subcomputation FLOPs.  Suppressions follow
the same key-scoped, reason-mandatory discipline as the other pillars::

    "suppressions": [
      {"rule": "EQ604", "key": "duplicate_flops",
       "reason": "threefry key splits duplicate by construction"}
    ]

Rules:

  EQ002  manifest suppression without a reason               (warning)
  EQ601  semantic fingerprint drift (names the divergent op)  (error)
  EQ602  hoist not verified / refuted by the hoist verifier   (error)
  EQ603  dead computation feeding no program output           (error)
  EQ604  duplicate subcomputation FLOPs over budget           (error)
  EQ605  program has no committed manifest                    (error)

Workflow mirrors memcheck::

    equivcheck                      # check all programs vs manifests
    equivcheck --programs-tier1     # the tier-1 gate (tools/lint.py)
    equivcheck --update             # re-pin manifests, keep suppressions
    equivcheck --program step_many --format json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import List, Optional, Sequence

from diff3d_tpu.analysis import manifests as manifests_lib
from diff3d_tpu.analysis import shardcheck as shardcheck_lib
from diff3d_tpu.analysis.equiv import (SemanticReport, semantic_summary,
                                       structural_diff)
from diff3d_tpu.analysis.lint import (Finding, SEVERITY_ERROR,
                                      SEVERITY_WARNING)
from diff3d_tpu.analysis.manifests import Suppression, manifest_path  # noqa: F401 (re-exported API)
from diff3d_tpu.analysis.shardcheck import (REGISTRY, TIER1_PROGRAMS,
                                            ensure_cpu_mesh_devices)

#: Default manifest directory, relative to the repo root.
DEFAULT_MANIFEST_DIR = os.path.join("runs", "equivcheck")

MANIFEST_VERSION = 1
MANIFEST_TOOL = "equivcheck"


@dataclasses.dataclass
class EquivBudget:
    """What a manifest pins.  ``digest`` is an equality pin (semantics
    either moved or they did not); the FLOP/count fields are ceilings."""

    digest: str = ""
    n_ops: int = 0
    duplicate_flops: float = 0.0
    dead_ops: int = 0


@dataclasses.dataclass
class EquivManifest:
    program: str
    budgets: EquivBudget
    observed: dict = dataclasses.field(default_factory=dict)
    suppressions: List[Suppression] = dataclasses.field(
        default_factory=list)

    def to_json(self) -> dict:
        return {
            "version": MANIFEST_VERSION,
            "tool": MANIFEST_TOOL,
            "program": self.program,
            "budgets": dataclasses.asdict(self.budgets),
            "observed": self.observed,
            "suppressions": [dataclasses.asdict(s)
                             for s in self.suppressions],
        }


def load_manifest(path: str) -> EquivManifest:
    data = manifests_lib.load_manifest_data(
        path, MANIFEST_TOOL, MANIFEST_VERSION, "equivcheck manifest")
    b = data.get("budgets", {})
    budgets = EquivBudget(
        digest=str(b.get("digest", "")),
        n_ops=int(b.get("n_ops", 0)),
        duplicate_flops=float(b.get("duplicate_flops", 0.0)),
        dead_ops=int(b.get("dead_ops", 0)))
    supps = manifests_lib.parse_suppressions(data.get("suppressions", []))
    return EquivManifest(program=str(data.get("program", "")),
                         budgets=budgets,
                         observed=data.get("observed", {}),
                         suppressions=supps)


def write_manifest(path: str, manifest: EquivManifest) -> None:
    manifests_lib.write_manifest_data(path, manifest.to_json())


def manifest_from_report(report: SemanticReport,
                         suppressions: Optional[
                             Sequence[Suppression]] = None
                         ) -> EquivManifest:
    """Pin a report: the digest becomes the equality pin, observed
    dead/duplicate figures become the ceilings."""
    budgets = EquivBudget(
        digest=report.digest,
        n_ops=report.n_ops,
        duplicate_flops=report.duplicate_flops,
        dead_ops=len(report.dead_ops))
    return EquivManifest(program=report.name, budgets=budgets,
                         observed=report.to_json(),
                         suppressions=list(suppressions or []))


# -- checking ----------------------------------------------------------


def _finding(manifest_file: str, rule: str, program: str, key: str,
             message: str, severity: str = SEVERITY_ERROR) -> Finding:
    return Finding(
        path=manifest_file, rule=rule, line=1, col=0, severity=severity,
        message=f"[{program}] {message}",
        fingerprint_data=f"{program}\x00{rule}\x00{key}")


def check_report(report: SemanticReport, manifest: EquivManifest,
                 manifest_file: str) -> List[Finding]:
    """Diff a semantic report against its manifest.  Returns ALL
    findings (suppressed ones marked), same contract as
    ``lint_source``."""
    raw: List[Finding] = []
    b = manifest.budgets
    prog = report.name

    if report.available and b.digest and report.digest != b.digest:
        diff = structural_diff(
            manifest.observed.get("lines", []), report.lines)
        raw.append(_finding(
            manifest_file, "EQ601", prog, "digest",
            f"semantic fingerprint drifted from pinned "
            f"{b.digest[:12]} to {report.digest[:12]} — "
            f"{diff or 'canonical line lists differ'}; if the change "
            f"is intended, re-pin with 'equivcheck --update'"))

    if report.available and len(report.dead_ops) > b.dead_ops:
        sample = ", ".join(
            f"{d.op} ({d.flops:.3g} FLOPs)"
            for d in report.dead_ops[:3])
        raw.append(_finding(
            manifest_file, "EQ603", prog, "dead_ops",
            f"{len(report.dead_ops)} dead computation(s) feed no "
            f"program output (budget {b.dead_ops}) — e.g. {sample}; "
            f"an output was dropped or a refactor orphaned a "
            f"subgraph"))

    dup = report.duplicate_flops
    if report.available and dup > b.duplicate_flops:
        raw.append(_finding(
            manifest_file, "EQ604", prog, "duplicate_flops",
            f"duplicate subcomputation estimate {dup:.6g} FLOPs "
            f"exceeds budget {b.duplicate_flops:.6g} — identical "
            f"canonical subgraphs are evaluated more than once "
            f"(static precursor of memcheck's MC404 recompute gate)"))

    return _apply_suppressions(raw, manifest, manifest_file, prog)


def _apply_suppressions(raw: Sequence[Finding], manifest: EquivManifest,
                        manifest_file: str, prog: str) -> List[Finding]:
    # Reason-mandatory, like the other five pillars.
    return manifests_lib.apply_suppressions(
        raw, manifest.suppressions,
        lambda s: _finding(
            manifest_file, "EQ002", prog, f"{s.rule}:{s.key}",
            f"manifest suppression of {s.rule} (key={s.key!r}) has "
            f"no reason — every suppression documents why it is "
            f"safe", severity=SEVERITY_WARNING))


def missing_manifest_finding(program: str,
                             manifest_dir: str) -> Finding:
    path = manifest_path(program, manifest_dir)
    return _finding(
        path, "EQ605", program, "missing",
        f"no committed manifest at {path} — run "
        f"'equivcheck --update --program {program}' and commit the "
        f"result")


def check_report_against_dir(report: SemanticReport,
                             manifest_dir: str) -> List[Finding]:
    """Load ``<dir>/<program>.json`` and check; a missing or unreadable
    manifest is itself a finding (EQ605)."""
    path = manifest_path(report.name, manifest_dir)
    if not os.path.exists(path):
        return [missing_manifest_finding(report.name, manifest_dir)]
    try:
        manifest = load_manifest(path)
    except (ValueError, json.JSONDecodeError) as e:
        return [_finding(path, "EQ605", report.name, "unreadable",
                         f"manifest unreadable: {e}")]
    return check_report(report, manifest, path)


# -- the CLI -----------------------------------------------------------


def default_manifest_dir(root: Optional[str] = None) -> str:
    if root is None:
        root = shardcheck_lib._find_root()
    return os.path.join(root, DEFAULT_MANIFEST_DIR)


def semantic_report_for(name: str) -> SemanticReport:
    """Build the registered program (through shardcheck's in-process
    report cache — all pillars analyze the same compiled programs) and
    return its semantic report."""
    report = shardcheck_lib.build_report(name)
    sem = getattr(report, "semantic", None)
    if sem is None:
        # analyze_lowered always attaches one; a None here means an
        # out-of-band builder — treat as an unavailable report so the
        # manifest checks still run (and EQ601 stays quiet rather than
        # firing on an empty digest).
        sem = SemanticReport(name=name, available=False)
    return sem


def check_programs(names: Sequence[str], manifest_dir: str,
                   reports_out: Optional[list] = None) -> List[Finding]:
    """Build + analyze each named program and diff its semantic report
    against the committed manifest.  Returns ALL findings (suppressed
    marked), ``lint_source``-style."""
    findings: List[Finding] = []
    for nm in names:
        sem = semantic_report_for(nm)
        if reports_out is not None:
            reports_out.append(sem)
        findings.extend(check_report_against_dir(sem, manifest_dir))
    return findings


def update_manifests(names: Sequence[str], manifest_dir: str) -> List[str]:
    """Re-pin each named program's manifest from its current semantic
    report, PRESERVING any suppressions the committed manifest carries
    (they are reviewed policy, not observations)."""
    written = []
    for nm in names:
        sem = semantic_report_for(nm)
        path = manifest_path(nm, manifest_dir)
        supps = manifests_lib.carry_suppressions(path, load_manifest)
        write_manifest(path, manifest_from_report(sem, supps))
        written.append(path)
    return written


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="equivcheck",
        description="StableHLO semantic-equivalence analyzer over the "
                    "repo's pjit programs (rules EQ6xx; see "
                    "docs/DESIGN.md §18)")
    p.add_argument("--program", action="append", default=None,
                   choices=sorted(REGISTRY), dest="programs",
                   help="check one program (repeatable; default: all)")
    p.add_argument("--programs-tier1", action="store_true",
                   help=f"check only the tier-1 set {TIER1_PROGRAMS}")
    p.add_argument("--manifest-dir", default=None,
                   help="manifest directory (default <root>/"
                        f"{DEFAULT_MANIFEST_DIR})")
    p.add_argument("--update", action="store_true",
                   help="write manifests pinned to the current reports "
                        "(keeps existing suppressions) and exit 0")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--show-suppressed", action="store_true")
    p.add_argument("--list", action="store_true", dest="list_programs",
                   help="list registered programs")
    args = p.parse_args(argv)

    if args.list_programs:
        for spec in REGISTRY.values():
            tag = " [tier1]" if spec.tier1 else ""
            print(f"{spec.name:18s} {spec.description}{tag}")
        return 0

    if args.programs and args.programs_tier1:
        print("equivcheck: --program and --programs-tier1 are exclusive",
              file=sys.stderr)
        return 2
    names = (args.programs or
             (list(TIER1_PROGRAMS) if args.programs_tier1
              else sorted(REGISTRY)))
    manifest_dir = args.manifest_dir or default_manifest_dir()

    ensure_cpu_mesh_devices()

    if args.update:
        for path in update_manifests(names, manifest_dir):
            print(f"equivcheck: wrote {path}")
        return 0

    reports: list = []
    findings = check_programs(names, manifest_dir, reports_out=reports)
    live = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if args.format == "json":
        print(json.dumps({
            "summaries": {r.name: semantic_summary(r) for r in reports},
            "findings": [dataclasses.asdict(f) for f in findings],
            "unsuppressed": len(live),
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        shown = findings if args.show_suppressed else live
        for f in shown:
            print(f.render())
        print(f"equivcheck: {len(live)} finding(s), "
              f"{len(suppressed)} suppressed, "
              f"{len(names)} program(s)")
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
