"""Per-program memory budget manifests for memcheck.

A **manifest** pins one compiled program's memory footprint: peak HBM
(argument + output + temp + generated-code bytes, aliased bytes counted
once), temp bytes on their own (the scratch XLA materialises between
fusions — the number that moves when an optimisation boundary shifts),
the donation table (which requested donations must stay effective), and
the hoistable scan-invariant FLOPs per step (the recompute budget —
ROADMAP item 2a's pinned number).  Manifests are JSON files committed
under ``runs/memcheck/`` — one per registered program — so a PR that
doubles the sampler's temp bytes or silently un-aliases the
``record_imgs`` donation shows up as a *diff against a committed file*,
reviewable like any other regression.

Checking a :class:`~diff3d_tpu.analysis.mem.MemoryReport` against its
manifest yields graftlint-compatible :class:`Finding`s (rules MC4xx,
fingerprinted via ``fingerprint_data`` so they share the baseline
format).  Suppressions follow the same reason-mandatory discipline as
shardcheck manifests::

    "suppressions": [
      {"rule": "MC402", "key": "7",
       "reason": "optimizer mu buffer donation blocked by psum layout"}
    ]

``key`` scopes the suppression to one subject (an arg index, a byte
field); ``"*"`` covers the whole rule.  A suppression without a reason
is itself reported (MC002, mirroring GL002/SC002).

Rules:

  MC002  manifest suppression without a reason             (warning)
  MC401  peak-HBM bytes over budget                        (error)
  MC402  requested donation not aliased by XLA             (error)
  MC403  temp bytes over budget                            (error)
  MC404  hoistable scan-invariant FLOPs/step over budget   (error)
  MC405  program has no committed manifest                 (error)

Budgets are pinned exactly from the observed report (lowering and
compilation are deterministic for a fixed jax/XLA version, shapes and
mesh): any drift is a diff a human reviews and either accepts by
re-pinning with ``memcheck --update`` or fixes.  When the
conditioning-branch reuse of ROADMAP item 2a lands, tightening the
MC404 ceiling in ``runs/memcheck/step_many.json`` is the regression
gate that keeps it from creeping back.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence

from diff3d_tpu.analysis import manifests as manifests_lib
from diff3d_tpu.analysis.lint import (Finding, SEVERITY_ERROR,
                                      SEVERITY_WARNING)
from diff3d_tpu.analysis.manifests import Suppression, manifest_path  # noqa: F401 (re-exported API)
from diff3d_tpu.analysis.mem import MemoryReport

#: Default manifest directory, relative to the repo root.
DEFAULT_MANIFEST_DIR = os.path.join("runs", "memcheck")

MANIFEST_VERSION = 1
MANIFEST_TOOL = "memcheck"


@dataclasses.dataclass
class MemBudget:
    """The limits a manifest imposes.  Byte/FLOP fields are ceilings;
    ``effective_donations`` lists arg indices whose requested donation
    MUST alias (a requested donation outside the list still fires MC402
    — the list exists so ``--update`` records which aliases the pin was
    taken against, making the manifest diff reviewable)."""

    peak_bytes: int = 0
    temp_bytes: int = 0
    hoistable_flops_per_step: float = 0.0
    effective_donations: List[int] = dataclasses.field(
        default_factory=list)


@dataclasses.dataclass
class MemManifest:
    program: str
    budgets: MemBudget
    observed: dict = dataclasses.field(default_factory=dict)
    suppressions: List[Suppression] = dataclasses.field(
        default_factory=list)

    def to_json(self) -> dict:
        return {
            "version": MANIFEST_VERSION,
            "tool": MANIFEST_TOOL,
            "program": self.program,
            "budgets": dataclasses.asdict(self.budgets),
            "observed": self.observed,
            "suppressions": [dataclasses.asdict(s)
                             for s in self.suppressions],
        }


def load_manifest(path: str) -> MemManifest:
    data = manifests_lib.load_manifest_data(
        path, MANIFEST_TOOL, MANIFEST_VERSION, "memcheck manifest")
    b = data.get("budgets", {})
    budgets = MemBudget(
        peak_bytes=int(b.get("peak_bytes", 0)),
        temp_bytes=int(b.get("temp_bytes", 0)),
        hoistable_flops_per_step=float(
            b.get("hoistable_flops_per_step", 0.0)),
        effective_donations=[int(x)
                             for x in b.get("effective_donations", [])])
    supps = manifests_lib.parse_suppressions(data.get("suppressions", []))
    return MemManifest(program=str(data.get("program", "")),
                       budgets=budgets,
                       observed=data.get("observed", {}),
                       suppressions=supps)


def write_manifest(path: str, manifest: MemManifest) -> None:
    manifests_lib.write_manifest_data(path, manifest.to_json())


def manifest_from_report(report: MemoryReport,
                         suppressions: Optional[
                             Sequence[Suppression]] = None) -> MemManifest:
    """Pin a report as the budget: observed bytes/FLOPs become the
    ceilings, currently-effective donations become mandatory."""
    budgets = MemBudget(
        peak_bytes=report.peak_bytes,
        temp_bytes=report.temp_bytes,
        hoistable_flops_per_step=report.hoistable_flops_per_step,
        effective_donations=sorted(
            d.arg_index for d in report.donations
            if d.requested and d.effective))
    return MemManifest(program=report.name, budgets=budgets,
                       observed=report.to_json(),
                       suppressions=list(suppressions or []))


# -- checking ----------------------------------------------------------


def _finding(manifest_file: str, rule: str, program: str, key: str,
             message: str, severity: str = SEVERITY_ERROR) -> Finding:
    return Finding(
        path=manifest_file, rule=rule, line=1, col=0, severity=severity,
        message=f"[{program}] {message}",
        fingerprint_data=f"{program}\x00{rule}\x00{key}")


def check_report(report: MemoryReport, manifest: MemManifest,
                 manifest_file: str) -> List[Finding]:
    """Diff a memory report against its manifest.  Returns ALL findings
    (suppressed ones marked), same contract as ``lint_source``."""
    raw: List[Finding] = []
    b = manifest.budgets
    prog = report.name

    if report.available and report.peak_bytes > b.peak_bytes:
        raw.append(_finding(
            manifest_file, "MC401", prog, "peak_bytes",
            f"peak HBM estimate {report.peak_bytes} bytes exceeds budget "
            f"{b.peak_bytes} (+{report.peak_bytes - b.peak_bytes}) — the "
            f"router's admission control sizes replicas from this pin"))

    for d in report.donations:
        if d.requested and not d.effective:
            stage = ("jax could not pair the donated buffer with an "
                     "output at lowering time"
                     if not d.lowered else
                     "XLA declined the alias at compile time")
            raw.append(_finding(
                manifest_file, "MC402", prog, str(d.arg_index),
                f"donation of arg {d.arg_index} "
                f"({d.type or 'unknown type'}, {d.bytes} bytes) was "
                f"requested but never aliased — {stage}; the buffer is "
                f"silently copied and lives twice"))

    if report.available and report.temp_bytes > b.temp_bytes:
        raw.append(_finding(
            manifest_file, "MC403", prog, "temp_bytes",
            f"temp bytes {report.temp_bytes} exceed budget "
            f"{b.temp_bytes} (+{report.temp_bytes - b.temp_bytes}) — "
            f"scratch between fusions grew; check for a lost fusion or "
            f"a materialised broadcast"))

    hoist = report.hoistable_flops_per_step
    if hoist > b.hoistable_flops_per_step:
        raw.append(_finding(
            manifest_file, "MC404", prog, "hoistable_flops_per_step",
            f"scan-invariant compute {hoist:.6g} FLOPs/step exceeds "
            f"budget {b.hoistable_flops_per_step:.6g} — loop-invariant "
            f"ops were added to (or stopped being hoisted out of) a "
            f"scan body; each one re-runs every denoise step"))

    return _apply_suppressions(raw, manifest, manifest_file, prog)


def _apply_suppressions(raw: Sequence[Finding], manifest: MemManifest,
                        manifest_file: str, prog: str) -> List[Finding]:
    # Reason-mandatory, like graftlint/shardcheck suppressions.
    return manifests_lib.apply_suppressions(
        raw, manifest.suppressions,
        lambda s: _finding(
            manifest_file, "MC002", prog, f"{s.rule}:{s.key}",
            f"manifest suppression of {s.rule} (key={s.key!r}) has "
            f"no reason — every suppression documents why it is "
            f"safe", severity=SEVERITY_WARNING))


def missing_manifest_finding(program: str,
                             manifest_dir: str) -> Finding:
    path = manifest_path(program, manifest_dir)
    return _finding(
        path, "MC405", program, "missing",
        f"no committed manifest at {path} — run "
        f"'memcheck --update --program {program}' and commit the "
        f"result")


def check_report_against_dir(report: MemoryReport,
                             manifest_dir: str) -> List[Finding]:
    """Load ``<dir>/<program>.json`` and check; a missing or unreadable
    manifest is itself a finding (MC405)."""
    path = manifest_path(report.name, manifest_dir)
    if not os.path.exists(path):
        return [missing_manifest_finding(report.name, manifest_dir)]
    try:
        manifest = load_manifest(path)
    except (ValueError, json.JSONDecodeError) as e:
        return [_finding(path, "MC405", report.name, "unreadable",
                         f"manifest unreadable: {e}")]
    return check_report(report, manifest, path)
