"""rngcheck: interprocedural RNG-lineage & precision-flow analyzer.

The fifth analysis pillar.  Every load-bearing correctness contract in
this repo is a *determinism* contract — the ancestral-256 bit-parity
oracle, the chunked carried-RNG schedule independence, the elastic
bit-identical consumed-batch stream, stochastic conditioning itself —
and all of them sit on disciplined key derivation.  graftlint GL101
catches literal same-function key reuse; this tool extends the same
linear-resource model (``analysis/rngflow.py``) across the call graph,
adds seed-hygiene and precision-flow rules, and pins each production
program's ordered key-derivation stream as a committed manifest under
``runs/rngcheck/`` — so a change that perturbs any RNG stream fails
tier-1 by manifest diff, not by a 900-second parity test.

Static rules (suppress inline with
``# rngcheck: disable=<rule>(reason)``):

  RC001  file does not parse                                  (error)
  RC002  suppression without a reason                       (warning)
  RC003  malformed ``# rng-lineage:`` annotation              (error)
  RC501  key double-consumption across call sites             (error)
  RC502  key reused after being split, across call sites      (error)
  RC503  derived key never consumed (dead stream branch)    (warning)
  RC504  host-level random / np.random inside a traced body   (error)
  RC505  PRNGKey built from non-static traced data            (error)
  RC506  seed derived from host time / pid / urandom          (error)
  RC507  fold_in with loop-invariant key AND index in a loop  (error)
  RC508  sharded-vs-replicated exact-equality comparison with
         no threefry_partitionable guard                      (error)
  RC509  f32→bf16 downcast on a loss/accumulation path        (error)

Stream-manifest rules (suppress in the manifest's
``suppressions`` list, key-scoped, reason mandatory):

  RC510  observed stream digest differs from the manifest     (error)
  RC511  program has no committed stream manifest             (error)
  RC512  runtime witness recorded a key consumed twice        (error)

GL101 and RC501/RC502 share one scanner (:func:`rngflow.
linear_violations`) and partition cleanly: GL101 owns violations whose
both sides are local ``jax.random`` events; rngcheck owns the ones
involving a resolved call edge.  They cannot disagree.

CLI (also the ``rngcheck`` console script)::

    rngcheck                       # static pass + all stream manifests
    rngcheck --ast-only            # static rules only (no jax import)
    rngcheck --streams-tier1       # static + tier-1 streams (the gate)
    rngcheck --update              # re-pin stream manifests
    rngcheck --list-streams        # registry contents

Exit codes match graftlint: 0 clean, 1 unsuppressed findings, 2 bad
invocation.  ``tools/lint.py`` runs this as the fifth gate.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import re
import sys
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Set

from diff3d_tpu.analysis import manifests as manifests_lib
from diff3d_tpu.analysis import rngflow
from diff3d_tpu.analysis.lint import (DEFAULT_TARGETS, Finding,
                                      SEVERITY_ERROR, SEVERITY_WARNING,
                                      _find_root, apply_baseline,
                                      iter_python_files, lint_source,
                                      load_baseline, write_baseline)
from diff3d_tpu.analysis.rules.base import Rule
from diff3d_tpu.analysis.rules.context import (ModuleContext, dotted_name,
                                               param_names)

TOOL = "rngcheck"
PARSE_RULE = "RC001"
REASONLESS_RULE = "RC002"
DEFAULT_BASELINE = ".rngcheck-baseline.json"

#: Default stream-manifest directory, relative to the repo root.
DEFAULT_MANIFEST_DIR = os.path.join("runs", "rngcheck")
MANIFEST_VERSION = 1


# ---------------------------------------------------------------------
# static rules
# ---------------------------------------------------------------------


class RcAnnotationRule(Rule):
    id = "RC003"
    name = "rng-lineage-annotation"
    severity = SEVERITY_ERROR
    description = ("a # rng-lineage: annotation does not parse "
                   "(unknown directive or bad argument list)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            ann = rngflow.parse_lineage_annotations(ctx, node)
            for lineno, msg in ann.errors:
                yield Finding(path=ctx.path, rule=self.id, line=lineno,
                              col=0, severity=self.severity,
                              message=msg)


class RcLinearRule(Rule):
    """RC501/RC502: the interprocedural half of the linear-key scan.

    GL101 owns violations where both consumptions are local
    ``jax.random`` events; this rule emits only when a resolved call
    edge is involved — the cross-function cases a single-scope pass
    cannot see.  One shared scanner, disjoint jurisdictions."""

    id = "RC501"
    name = "rng-key-cross-call-reuse"
    severity = SEVERITY_ERROR
    description = ("a PRNG key is consumed twice, at least once by "
                   "passing it to a function that draws from it")

    def __init__(self, graph: Optional[rngflow.ProgramGraph] = None):
        self.graph = graph

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if self.graph is None:
            return
        for v in rngflow.linear_violations(ctx, self.graph):
            if v.kind != "call" and v.prev_kind != "call":
                continue  # GL101's jurisdiction
            rule = "RC502" if v.prev_kind == "split" else "RC501"
            prev = {"split": "split", "draw": "drawn from",
                    "call": "consumed by a callee"}[v.prev_kind]
            if v.kind == "call":
                how = (f"passing it to '{v.detail}()' (which draws "
                       f"from its key parameter) consumes it again")
            else:
                how = "this draw consumes it again"
            yield Finding(
                path=ctx.path, rule=rule, line=v.node.lineno,
                col=v.node.col_offset + 1, severity=self.severity,
                message=(f"PRNG key '{v.name}' was already "
                         f"{prev} on line {v.prev_line} — {how}; "
                         "split it (or reassign the carry) first"))


class RcDeadKeyRule(Rule):
    id = "RC503"
    name = "rng-dead-derived-key"
    severity = SEVERITY_WARNING
    description = ("a key derived via split/fold_in/PRNGKey is never "
                   "used — a dead stream branch (or a stream-schema "
                   "drift waiting to happen)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node, name in rngflow.dead_derived_keys(ctx):
            yield self.finding(
                ctx, node,
                f"derived key '{name}' is never consumed — prefix "
                f"with _ if the discard is intentional (it still "
                f"shapes the split schema), else delete the branch")


class RcHostRandomRule(Rule):
    id = "RC504"
    name = "host-rng-in-traced-body"
    severity = SEVERITY_ERROR
    description = ("Python random / np.random called inside a traced "
                   "body — it runs once at trace time, baking one "
                   "sample into the compiled program")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        random_roots: Set[str] = set()
        random_names: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "random":
                        random_roots.add(a.asname or "random")
                    elif a.name in ("numpy", "numpy.random"):
                        pass  # covered by the np-root check below
            elif isinstance(node, ast.ImportFrom):
                if node.module in ("random", "numpy.random"):
                    for a in node.names:
                        random_names.add(a.asname or a.name)
        # `from jax import random` shadows the stdlib name.
        random_roots -= ctx.random_aliases
        if not ctx.traced_functions:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = ctx.enclosing_function(node)
            if fn is None or id(fn) not in ctx.traced_functions:
                continue
            dotted = dotted_name(node.func)
            if dotted is None:
                continue
            root = dotted.split(".")[0]
            hit = (root in random_roots
                   or dotted in random_names
                   or (root in ("np", "numpy")
                       and dotted.split(".")[1:2] == ["random"]))
            if hit:
                yield self.finding(
                    ctx, node,
                    f"'{dotted}' inside a traced body runs ONCE at "
                    "trace time — the compiled program replays that "
                    "single sample forever; thread a jax.random key "
                    "instead")


class RcTracedSeedRule(Rule):
    id = "RC505"
    name = "key-from-traced-data"
    severity = SEVERITY_ERROR
    description = ("PRNGKey/key constructed from a non-static traced "
                   "value — the stream becomes data-dependent")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        seen: Set[int] = set()
        for fn in ctx.traced_nodes():
            dyn = set(param_names(fn)) - ctx.static_params_of(fn)
            if not dyn:
                continue
            for node in ast.walk(fn):
                if (not isinstance(node, ast.Call)
                        or id(node) in seen
                        or not isinstance(node.func, ast.Attribute)):
                    continue
                if (dotted_name(node.func.value)
                        not in ctx.random_aliases
                        or node.func.attr not in ("PRNGKey", "key")):
                    continue
                if ctx.enclosing_function(node) is not fn:
                    continue
                names = {n.id for a in node.args
                         for n in ast.walk(a)
                         if isinstance(n, ast.Name)
                         and isinstance(n.ctx, ast.Load)}
                bad = sorted(names & dyn)
                if bad:
                    seen.add(id(node))
                    yield self.finding(
                        ctx, node,
                        f"PRNGKey built from traced value(s) "
                        f"{', '.join(bad)} — the seed is data-"
                        "dependent; derive via fold_in on a threaded "
                        "key instead")


#: Host entropy sources that make a seed unreproducible.
_TIME_SOURCES = ("time.time", "time.time_ns", "time.monotonic",
                 "time.monotonic_ns", "time.perf_counter",
                 "datetime.now", "datetime.utcnow", "os.urandom",
                 "os.getpid", "uuid.uuid4", "uuid.uuid1")

_NP_SEED_SUFFIXES = (".random.seed", ".random.default_rng",
                     ".random.RandomState")


class RcHostTimeSeedRule(Rule):
    id = "RC506"
    name = "host-time-seed"
    severity = SEVERITY_ERROR
    description = ("a PRNG seed derived from wall clock / pid / "
                   "urandom — the run is unreproducible by "
                   "construction")

    def _is_seed_ctor(self, ctx: ModuleContext, node: ast.Call) -> bool:
        if isinstance(node.func, ast.Attribute):
            if (dotted_name(node.func.value) in ctx.random_aliases
                    and node.func.attr in ("PRNGKey", "key")):
                return True
        dotted = dotted_name(node.func)
        if dotted is None:
            return False
        if any(dotted.endswith(s) for s in _NP_SEED_SUFFIXES):
            return True
        return dotted.split(".")[-1] == "SeedSequence"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and self._is_seed_ctor(ctx, node)):
                continue
            for arg in list(node.args) + [kw.value
                                          for kw in node.keywords]:
                for inner in ast.walk(arg):
                    if not isinstance(inner, ast.Call):
                        continue
                    d = dotted_name(inner.func)
                    if d and any(d == s or d.endswith("." + s)
                                 for s in _TIME_SOURCES):
                        yield self.finding(
                            ctx, node,
                            f"seed derived from '{d}()' — every run "
                            "gets a different stream; take the seed "
                            "from config and log it instead")
                        break


class RcFoldInLoopRule(Rule):
    id = "RC507"
    name = "fold-in-loop-invariant"
    severity = SEVERITY_ERROR
    description = ("fold_in inside a Python loop with BOTH key and "
                   "index loop-invariant — every iteration derives "
                   "the same key")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        flagged: Set[int] = set()
        for loop in ast.walk(ctx.tree):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            stored = {n.id for n in ast.walk(loop)
                      if isinstance(n, ast.Name)
                      and isinstance(n.ctx, (ast.Store, ast.Del))}
            for node in ast.walk(loop):
                if (not isinstance(node, ast.Call)
                        or id(node) in flagged
                        or not isinstance(node.func, ast.Attribute)
                        or node.func.attr != "fold_in"
                        or dotted_name(node.func.value)
                        not in ctx.random_aliases
                        or len(node.args) < 2):
                    continue
                key_a, data_a = node.args[0], node.args[1]
                # A Call in either slot derives fresh state per
                # iteration as far as this syntactic pass can tell.
                if any(isinstance(n, ast.Call)
                       for a in (key_a, data_a) for n in ast.walk(a)):
                    continue
                names = {n.id for a in (key_a, data_a)
                         for n in ast.walk(a)
                         if isinstance(n, ast.Name)
                         and isinstance(n.ctx, ast.Load)}
                if names & stored:
                    continue
                flagged.add(id(node))
                yield self.finding(
                    ctx, node,
                    "fold_in with loop-invariant key AND index — "
                    "every iteration of this loop derives the same "
                    "key; fold in the loop counter")


_EXACT_EQ_TAILS = ("assert_array_equal", "array_equal",
                   "assert_trees_all_equal")
_GUARD_TOKENS = ("threefry_partitionable", "partitionable_rng",
                 "jax_threefry_partitionable")


class RcThreefryGuardRule(Rule):
    id = "RC508"
    name = "unguarded-sharded-parity"
    severity = SEVERITY_ERROR
    description = ("sharded-vs-replicated exact-equality comparison "
                   "with no threefry_partitionable guard — legacy "
                   "threefry produces different bits under "
                   "partitioning (the PR 8 tier-1 failures)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        uses_random = any(
            isinstance(n, ast.Attribute)
            and dotted_name(n.value) in ctx.random_aliases
            for n in ast.walk(ctx.tree))
        if not uses_random:
            return
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            text = ast.get_source_segment(ctx.source, fn) or ""
            if any(tok in text for tok in _GUARD_TOKENS):
                continue
            if fn.args and any(a.arg in _GUARD_TOKENS
                               for a in fn.args.args):
                continue
            exact_eq = False
            callee_modes: Dict[str, Set[str]] = {}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted_name(node.func)
                if d and any(d.endswith(t) for t in _EXACT_EQ_TAILS):
                    exact_eq = True
                name = d or (node.func.attr if isinstance(
                    node.func, ast.Attribute) else None)
                if name is None:
                    continue
                mode = "nomesh"
                for kw in node.keywords:
                    if kw.arg == "mesh":
                        mode = ("nomesh" if isinstance(kw.value,
                                                       ast.Constant)
                                and kw.value.value is None else "mesh")
                callee_modes.setdefault(name, set()).add(mode)
            both = sorted(n for n, modes in callee_modes.items()
                          if {"mesh", "nomesh"} <= modes)
            if exact_eq and both:
                yield self.finding(
                    ctx, fn,
                    f"'{fn.name}' compares {both[0]}(mesh=...) against "
                    "an unsharded run with exact equality and no "
                    "threefry_partitionable guard — wrap the test in "
                    "`with jax.threefry_partitionable(True):` (or the "
                    "partitionable_rng fixture)")


_ACC_NAME_RE = re.compile(
    r"(loss|grad|acc|accum|sum|mean|total|metric|avg|norm|err)",
    re.IGNORECASE)
_REDUCTIONS = ("mean", "sum", "prod", "average", "var", "std")


def _is_bf16(node: ast.AST) -> bool:
    d = dotted_name(node)
    if d is not None and d.split(".")[-1] == "bfloat16":
        return True
    return (isinstance(node, ast.Constant)
            and node.value == "bfloat16")


class RcPrecisionFlowRule(Rule):
    id = "RC509"
    name = "bf16-on-accumulation-path"
    severity = SEVERITY_ERROR
    description = ("f32→bf16 downcast on a loss/accumulation/"
                   "reduction path inside a traced body — bf16 "
                   "accumulation loses ~8 bits of mantissa per "
                   "reduce")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        seen: Set[int] = set()
        for fn in ctx.traced_nodes():
            for node in ast.walk(fn):
                if (not isinstance(node, ast.Call)
                        or id(node) in seen):
                    continue
                seen.add(id(node))
                # pattern A: <acc>.astype(bfloat16) / casting into an
                # accumulator-named target.
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "astype"
                        and node.args and _is_bf16(node.args[0])):
                    recv = dotted_name(node.func.value) or ""
                    target = ""
                    parent = ctx.parent.get(id(node))
                    if isinstance(parent, ast.Assign):
                        target = " ".join(
                            t.id for t in parent.targets
                            if isinstance(t, ast.Name))
                    subject = " ".join(dict.fromkeys(
                        s for s in (recv, target) if s))
                    if _ACC_NAME_RE.search(subject):
                        yield self.finding(
                            ctx, node,
                            f"'{subject or 'value'}' downcast to "
                            "bfloat16 on an accumulation path — keep "
                            "the reduce in f32 and cast afterwards")
                    continue
                # pattern B: a reduction told to accumulate in bf16.
                d = dotted_name(node.func)
                if d and d.split(".")[-1] in _REDUCTIONS:
                    for kw in node.keywords:
                        if kw.arg == "dtype" and _is_bf16(kw.value):
                            yield self.finding(
                                ctx, node,
                                f"'{d}(dtype=bfloat16)' accumulates "
                                "the reduction in bf16 — reduce in "
                                "f32, cast the result")


def make_rc_rules(
        graph: Optional[rngflow.ProgramGraph] = None) -> tuple:
    """The full RC rule pack (graph-bound linear rule included)."""
    return (RcAnnotationRule(), RcLinearRule(graph), RcDeadKeyRule(),
            RcHostRandomRule(), RcTracedSeedRule(),
            RcHostTimeSeedRule(), RcFoldInLoopRule(),
            RcThreefryGuardRule(), RcPrecisionFlowRule())


#: Ids listed by --list-rules (RC510+ are manifest-side, not AST).
_RULE_DOCS = (
    ("RC001", "file does not parse"),
    ("RC002", "suppression without a reason"),
    ("RC003", "malformed # rng-lineage: annotation"),
    ("RC510", "stream digest differs from the committed manifest"),
    ("RC511", "program has no committed stream manifest"),
    ("RC512", "runtime witness recorded a key consumed twice"),
)


# ---------------------------------------------------------------------
# static pass
# ---------------------------------------------------------------------


def _read_sources(targets: Sequence[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for path in iter_python_files(targets):
        try:
            with open(path, encoding="utf-8") as f:
                out[path] = f.read()
        except OSError:
            out[path] = ""
    return out


def rngcheck_paths(targets: Sequence[str],
                   tests: Optional[Sequence[str]] = None
                   ) -> List[Finding]:
    """Static pass: full RC rule pack over ``targets`` (one program
    graph spanning all of them), plus the RC508 guard rule over
    ``tests`` (test files get only the rules that are *about* tests —
    running the linear pack there would police fixture code that
    intentionally abuses keys)."""
    sources = _read_sources(targets)
    graph = rngflow.build_program_graph(sources)
    rules = make_rc_rules(graph)
    findings: List[Finding] = []
    for path in sorted(sources):
        findings.extend(lint_source(
            path, sources[path], rules, tool=TOOL,
            parse_rule=PARSE_RULE, reasonless_rule=REASONLESS_RULE))
    if tests:
        test_rules = (RcThreefryGuardRule(),)
        for path, source in sorted(_read_sources(tests).items()):
            findings.extend(lint_source(
                path, source, test_rules, tool=TOOL,
                parse_rule=PARSE_RULE,
                reasonless_rule=REASONLESS_RULE))
    return findings


# ---------------------------------------------------------------------
# stream registry + manifests
# ---------------------------------------------------------------------


# The shared manifest contract (envelope validation, key-scoped
# reason-mandatory suppressions, suppression-preserving --update) lives
# in analysis/manifests.py; the dataclass is re-exported so callers
# keep constructing ``rngcheck.Suppression``.
Suppression = manifests_lib.Suppression


@dataclasses.dataclass(frozen=True)
class StreamSpec:
    """One registered RNG stream: a builder that traces (or runs) the
    real program under the witness and returns the ordered events."""

    name: str
    description: str
    build: Callable[[], List[str]]
    tier1: bool = False


def _witnessed_lower(lower: Callable[[], object]) -> List[str]:
    """Install the witness, trace, uninstall, return the events.  A
    key consumed twice during the trace raises — a linearity bug in a
    *production* program must never be pinned into a manifest."""
    w, uninstall = rngflow.install_rng_witness()
    try:
        lower()
    finally:
        uninstall()
    w.check()
    return list(w.events)


def build_train_step_events() -> List[str]:
    import jax
    import jax.numpy as jnp

    from diff3d_tpu.analysis import shardcheck
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train import make_train_step

    cfg = shardcheck._train_cfg()
    env = shardcheck._fsdp_mesh()
    model = XUNet(cfg.model)
    state = shardcheck._abstract_state(model, cfg)
    batch = shardcheck._abstract_batch(cfg)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    step = make_train_step(model, cfg, env, donate=False)
    return _witnessed_lower(lambda: step.lower(state, batch, rng))


def build_distill_step_events() -> List[str]:
    import jax
    import jax.numpy as jnp

    from diff3d_tpu.analysis import shardcheck
    from diff3d_tpu.models import XUNet
    from diff3d_tpu.train.distill import make_distill_step

    cfg = shardcheck._train_cfg()
    env = shardcheck._fsdp_mesh()
    model = XUNet(cfg.model)
    state = shardcheck._abstract_state(model, cfg)
    batch = shardcheck._abstract_batch(cfg)
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32)
    k = jax.ShapeDtypeStruct((), jnp.int32)
    step = make_distill_step(model, cfg, env, donate=False)
    return _witnessed_lower(
        lambda: step.lower(state, state.params, batch, rng, k))


def build_step_many_events() -> List[str]:
    from diff3d_tpu.analysis import shardcheck

    sampler, _env = shardcheck._sampler()
    return _witnessed_lower(
        lambda: sampler.lower_step_many(lanes=shardcheck.MESH_DEVICES,
                                        capacity=4))


def build_step_many_pallas_events() -> List[str]:
    from diff3d_tpu.analysis import shardcheck

    sampler, _env = shardcheck._sampler(kernels="pallas")
    return _witnessed_lower(
        lambda: sampler.lower_step_many(lanes=shardcheck.MESH_DEVICES,
                                        capacity=4))


def build_step_many_ddim_events() -> List[str]:
    from diff3d_tpu.analysis import shardcheck

    sampler, _env = shardcheck._sampler(sampler_kind="ddim", steps=2)
    return _witnessed_lower(
        lambda: sampler.lower_step_many(lanes=shardcheck.MESH_DEVICES,
                                        capacity=4))


def build_step_many_cascade_draft_events() -> List[str]:
    from diff3d_tpu.analysis import shardcheck

    cascade, _env = shardcheck._cascade()
    return _witnessed_lower(
        lambda: cascade.draft.lower_step_many(
            lanes=shardcheck.MESH_DEVICES, capacity=4))


def build_step_many_cascade_refine_events() -> List[str]:
    from diff3d_tpu.analysis import shardcheck

    cascade, _env = shardcheck._cascade()
    return _witnessed_lower(
        lambda: cascade.refine.lower_step_many(
            lanes=shardcheck.MESH_DEVICES, capacity=4))


def build_loader_events() -> List[str]:
    return rngflow.loader_stream_events()


STREAM_REGISTRY: Dict[str, StreamSpec] = {
    spec.name: spec for spec in (
        StreamSpec(
            "train_step",
            "key-derivation stream of the mesh-sharded train step "
            "(fold_in(step) -> dropout/p_losses splits)",
            build_train_step_events, tier1=True),
        StreamSpec(
            "step_many",
            "sampler step_many ancestral stream (per-view split "
            "schedule through the scan)",
            build_step_many_events, tier1=True),
        StreamSpec(
            "loader",
            "InfiniteLoader SeedSequence spawn tree: global batch as "
            "a pure function of (seed, step, slot), both sample modes",
            build_loader_events, tier1=True),
        StreamSpec(
            "step_many_pallas",
            "sampler step_many with fused GroupNorm Pallas kernels — "
            "the kernels consume no keys, so this stream must be "
            "byte-identical to step_many's",
            build_step_many_pallas_events),
        StreamSpec(
            "distill_step",
            "progressive-distillation step: teacher/student stream "
            "split off one folded key",
            build_distill_step_events),
        StreamSpec(
            "step_many_ddim",
            "sampler step_many deterministic-DDIM stream (noise keys "
            "derived but unconsumed by design)",
            build_step_many_ddim_events),
        StreamSpec(
            "step_many_cascade_draft",
            "cascade draft phase stream: the few-step student at the "
            "draft resolution (its own split of the parent key)",
            build_step_many_cascade_draft_events, tier1=True),
        StreamSpec(
            "step_many_cascade_refine",
            "cascade refine phase stream: start_t-truncated scan — the "
            "init-noise key is always drawn (renoising the draft), so "
            "the stream matches the untruncated sampler's exactly",
            build_step_many_cascade_refine_events, tier1=True),
    )
}

TIER1_STREAMS = tuple(s.name for s in STREAM_REGISTRY.values()
                      if s.tier1)

#: In-process events cache, keyed by (name, builder) so a test that
#: monkeypatches a STREAM_REGISTRY entry's ``build`` never sees a
#: stale cached stream (same convention as shardcheck's report cache).
_EVENTS_CACHE: Dict[tuple, List[str]] = {}


def build_events(name: str) -> List[str]:
    spec = STREAM_REGISTRY[name]
    key = (name, spec.build)
    events = _EVENTS_CACHE.get(key)
    if events is None:
        events = _EVENTS_CACHE[key] = spec.build()
    return list(events)


def manifest_path(program: str, manifest_dir: str) -> str:
    return os.path.join(manifest_dir, f"{program}.json")


def stream_manifest(program: str, events: Sequence[str],
                    suppressions: Sequence[Suppression] = ()) -> dict:
    digest = rngflow.stream_digest(events)
    return {
        "version": MANIFEST_VERSION,
        "tool": TOOL,
        "program": program,
        "budgets": {"digest": digest, "n_events": len(events)},
        "observed": {"digest": digest, "events": list(events)},
        "suppressions": [dataclasses.asdict(s) for s in suppressions],
    }


def load_stream_manifest(path: str) -> dict:
    return manifests_lib.load_manifest_data(
        path, TOOL, MANIFEST_VERSION, "rngcheck stream manifest")


def write_stream_manifest(path: str, manifest: dict) -> None:
    manifests_lib.write_manifest_data(path, manifest)


def _manifest_suppressions(data: dict) -> List[Suppression]:
    return manifests_lib.parse_suppressions(data.get("suppressions", []))


def _stream_finding(program: str, rule: str, key: str,
                    message: str, path: str = "",
                    severity: str = SEVERITY_ERROR) -> Finding:
    return Finding(
        path=path or f"<{TOOL}:{program}>", rule=rule, line=0, col=0,
        severity=severity, message=message,
        fingerprint_data=f"{program}\x00{rule}\x00{key}")


def _apply_stream_suppressions(
        findings: List[Finding], supps: Sequence[Suppression],
        program: str, path: str) -> List[Finding]:
    return manifests_lib.apply_suppressions(
        findings, supps,
        lambda s: _stream_finding(
            program, REASONLESS_RULE, f"{s.rule}:{s.key}",
            f"manifest suppression of {s.rule} (key "
            f"'{s.key}') has no reason — suppressions are "
            "reviewed policy, write why it is safe",
            path=path, severity=SEVERITY_WARNING))


def _first_divergence(committed: Sequence[str],
                      observed: Sequence[str]) -> str:
    for i, (a, b) in enumerate(zip(committed, observed)):
        if a != b:
            return (f"first divergence at event {i}: committed "
                    f"{a!r}, observed {b!r}")
    n, m = len(committed), len(observed)
    if n == m:
        return "event lists equal but digests differ (corrupt manifest?)"
    short, longer = (committed, observed) if n < m else (observed,
                                                         committed)
    extra = longer[len(short)]
    side = "observed" if m > n else "committed"
    return (f"streams agree for {len(short)} event(s), then the "
            f"{side} side continues with {extra!r}")


def check_streams(names: Sequence[str],
                  manifest_dir: str) -> List[Finding]:
    """Build each named stream and diff it against the committed
    manifest.  Returns ALL findings (suppressed marked)."""
    findings: List[Finding] = []
    for nm in names:
        path = manifest_path(nm, manifest_dir)
        try:
            events = build_events(nm)
            witness_violations: List[str] = []
        except rngflow.RngWitnessViolation as e:
            events = None
            witness_violations = [str(e)]
        per: List[Finding] = []
        supps: List[Suppression] = []
        for v in witness_violations:
            per.append(_stream_finding(
                nm, "RC512", "witness",
                f"program '{nm}': {v}", path=path))
        if not os.path.exists(path):
            per.append(_stream_finding(
                nm, "RC511", "manifest",
                f"program '{nm}' has no committed stream manifest — "
                f"run `rngcheck --update --program {nm}` and commit "
                f"{path}", path=path))
            findings.extend(per)
            continue
        try:
            data = load_stream_manifest(path)
            supps = _manifest_suppressions(data)
        except (ValueError, json.JSONDecodeError) as e:
            per.append(_stream_finding(
                nm, "RC511", "manifest",
                f"unreadable stream manifest: {e}", path=path))
            findings.extend(
                _apply_stream_suppressions(per, supps, nm, path))
            continue
        if events is not None:
            committed = data.get("budgets", {}).get("digest")
            committed_events = data.get("observed", {}).get(
                "events", [])
            observed = rngflow.stream_digest(events)
            if observed != committed:
                per.append(_stream_finding(
                    nm, "RC510", "stream",
                    f"program '{nm}' RNG stream drifted: committed "
                    f"digest {str(committed)[:12]}…, observed "
                    f"{observed[:12]}… over {len(events)} event(s) "
                    f"({_first_divergence(committed_events, events)})"
                    f" — if intentional, re-pin with `rngcheck "
                    f"--update --program {nm}`", path=path))
        findings.extend(
            _apply_stream_suppressions(per, supps, nm, path))
    return findings


def update_stream_manifests(names: Sequence[str],
                            manifest_dir: str) -> List[str]:
    """Re-pin each named stream manifest, PRESERVING committed
    suppressions (they are reviewed policy, not observations)."""
    written = []
    for nm in names:
        path = manifest_path(nm, manifest_dir)
        supps = manifests_lib.carry_suppressions(
            path, load_stream_manifest)
        write_stream_manifest(
            path, stream_manifest(nm, build_events(nm), supps))
        written.append(path)
    return written


def default_manifest_dir(root: Optional[str] = None) -> str:
    if root is None:
        root = _find_root(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
    return os.path.join(root, DEFAULT_MANIFEST_DIR)


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="rngcheck",
        description="interprocedural RNG-lineage & precision-flow "
                    "analyzer (rules RC5xx + stream manifests; see "
                    "docs/DESIGN.md §17)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs for the static pass (default: "
                        "diff3d_tpu, tools, bench.py under the repo "
                        "root, plus tests/ for the RC508 guard rule)")
    p.add_argument("--ast-only", action="store_true",
                   help="static rules only (no stream builds, no jax)")
    p.add_argument("--streams-only", action="store_true",
                   help="stream-manifest checks only")
    p.add_argument("--program", action="append", default=None,
                   choices=sorted(STREAM_REGISTRY), dest="programs",
                   help="check one stream (repeatable; default: all)")
    p.add_argument("--streams-tier1", action="store_true",
                   help=f"limit streams to the tier-1 set "
                        f"{TIER1_STREAMS}")
    p.add_argument("--manifest-dir", default=None,
                   help="stream-manifest directory (default <root>/"
                        f"{DEFAULT_MANIFEST_DIR})")
    p.add_argument("--update", action="store_true",
                   help="re-pin stream manifests from the observed "
                        "streams (keeps suppressions) and exit 0")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON (default <root>/"
                        f"{DEFAULT_BASELINE} when present)")
    p.add_argument("--update-baseline", action="store_true")
    p.add_argument("--format", choices=("text", "json"),
                   default="text")
    p.add_argument("--show-suppressed", action="store_true")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--list-streams", action="store_true")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in make_rc_rules():
            print(f"{rule.id}  {rule.name:28s} [{rule.severity}] "
                  f"{rule.description}")
        for rid, desc in _RULE_DOCS:
            print(f"{rid}  {'(engine/manifest)':28s} [-] {desc}")
        return 0
    if args.list_streams:
        for spec in STREAM_REGISTRY.values():
            tag = " [tier1]" if spec.tier1 else ""
            print(f"{spec.name:16s} {spec.description}{tag}")
        return 0
    if args.ast_only and (args.streams_only or args.update):
        print("rngcheck: --ast-only excludes --streams-only/--update",
              file=sys.stderr)
        return 2
    if args.programs and args.streams_tier1:
        print("rngcheck: --program and --streams-tier1 are exclusive",
              file=sys.stderr)
        return 2

    root = _find_root(os.getcwd())
    manifest_dir = args.manifest_dir or default_manifest_dir(root)
    stream_names = (args.programs
                    or (list(TIER1_STREAMS) if args.streams_tier1
                        else sorted(STREAM_REGISTRY)))

    findings: List[Finding] = []
    if not args.streams_only and not args.update:
        if args.paths:
            targets, tests = list(args.paths), []
        else:
            targets = [os.path.join(root, t) for t in DEFAULT_TARGETS]
            targets = [t for t in targets if os.path.exists(t)]
            tests_dir = os.path.join(root, "tests")
            tests = [tests_dir] if os.path.isdir(tests_dir) else []
            if not targets:
                print(f"rngcheck: no default targets under {root}",
                      file=sys.stderr)
                return 2
        findings.extend(rngcheck_paths(targets, tests))

    if not args.ast_only:
        # Stream builds trace real programs over the 8-device CPU mesh.
        from diff3d_tpu.analysis.shardcheck import ensure_cpu_mesh_devices

        if any(nm != "loader" for nm in stream_names):
            ensure_cpu_mesh_devices()
        if args.update:
            for path in update_stream_manifests(stream_names,
                                                manifest_dir):
                print(f"rngcheck: wrote {path}")
            return 0
        findings.extend(check_streams(stream_names, manifest_dir))

    baseline_path = args.baseline or os.path.join(root,
                                                  DEFAULT_BASELINE)
    if args.update_baseline:
        n = write_baseline(baseline_path, findings, root, tool=TOOL)
        print(f"rngcheck: baseline written to {baseline_path} "
              f"({n} entries)")
        return 0
    try:
        baseline = load_baseline(baseline_path)
    except (ValueError, json.JSONDecodeError) as e:
        print(f"rngcheck: {e}", file=sys.stderr)
        return 2
    findings = apply_baseline(findings, baseline, root)

    live = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    if args.format == "json":
        print(json.dumps({
            "findings": [dataclasses.asdict(f) for f in findings],
            "unsuppressed": len(live),
            "suppressed": len(suppressed),
        }, indent=1))
    else:
        shown = findings if args.show_suppressed else live
        for f in shown:
            print(f.render())
        print(f"rngcheck: {len(live)} finding(s), "
              f"{len(suppressed)} suppressed")
    return 1 if live else 0


if __name__ == "__main__":
    sys.exit(main())
