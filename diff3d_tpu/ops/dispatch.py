"""Shared kernel-dispatch registry for the hot-path ops.

One registry answers "which implementation of op X runs here?" for every
backend-dispatched op in the model — attention's sdpa core and the fused
GroupNorm epilogues (:mod:`diff3d_tpu.ops.pallas_film`).  Before this
module each op hand-rolled its own resolution (``attention._resolve_auto``);
the rules are now stated once:

  * ``'xla'``    — the plain XLA composition, always available.  The
    default everywhere: CPU-mesh tests, the analysis pillars' lowering
    passes and converted-checkpoint parity all run it.
  * ``'pallas'`` — the hand-tiled TPU kernel, IF the registered
    ``supports`` predicate accepts the operands; otherwise fall back to
    xla (never an error: an odd shape must not crash a model that merely
    asked for the fast path).  Off-TPU the kernels run in Pallas
    interpret mode, so 'pallas' is still honoured there — that is how
    the CPU tests exercise the exact TPU tile program.
  * ``'auto'``   — pallas only on a TPU-default-backend process AND when
    the impl's ``auto`` policy (a measured heuristic, e.g. attention's
    D>64/L>=4096 rule) says the kernel wins; else xla.

Resolution happens at TRACE time from static shapes/dtypes and the
process-default backend, so dispatch can never introduce a retrace
(pinned by ``tests/test_pallas_film.py``'s compile_budget test).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional


def _always(*args, **kwargs) -> bool:
    return True


@dataclasses.dataclass(frozen=True)
class KernelImpl:
    """One registered implementation of a dispatched op.

    ``supports`` gates correctness (shapes/dtypes the kernel handles at
    all); ``auto`` gates the 'auto' policy (where the kernel *wins*).
    Both see the same operands the caller passes to :func:`resolve`.
    """

    op: str
    name: str
    fn: Callable
    supports: Callable[..., bool] = _always
    auto: Callable[..., bool] = _always


_REGISTRY: Dict[str, Dict[str, KernelImpl]] = {}


def register(op: str, name: str, fn: Callable, *,
             supports: Optional[Callable[..., bool]] = None,
             auto: Optional[Callable[..., bool]] = None) -> KernelImpl:
    """Register ``fn`` as implementation ``name`` of ``op``.
    Re-registering the same (op, name) replaces the entry (module
    reload friendliness); every op must register an 'xla' fallback."""
    impl = KernelImpl(op=op, name=name, fn=fn,
                      supports=supports or _always, auto=auto or _always)
    _REGISTRY.setdefault(op, {})[name] = impl
    return impl


def implementations(op: str) -> Dict[str, KernelImpl]:
    """The registered implementations of ``op`` (empty dict if none)."""
    return dict(_REGISTRY.get(op, {}))


def default_backend() -> str:
    """Process-default jax backend, 'cpu' when no backend exists yet
    (conservative: trace-time resolution must never raise)."""
    import jax

    try:
        return jax.default_backend()
    except RuntimeError:  # pragma: no cover - no backend at trace time
        return "cpu"


def resolve(op: str, requested: str, *args, **kwargs) -> KernelImpl:
    """Resolve ``requested`` ('auto' | 'pallas' | 'xla') to a registered
    implementation of ``op`` given the operands.

    The operands are passed to the candidate's ``supports`` / ``auto``
    predicates; they are trace-time values, so only static properties
    (shape, dtype) may be inspected.
    """
    impls = _REGISTRY.get(op)
    if not impls:
        raise KeyError(f"no implementations registered for op {op!r}")
    if requested not in ("auto", "pallas", "xla"):
        raise ValueError(
            f"op {op!r}: requested impl {requested!r} not in "
            "('auto', 'pallas', 'xla')")
    pallas = impls.get("pallas")
    if requested == "pallas" and pallas is not None \
            and pallas.supports(*args, **kwargs):
        return pallas
    if requested == "auto" and pallas is not None \
            and default_backend() == "tpu" \
            and pallas.auto(*args, **kwargs) \
            and pallas.supports(*args, **kwargs):
        return pallas
    xla = impls.get("xla")
    if xla is None:
        raise KeyError(f"op {op!r} has no 'xla' fallback registered")
    return xla


def dispatch(op: str, requested: str, *args, **kwargs):
    """Resolve and call in one step."""
    return resolve(op, requested, *args, **kwargs).fn(*args, **kwargs)
