"""TPU Pallas fused GroupNorm epilogues (forward + backward).

The X-UNet's per-step cost is dominated by memory-bound elementwise
chains around its ~40 ``FrameGroupNorm`` sites: every ``ResnetBlock``
runs GN -> SiLU at its entry and GN -> FiLM(scale/shift) before its
second conv, each as a string of separate XLA ops — statistics,
normalize, affine, modulate, activation — and each op is a full
``[B, F, H, W, C]`` HBM round trip.  This module fuses each chain into
one VMEM-resident kernel:

  * **forward** — a two-phase tile program over ``[N, L, C]`` (frames
    folded into N, pixels into L).  Phase 0 streams the row tiles once,
    accumulating per-channel sum / sum-of-squares in f32 VMEM scratch
    (the same mean/E[x^2] formulation Flax's GroupNorm uses).  Phase 1
    reduces channels to group statistics with a 0/1 group-membership
    mask matmul (static counts — padded rows and channels are excluded
    exactly), then re-streams each tile, normalizing, applying
    gamma/beta, the optional per-pixel FiLM ``(1+scale)*y + shift``,
    and the optional SiLU, writing the only ``[N, L, C]``-sized HBM
    traffic of the whole chain.  Under differentiation the per-channel
    mean/rstd are written out as an ``[N, 8, C_pad]`` residual
    (sublane-replicated — TPU output blocks need (8, 128)-aligned
    trailing dims); the inference path skips them.
  * **backward** — the standard GN gradient in the same two-phase
    shape: phase 0 re-derives x_hat and the upstream gradient through
    SiLU/FiLM per tile, accumulating the two per-channel reductions
    ``sum(dxhat)`` / ``sum(dxhat * xhat)`` plus per-N dgamma/dbeta
    partials in scratch; phase 1 turns them into group means via the
    same mask matmul and emits ``dx = rstd * (dxhat - mean_g(dxhat)
    - xhat * mean_g(dxhat * xhat))`` and the per-pixel dscale/dshift
    tiles.  dgamma/dbeta partials are summed over N outside the kernel.

Channels are zero-padded to the 128-lane tile and rows to the f32
sublane multiple; padded channels carry zero gamma and land in
out-of-range mask groups, so they never pollute real statistics.  All
accumulation is float32 regardless of input dtype (bf16 inputs use the
MXU mask matmuls with f32 ``preferred_element_type``).

On non-TPU backends the kernels run in Pallas interpret mode (tests);
:mod:`diff3d_tpu.ops.dispatch` only routes here when asked ('pallas')
or on TPU ('auto').
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from diff3d_tpu.ops import dispatch

try:  # pltpu imports without TPU; used for CompilerParams / VMEM only
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANE = 128          # TPU lane width: channels padded to a multiple
MAX_C = 4096        # padded-channel cap (srn128 up-path concat is 2048)
MIN_SUBLANE = 8     # f32 sublane granularity: row tiles padded to this
EPS = 1e-5          # torch/Flax GroupNorm epsilon (models/layers.py)
#: Row-tile VMEM budget: block_rows * C_pad * 4B stays under this, so
#: the streamed x/scale/shift/out tiles plus double-buffering fit VMEM
#: comfortably even at C_pad=2048.
_TILE_BYTES = 512 * 1024


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _row_block(L: int, C_pad: int) -> int:
    """Rows per tile: 128 at model widths, halved while the f32 tile
    exceeds the VMEM budget, shrunk to the sublane-padded L for tiny
    test images."""
    br = 128
    while br > MIN_SUBLANE and br * C_pad * 4 > _TILE_BYTES:
        br //= 2
    if L < br:
        br = max(MIN_SUBLANE, _round_up(L, MIN_SUBLANE))
    return br


def _g_pad(C_pad: int, group_size: int) -> int:
    """Mask-group count padded to full lanes.  Covers every padded
    channel's ``c // group_size`` id: pad channels (c >= C) map to ids
    >= num_groups, i.e. into all-pad groups that real channels never
    read back."""
    return _round_up((C_pad + group_size - 1) // group_size, LANE)


def _out_struct(shape, dtype, like) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct carrying ``like``'s varying-manual-axes set so
    the kernels work inside ``shard_map`` (same contract as
    pallas_attention)."""
    try:
        vma = jax.typeof(like).vma
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):  # pragma: no cover - older jax
        return jax.ShapeDtypeStruct(shape, dtype)


def supports(x: jnp.ndarray, *args, num_groups: int = 32,
             **kwargs) -> bool:
    """Shapes/dtypes the fused kernel handles: ``[N, L, C]`` with C
    divisible by ``num_groups`` and padded channels within MAX_C."""
    if getattr(x, "ndim", 0) != 3:
        return False
    if x.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    C = x.shape[-1]
    if C < 1 or C % num_groups:
        return False
    return _round_up(C, LANE) <= MAX_C


def _auto(x: jnp.ndarray, *args, **kwargs) -> bool:
    """'auto' policy: the fusion pays off once the chain is actually
    memory-bound — any real feature map qualifies; only degenerate
    few-pixel shapes stay on XLA."""
    return x.shape[1] >= 64


def _compiler_params(interpret: bool):
    if pltpu is None or interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "arbitrary", "arbitrary"))


def _vmem(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.ANY  # pragma: no cover


def _group_masks(C: int, C_pad: int, G_pad: int, group_size: int):
    """The 0/1 group-membership matrix ``M [C_pad, G_pad]`` (channel c
    belongs to group c // group_size; padded channels excluded), built
    from 2D iotas in-kernel."""
    cid = jax.lax.broadcasted_iota(jnp.int32, (C_pad, G_pad), 0)
    gid = jax.lax.broadcasted_iota(jnp.int32, (C_pad, G_pad), 1)
    member = (cid // group_size == gid) & (cid < C)
    return member.astype(jnp.float32)


def _channel_stats(chan_sum, chan_sq, M, count: float):
    """Per-channel mean / rstd ``[1, C_pad]`` from per-channel sums via
    the group mask: reduce channels -> groups, normalize by the static
    real-element count, broadcast groups -> channels.  Padded channels
    (all-zero mask rows) come back with mean = rstd = 0."""
    gsum = jnp.dot(chan_sum, M, preferred_element_type=jnp.float32)
    gsq = jnp.dot(chan_sq, M, preferred_element_type=jnp.float32)
    gmean = gsum / count
    gvar = jnp.maximum(gsq / count - gmean * gmean, 0.0)
    grstd = jax.lax.rsqrt(gvar + EPS)
    mean_c = jnp.dot(gmean, M.T, preferred_element_type=jnp.float32)
    rstd_c = jnp.dot(grstd, M.T, preferred_element_type=jnp.float32)
    return mean_c, rstd_c


def _silu_grad(y: jnp.ndarray) -> jnp.ndarray:
    sig = jax.nn.sigmoid(y)
    return sig * (1.0 + y * (1.0 - sig))


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------


def _fwd_kernel(*refs, L: int, C: int, C_pad: int, G_pad: int,
                group_size: int, block_rows: int, film: bool, silu: bool,
                save_stats: bool):
    if film:
        x_ref, gamma_ref, beta_ref, scale_ref, shift_ref = refs[:5]
        rest = refs[5:]
    else:
        x_ref, gamma_ref, beta_ref = refs[:3]
        scale_ref = shift_ref = None
        rest = refs[3:]
    if save_stats:
        o_ref, mean_ref, rstd_ref, sum_scr, sq_scr = rest
    else:
        o_ref, sum_scr, sq_scr = rest
        mean_ref = rstd_ref = None
    p = pl.program_id(1)
    t = pl.program_id(2)

    @pl.when((p == 0) & (t == 0))
    def _init():
        sum_scr[...] = jnp.zeros_like(sum_scr)
        sq_scr[...] = jnp.zeros_like(sq_scr)

    @pl.when(p == 0)
    def _accumulate():
        x = x_ref[0].astype(jnp.float32)               # [br, C_pad]
        rows = t * block_rows + jax.lax.broadcasted_iota(
            jnp.int32, (block_rows, 1), 0)
        x = jnp.where(rows < L, x, 0.0)                # mask pad rows
        sum_scr[...] += jnp.sum(x, axis=0, keepdims=True)
        sq_scr[...] += jnp.sum(x * x, axis=0, keepdims=True)

    @pl.when(p == 1)
    def _emit():
        M = _group_masks(C, C_pad, G_pad, group_size)
        mean_c, rstd_c = _channel_stats(
            sum_scr[0:1, :], sq_scr[0:1, :], M,
            float(L * group_size))
        x = x_ref[0].astype(jnp.float32)
        y = (x - mean_c) * rstd_c
        y = y * gamma_ref[0:1, :] + beta_ref[0:1, :]
        if film:
            y = y * (1.0 + scale_ref[0].astype(jnp.float32)) \
                + shift_ref[0].astype(jnp.float32)
        if silu:
            y = y * jax.nn.sigmoid(y)
        o_ref[0] = y.astype(o_ref.dtype)
        if save_stats:
            @pl.when(t == 0)
            def _stats():
                mean_ref[0] = jnp.broadcast_to(mean_c, mean_ref.shape[1:])
                rstd_ref[0] = jnp.broadcast_to(rstd_c, rstd_ref.shape[1:])


def _pad_rows_chans(x, L_pad: int, C_pad: int):
    N, L, C = x.shape
    return jnp.pad(x, ((0, 0), (0, L_pad - L), (0, C_pad - C)))


def _affine_tile(p, C_pad: int):
    """[C] f32 param -> sublane-replicated [8, C_pad] kernel operand."""
    p = jnp.pad(p.astype(jnp.float32), (0, C_pad - p.shape[0]))
    return jnp.broadcast_to(p[None], (MIN_SUBLANE, C_pad))


def _fwd_call(x, gamma, beta, scale, shift, *, num_groups: int,
              film: bool, silu: bool, interpret: bool, save_stats: bool):
    N, L, C = x.shape
    C_pad = _round_up(C, LANE)
    br = _row_block(L, C_pad)
    L_pad = _round_up(L, br)
    gs = C // num_groups
    G_pad = _g_pad(C_pad, gs)
    grid = (N, 2, L_pad // br)

    xp = _pad_rows_chans(x, L_pad, C_pad)
    gp, bp = _affine_tile(gamma, C_pad), _affine_tile(beta, C_pad)
    x_spec = pl.BlockSpec((1, br, C_pad), lambda n, p, t: (n, t, 0))
    ab_spec = pl.BlockSpec((MIN_SUBLANE, C_pad), lambda n, p, t: (0, 0))
    # Each out block is visited through all of phase 0 at row 0 (no
    # write, no flush — the index only changes on phase 1's walk), then
    # written exactly once with real data.
    o_spec = pl.BlockSpec((1, br, C_pad), lambda n, p, t: (n, p * t, 0))
    st_spec = pl.BlockSpec((1, MIN_SUBLANE, C_pad),
                           lambda n, p, t: (n, 0, 0))

    operands = [xp, gp, bp]
    in_specs = [x_spec, ab_spec, ab_spec]
    if film:
        operands += [_pad_rows_chans(scale, L_pad, C_pad),
                     _pad_rows_chans(shift, L_pad, C_pad)]
        in_specs += [x_spec, x_spec]
    out_specs = [o_spec]
    out_shape = [_out_struct((N, L_pad, C_pad), x.dtype, x)]
    if save_stats:
        out_specs += [st_spec, st_spec]
        out_shape += [
            _out_struct((N, MIN_SUBLANE, C_pad), jnp.float32, x),
            _out_struct((N, MIN_SUBLANE, C_pad), jnp.float32, x)]

    kernel = functools.partial(
        _fwd_kernel, L=L, C=C, C_pad=C_pad, G_pad=G_pad, group_size=gs,
        block_rows=br, film=film, silu=silu, save_stats=save_stats)
    outs = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[_vmem((MIN_SUBLANE, C_pad)),
                        _vmem((MIN_SUBLANE, C_pad))],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*operands)
    out = outs[0][:, :L, :C]
    if save_stats:
        return out, outs[1], outs[2]
    return out, None, None


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------


def _bwd_kernel(*refs, L: int, C: int, C_pad: int, G_pad: int,
                group_size: int, block_rows: int, film: bool, silu: bool):
    if film:
        (x_ref, g_ref, gamma_ref, beta_ref, scale_ref, shift_ref,
         mean_ref, rstd_ref, dx_ref, dscale_ref, dshift_ref,
         dgamma_ref, dbeta_ref, s1_scr, s2_scr, dg_scr, db_scr) = refs
    else:
        (x_ref, g_ref, gamma_ref, beta_ref, mean_ref, rstd_ref,
         dx_ref, dgamma_ref, dbeta_ref, s1_scr, s2_scr, dg_scr,
         db_scr) = refs
        scale_ref = shift_ref = dscale_ref = dshift_ref = None
    p = pl.program_id(1)
    t = pl.program_id(2)

    mean_c = mean_ref[0][0:1, :]                       # [1, C_pad]
    rstd_c = rstd_ref[0][0:1, :]
    gamma = gamma_ref[0:1, :]

    def _tile_grads():
        """(xhat, y_gn, dy_f, dy_gn, dxhat) for the current tile.
        All padding is benign: the upstream gradient is zero-padded, so
        every padded row/channel contributes exact zeros."""
        x = x_ref[0].astype(jnp.float32)
        g = g_ref[0].astype(jnp.float32)
        xhat = (x - mean_c) * rstd_c
        y_gn = xhat * gamma + beta_ref[0:1, :]
        if film:
            scale = scale_ref[0].astype(jnp.float32)
            y = y_gn * (1.0 + scale) + shift_ref[0].astype(jnp.float32)
        else:
            scale = None
            y = y_gn
        dy_f = g * _silu_grad(y) if silu else g
        dy_gn = dy_f * (1.0 + scale) if film else dy_f
        dxhat = dy_gn * gamma
        return xhat, y_gn, dy_f, dy_gn, dxhat

    @pl.when((p == 0) & (t == 0))
    def _init():
        s1_scr[...] = jnp.zeros_like(s1_scr)
        s2_scr[...] = jnp.zeros_like(s2_scr)
        dg_scr[...] = jnp.zeros_like(dg_scr)
        db_scr[...] = jnp.zeros_like(db_scr)

    @pl.when(p == 0)
    def _accumulate():
        xhat, _y_gn, _dy_f, dy_gn, dxhat = _tile_grads()
        s1_scr[...] += jnp.sum(dxhat, axis=0, keepdims=True)
        s2_scr[...] += jnp.sum(dxhat * xhat, axis=0, keepdims=True)
        dg_scr[...] += jnp.sum(dy_gn * xhat, axis=0, keepdims=True)
        db_scr[...] += jnp.sum(dy_gn, axis=0, keepdims=True)

    @pl.when(p == 1)
    def _emit():
        M = _group_masks(C, C_pad, G_pad, group_size)
        count = float(L * group_size)
        gS1 = jnp.dot(s1_scr[0:1, :], M,
                      preferred_element_type=jnp.float32) / count
        gS2 = jnp.dot(s2_scr[0:1, :], M,
                      preferred_element_type=jnp.float32) / count
        m1_c = jnp.dot(gS1, M.T, preferred_element_type=jnp.float32)
        m2_c = jnp.dot(gS2, M.T, preferred_element_type=jnp.float32)
        xhat, y_gn, dy_f, _dy_gn, dxhat = _tile_grads()
        dx = rstd_c * (dxhat - m1_c - xhat * m2_c)
        dx_ref[0] = dx.astype(dx_ref.dtype)
        if film:
            dscale_ref[0] = (dy_f * y_gn).astype(dscale_ref.dtype)
            dshift_ref[0] = dy_f.astype(dshift_ref.dtype)

        @pl.when(t == 0)
        def _partials():
            dgamma_ref[0] = dg_scr[...]
            dbeta_ref[0] = db_scr[...]


def _bwd_call(x, g, gamma, beta, scale, shift, mean, rstd, *,
              num_groups: int, film: bool, silu: bool, interpret: bool):
    N, L, C = x.shape
    C_pad = _round_up(C, LANE)
    br = _row_block(L, C_pad)
    L_pad = _round_up(L, br)
    gs = C // num_groups
    G_pad = _g_pad(C_pad, gs)
    grid = (N, 2, L_pad // br)

    xp = _pad_rows_chans(x, L_pad, C_pad)
    gup = _pad_rows_chans(g, L_pad, C_pad)
    gp, bp = _affine_tile(gamma, C_pad), _affine_tile(beta, C_pad)
    x_spec = pl.BlockSpec((1, br, C_pad), lambda n, p, t: (n, t, 0))
    ab_spec = pl.BlockSpec((MIN_SUBLANE, C_pad), lambda n, p, t: (0, 0))
    o_spec = pl.BlockSpec((1, br, C_pad), lambda n, p, t: (n, p * t, 0))
    st_spec = pl.BlockSpec((1, MIN_SUBLANE, C_pad),
                           lambda n, p, t: (n, 0, 0))

    operands = [xp, gup, gp, bp]
    in_specs = [x_spec, x_spec, ab_spec, ab_spec]
    if film:
        operands += [_pad_rows_chans(scale, L_pad, C_pad),
                     _pad_rows_chans(shift, L_pad, C_pad)]
        in_specs += [x_spec, x_spec]
    operands += [mean, rstd]
    in_specs += [st_spec, st_spec]

    out_specs = [o_spec]
    out_shape = [_out_struct((N, L_pad, C_pad), x.dtype, x)]
    if film:
        out_specs += [o_spec, o_spec]
        out_shape += [
            _out_struct((N, L_pad, C_pad), scale.dtype, x),
            _out_struct((N, L_pad, C_pad), shift.dtype, x)]
    out_specs += [st_spec, st_spec]
    out_shape += [
        _out_struct((N, MIN_SUBLANE, C_pad), jnp.float32, x),
        _out_struct((N, MIN_SUBLANE, C_pad), jnp.float32, x)]

    kernel = functools.partial(
        _bwd_kernel, L=L, C=C, C_pad=C_pad, G_pad=G_pad, group_size=gs,
        block_rows=br, film=film, silu=silu)
    outs = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[_vmem((MIN_SUBLANE, C_pad))] * 4,
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*operands)
    dx = outs[0][:, :L, :C]
    nxt = 1
    if film:
        dscale = outs[nxt][:, :L, :C]
        dshift = outs[nxt + 1][:, :L, :C]
        nxt += 2
    else:
        dscale = dshift = None
    # Per-N partials: row 0 of the sublane-replicated block, real
    # channels only, summed over N in XLA (a [N, C] reduce — tiny).
    dgamma = jnp.sum(outs[nxt][:, 0, :C], axis=0)
    dbeta = jnp.sum(outs[nxt + 1][:, 0, :C], axis=0)
    return dx, dscale, dshift, dgamma, dbeta


# --------------------------------------------------------------------------
# public entry: custom-vjp fused GroupNorm epilogue over [N, L, C]
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _fused(x, gamma, beta, scale, shift, num_groups: int, film: bool,
           silu: bool, interpret: bool):
    # Primal (inference) path: no stats residuals materialised.
    out, _, _ = _fwd_call(x, gamma, beta, scale, shift,
                          num_groups=num_groups, film=film, silu=silu,
                          interpret=interpret, save_stats=False)
    return out


def _fused_fwd(x, gamma, beta, scale, shift, num_groups: int, film: bool,
               silu: bool, interpret: bool):
    out, mean, rstd = _fwd_call(x, gamma, beta, scale, shift,
                                num_groups=num_groups, film=film,
                                silu=silu, interpret=interpret,
                                save_stats=True)
    return out, (x, gamma, beta, scale, shift, mean, rstd)


def _fused_bwd(num_groups: int, film: bool, silu: bool, interpret: bool,
               res, g):
    x, gamma, beta, scale, shift, mean, rstd = res
    dx, dscale, dshift, dgamma, dbeta = _bwd_call(
        x, g, gamma, beta, scale, shift, mean, rstd,
        num_groups=num_groups, film=film, silu=silu, interpret=interpret)
    if not film:
        dscale = jnp.zeros_like(scale)
        dshift = jnp.zeros_like(shift)
    return (dx, dgamma.astype(gamma.dtype), dbeta.astype(beta.dtype),
            dscale, dshift)


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_groupnorm(x: jnp.ndarray, gamma: jnp.ndarray, beta: jnp.ndarray,
                    *, num_groups: int, scale: Optional[jnp.ndarray] = None,
                    shift: Optional[jnp.ndarray] = None, silu: bool = False,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused GroupNorm -> (FiLM) -> (SiLU) over ``[N, L, C]``.

    ``gamma`` / ``beta`` are the ``[C]`` GroupNorm affine params
    (float32, like Flax keeps them); ``scale`` / ``shift`` — both or
    neither — are per-pixel FiLM tensors shaped like ``x`` and the
    epilogue becomes ``y * (1 + scale) + shift``.  ``silu`` appends the
    activation.  ``interpret`` defaults to True off TPU so the same
    tile program runs everywhere (the CPU tests exercise exactly what
    the TPU executes).  Epsilon is the torch-parity 1e-5.
    """
    assert supports(x, num_groups=num_groups), \
        (x.shape, x.dtype, num_groups)
    film = scale is not None
    assert film == (shift is not None), "scale and shift come together"
    if film:
        assert scale.shape == x.shape and shift.shape == x.shape, \
            (x.shape, scale.shape, shift.shape)
    else:
        scale = jnp.zeros((), x.dtype)
        shift = jnp.zeros((), x.dtype)
    if interpret is None:
        try:
            interpret = jax.devices()[0].platform != "tpu"
        except RuntimeError:  # pragma: no cover
            interpret = True
    return _fused(x, gamma, beta, scale, shift, int(num_groups), film,
                  bool(silu), bool(interpret))


def xla_groupnorm(x: jnp.ndarray, gamma: jnp.ndarray, beta: jnp.ndarray,
                  *, num_groups: int, scale: Optional[jnp.ndarray] = None,
                  shift: Optional[jnp.ndarray] = None, silu: bool = False,
                  interpret: Optional[bool] = None) -> jnp.ndarray:
    """The unfused XLA composition of the same chain — the dispatch
    fallback and the parity reference the kernel tests compare against.
    Statistics in f32 with Flax GroupNorm's mean/E[x^2] formulation and
    the same 1e-5 epsilon."""
    del interpret
    N, L, C = x.shape
    xf = x.astype(jnp.float32).reshape(N, L, num_groups, C // num_groups)
    mean = jnp.mean(xf, axis=(1, 3), keepdims=True)
    mean2 = jnp.mean(xf * xf, axis=(1, 3), keepdims=True)
    var = jnp.maximum(mean2 - mean * mean, 0.0)
    y = (xf - mean) * jax.lax.rsqrt(var + EPS)
    y = y.reshape(N, L, C)
    y = y * gamma.astype(jnp.float32) + beta.astype(jnp.float32)
    y = y.astype(x.dtype)
    if scale is not None:
        y = y * (1.0 + scale) + shift
    if silu:
        y = jax.nn.silu(y)
    return y


dispatch.register("groupnorm", "pallas", fused_groupnorm,
                  supports=supports, auto=_auto)
dispatch.register("groupnorm", "xla", xla_groupnorm)
