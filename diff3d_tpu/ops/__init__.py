from diff3d_tpu.ops.attention import multi_head_attention

__all__ = ["multi_head_attention"]
