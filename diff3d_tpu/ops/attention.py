"""Attention core with backend dispatch.

The reference runs ``torch.nn.MultiheadAttention`` over ``H*W`` tokens
(``/root/reference/xunet.py:154-177``) — 4096 tokens at 64^2, 16384 at
128^2.  Here the softmax(QK^T)V core is a swappable backend registered
with :mod:`diff3d_tpu.ops.dispatch` (shared with the fused GroupNorm
epilogues):

  * ``'xla'``    — ``jax.nn.dot_product_attention``; XLA already emits a
    fused, flash-style kernel on TPU for moderate sequence lengths.
  * ``'pallas'`` — hand-written TPU Pallas flash kernel
    (:mod:`diff3d_tpu.ops.pallas_attention`), tiled for the MXU.
  * ``'auto'``   — pallas on TPU when shapes qualify, else xla.

All shapes here are ``[B, L, n_heads, head_dim]`` (jax.nn convention).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from diff3d_tpu.ops import dispatch


def _xla_sdpa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.dot_product_attention(q, k, v)


def _pallas_sdpa(q: jnp.ndarray, k: jnp.ndarray,
                 v: jnp.ndarray) -> jnp.ndarray:
    from diff3d_tpu.ops.pallas_attention import flash_attention

    return flash_attention(q, k, v)


def _pallas_supports(q, k, v) -> bool:
    from diff3d_tpu.ops.pallas_attention import supports

    return supports(q, k, v)


def _pallas_auto(q, *args) -> bool:
    """Measured policy (one v5e chip, X-UNet shapes — see tools/tune_train):
    the Pallas flash kernel zero-pads the head dim to the 128-lane MXU
    tile, so at D=32/64 it wastes 4x/2x of every QK^T and PV matmul and
    XLA's fused attention wins; only lane-filling heads (D > 64) with
    sequences long enough that the materialised [L, L] logits' HBM traffic
    dominates are worth the flash kernel."""
    D, L = q.shape[-1], q.shape[1]
    return D > 64 and L >= 4096


dispatch.register("sdpa", "xla", _xla_sdpa)
dispatch.register("sdpa", "pallas", _pallas_sdpa,
                  supports=_pallas_supports, auto=_pallas_auto)


def _resolve_auto(q: jnp.ndarray) -> str:
    """Backend an ``impl='auto'`` sdpa call resolves to for ``q``.

    'auto' resolves from the PROCESS-DEFAULT backend, not from where the
    computation is actually placed: a TPU-backed process tracing a
    CPU-mesh program must pass ``impl='xla'`` explicitly (tests/conftest
    and the dryrun pin the whole process to CPU instead, which also
    resolves correctly)."""
    return dispatch.resolve("sdpa", "auto", q, q, q).name


def sdpa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
         impl: str = "auto") -> jnp.ndarray:
    """Scaled dot-product attention over ``[B, L, H, D]`` tensors.

    ``impl`` may also name a sequence-parallel core — ``'ring:<axis>'`` or
    ``'ulysses:<axis>'`` — in which case q/k/v are local token shards of a
    global sequence sharded over mesh axis ``<axis>`` and the call must be
    inside ``shard_map`` with that axis in scope.  This is how the X-UNet's
    attention layers scale past one device's tokens: set
    ``ModelConfig.attn_impl='ring:model'`` and run the step in a
    ``shard_map`` whose specs shard the spatial axis.  Everything else
    ('auto' | 'pallas' | 'xla') goes through the shared kernel registry.
    """
    if ":" in impl:
        from diff3d_tpu.parallel import ring_sdpa, ulysses_sdpa
        kind, _, axis = impl.partition(":")
        fn = {"ring": ring_sdpa, "ulysses": ulysses_sdpa}[kind]
        return fn(q, k, v, axis_name=axis)
    return dispatch.dispatch("sdpa", impl, q, k, v)


def multi_head_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         num_heads: int, impl: str = "auto") -> jnp.ndarray:
    """Splits pre-projected ``[B, L, C]`` q/k/v into heads, runs sdpa,
    merges heads back to ``[B, Lq, C]``.  Projections live in the Flax
    layer (:class:`diff3d_tpu.models.layers.AttnLayer`)."""
    B, Lq, C = q.shape
    Lk = k.shape[1]
    D = C // num_heads
    out = sdpa(q.reshape(B, Lq, num_heads, D),
               k.reshape(B, Lk, num_heads, D),
               v.reshape(B, Lk, num_heads, D), impl=impl)
    return out.reshape(B, Lq, C)
