"""TPU Pallas flash attention (forward + backward).

Replaces the reference's ``torch.nn.MultiheadAttention`` sdpa core
(``/root/reference/xunet.py:154-177``, which delegates to cuDNN) with a
hand-tiled TPU kernel:

  * **forward** — online-softmax flash attention: the KV sequence is
    streamed through VMEM in ``block_k`` tiles while running max / sum /
    output accumulators live in VMEM scratch; one QK^T and one PV matmul
    per tile hit the MXU, nothing of size ``[Lq, Lk]`` ever touches HBM.
    Under differentiation the per-row log-sum-exp is written out as the
    backward residual (lane-replicated to a ``[.., 128]`` tile — TPU
    output blocks need the last two dims (8, 128)-aligned); the inference
    path skips the residual entirely.
  * **backward** — the standard two-kernel flash backward: one kernel
    accumulating dK/dV over query tiles and one accumulating dQ over key
    tiles, each recomputing the probabilities from (Q, K, lse).  The
    ``delta = rowsum(dO * O)`` term is computed in-kernel from the dO/O
    blocks (each block holds the full padded head dim, so the row sum is
    block-local).

Head dim is zero-padded to a multiple of the 128 lane width (D <= 512;
one lane tile at the srn64 deep levels' D=128, two at srn128's D=256 —
the q/k/v blocks and the output accumulator are ``D_pad`` lanes wide,
while the running max / sum and the lse residual stay one lane tile) and
sequence lengths to the tile size; padded key columns are masked to
-1e30 before the softmax so both passes ignore them.  Zero-padded head
columns contribute nothing to QK^T and stay zero through PV.  All
accumulation is float32 regardless of input dtype (bf16 inputs still use
the MXU with f32 accumulation via ``preferred_element_type``).

On non-TPU backends the kernels run in Pallas interpret mode (tests); the
dispatcher in :mod:`diff3d_tpu.ops.attention` only routes here on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu is importable without TPU; used for CompilerParams only
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANE = 128          # TPU lane width: head dim is padded to a multiple
MAX_D = 512         # supported head-dim cap (4 lane tiles in VMEM)
MIN_SUBLANE = 8     # f32 sublane granularity: seq tiles padded to this
NEG_INF = -1e30


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _d_pad(D: int) -> int:
    """Head dim padded to full lane tiles (128 -> 128, 256 -> 256,
    96 -> 128, 160 -> 256)."""
    return _round_up(D, LANE)


def _out_struct(shape, dtype, like) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct carrying ``like``'s varying-manual-axes set, so the
    kernels work inside ``shard_map`` with its default ``check_vma=True``
    (the ring-attention engine path)."""
    try:
        vma = jax.typeof(like).vma
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except (AttributeError, TypeError):  # pragma: no cover - older jax
        return jax.ShapeDtypeStruct(shape, dtype)


def supports(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> bool:
    """Shapes/dtypes this kernel handles: ``[B, L, H, D]`` with
    D <= MAX_D (512; covers srn128's deep-level D=256)."""
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        return False
    if q.dtype not in (jnp.float32, jnp.bfloat16):
        return False
    D = q.shape[-1]
    return D <= MAX_D and k.shape[-1] == D and v.shape[-1] == D


def _block_sizes(Lq: int, Lk: int) -> tuple[int, int, int, int]:
    """Pick (block_q, block_k, Lq_pad, Lk_pad)."""
    bq = 128 if Lq >= 128 else _round_up(Lq, MIN_SUBLANE)
    bk = 128 if Lk >= 128 else _round_up(Lk, MIN_SUBLANE)
    return bq, bk, _round_up(Lq, bq), _round_up(Lk, bk)


def _key_mask(ki: jax.Array, block_k: int, Lk: int) -> jnp.ndarray:
    """[1, block_k] bool — True for real (non-pad) key columns."""
    col = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    return col < Lk


def _compiler_params(interpret: bool):
    if pltpu is None or interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _vmem(shape):
    if pltpu is not None:
        return pltpu.VMEM(shape, jnp.float32)
    return pl.ANY  # pragma: no cover


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *maybe_lse_then_scratch,
                scale: float, Lk: int, block_k: int, save_lse: bool):
    if save_lse:
        lse_ref, m_scr, l_scr, acc_scr = maybe_lse_then_scratch
    else:
        m_scr, l_scr, acc_scr = maybe_lse_then_scratch
        lse_ref = None
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q = q_ref[0].astype(jnp.float32)                       # [bq, D_pad]
    k = k_ref[0].astype(jnp.float32)                       # [bk, D_pad]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = jnp.where(_key_mask(ki, block_k, Lk), s, NEG_INF)  # [bq, bk]

    m_prev = m_scr[:, :1]                                  # [bq, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)             # [bq, 1]
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)                        # rescale old acc
    p = jnp.exp(s - m_new)                                 # [bq, bk]

    l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
    v = v_ref[0].astype(jnp.float32)                       # [bk, D_pad]
    pv = jnp.dot(p, v, preferred_element_type=jnp.float32)
    acc_scr[...] = acc_scr[...] * alpha + pv

    m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        if save_lse:
            lse = m_scr[:, :1] + jnp.log(l_safe)           # [bq, 1]
            lse_ref[0] = jnp.broadcast_to(lse, lse_ref.shape[1:])


def _fwd_call(q, k, v, *, scale: float, Lq: int, Lk: int, interpret: bool,
              save_lse: bool):
    """q/k/v: ``[N, L_pad, D_pad]``.  Returns ``o`` (and ``lse
    [N, Lq_pad, LANE]`` lane-replicated when ``save_lse``)."""
    N, Lq_pad, D_pad = q.shape
    Lk_pad = k.shape[1]
    bq, bk, _, _ = _block_sizes(Lq_pad, Lk_pad)
    grid = (N, Lq_pad // bq, Lk_pad // bk)

    qo_spec = pl.BlockSpec((1, bq, D_pad), lambda n, qi, ki: (n, qi, 0))
    kv_spec = pl.BlockSpec((1, bk, D_pad), lambda n, qi, ki: (n, ki, 0))
    lse_spec = pl.BlockSpec((1, bq, LANE), lambda n, qi, ki: (n, qi, 0))
    out_specs = [qo_spec]
    out_shape = [_out_struct((N, Lq_pad, D_pad), q.dtype, q)]
    if save_lse:
        out_specs.append(lse_spec)
        out_shape.append(
            _out_struct((N, Lq_pad, LANE), jnp.float32, q))

    kernel = functools.partial(_fwd_kernel, scale=scale, Lk=Lk, block_k=bk,
                               save_lse=save_lse)
    outs = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qo_spec, kv_spec, kv_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            _vmem((bq, LANE)), _vmem((bq, LANE)), _vmem((bq, D_pad)),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v)
    return (outs[0], outs[1]) if save_lse else (outs[0], None)


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
                     dk_ref, dv_ref, dk_scr, dv_scr, *, scale: float,
                     Lk: int, block_k: int):
    qi = pl.program_id(2)
    ki = pl.program_id(1)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q = q_ref[0].astype(jnp.float32)                       # [bq, D_pad]
    k = k_ref[0].astype(jnp.float32)                       # [bk, D_pad]
    v = v_ref[0].astype(jnp.float32)
    o = o_ref[0].astype(jnp.float32)                       # [bq, D_pad]
    do = do_ref[0].astype(jnp.float32)                     # [bq, D_pad]
    lse = lse_ref[0][:, :1]                                # [bq, 1]
    # delta = rowsum(dO * O): block-local (the D_pad-wide block covers the
    # whole padded head dim; padded columns are zero and contribute 0)
    delta = jnp.sum(do * o, axis=-1, keepdims=True)        # [bq, 1]
    glse = glse_ref[0][:, :1]                              # [bq, 1]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = jnp.where(_key_mask(ki, block_k, Lk), s, NEG_INF)
    p = jnp.exp(s - lse)                                   # [bq, bk]

    # dV += P^T dO ; dP = dO V^T ; dS = P*(dP - delta + glse) ; dK += dS^T Q
    # (glse is the lse-output cotangent: d lse_i / d s_ij = p_ij)
    dv_scr[...] += jax.lax.dot_general(
        p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta + glse) * scale
    dk_scr[...] += jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, glse_ref,
                   dq_ref, dq_scr, *, scale: float, Lk: int, block_k: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q = q_ref[0].astype(jnp.float32)
    k = k_ref[0].astype(jnp.float32)
    v = v_ref[0].astype(jnp.float32)
    o = o_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0][:, :1]
    delta = jnp.sum(do * o, axis=-1, keepdims=True)
    glse = glse_ref[0][:, :1]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    s = jnp.where(_key_mask(ki, block_k, Lk), s, NEG_INF)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    ds = p * (dp - delta + glse) * scale                   # [bq, bk]
    dq_scr[...] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_call(q, k, v, o, lse, do, glse, *, scale: float, Lq: int, Lk: int,
              interpret: bool):
    N, Lq_pad, D_pad = q.shape
    Lk_pad = k.shape[1]
    bq, bk, _, _ = _block_sizes(Lq_pad, Lk_pad)

    q_spec = pl.BlockSpec((1, bq, D_pad), lambda n, a, b: (n, b, 0))
    k_spec = pl.BlockSpec((1, bk, D_pad), lambda n, ki, qi: (n, ki, 0))
    lse_spec = pl.BlockSpec((1, bq, LANE), lambda n, a, b: (n, b, 0))
    dkdv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, Lk=Lk, block_k=bk),
        grid=(N, Lk_pad // bk, Lq_pad // bq),
        in_specs=[q_spec, k_spec, k_spec, q_spec, q_spec, lse_spec,
                  lse_spec],
        out_specs=[k_spec, k_spec],
        out_shape=[
            _out_struct((N, Lk_pad, D_pad), q.dtype, q),
            _out_struct((N, Lk_pad, D_pad), q.dtype, q),
        ],
        scratch_shapes=[_vmem((bk, D_pad)), _vmem((bk, D_pad))],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )
    dk, dv = dkdv(q, k, v, o, do, lse, glse)

    q2_spec = pl.BlockSpec((1, bq, D_pad), lambda n, qi, ki: (n, qi, 0))
    k2_spec = pl.BlockSpec((1, bk, D_pad), lambda n, qi, ki: (n, ki, 0))
    lse2_spec = pl.BlockSpec((1, bq, LANE), lambda n, qi, ki: (n, qi, 0))
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, Lk=Lk, block_k=bk),
        grid=(N, Lq_pad // bq, Lk_pad // bk),
        in_specs=[q2_spec, k2_spec, k2_spec, q2_spec, q2_spec, lse2_spec,
                  lse2_spec],
        out_specs=q2_spec,
        out_shape=_out_struct((N, Lq_pad, D_pad), q.dtype, q),
        scratch_shapes=[_vmem((bq, D_pad))],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, o, do, lse, glse)
    return dq, dk, dv


# --------------------------------------------------------------------------
# public entry: custom-vjp flash attention over [B, L, H, D]
# --------------------------------------------------------------------------

def _pad_qkv(x: jnp.ndarray, L_pad: int) -> jnp.ndarray:
    """[B, L, H, D] -> [B*H, L_pad, D_pad] (D_pad = full lane tiles)."""
    B, L, H, D = x.shape
    x = jnp.moveaxis(x, 2, 1).reshape(B * H, L, D)
    return jnp.pad(x, ((0, 0), (0, L_pad - L), (0, _d_pad(D) - D)))


def _unpad(x: jnp.ndarray, B: int, H: int, L: int, D: int) -> jnp.ndarray:
    """[B*H, L_pad, D_pad] -> [B, L, H, D]."""
    x = x[:, :L, :D].reshape(B, H, L, D)
    return jnp.moveaxis(x, 1, 2)


def _run_fwd(q, k, v, scale: float, interpret: bool, save_lse: bool):
    B, Lq, H, D = q.shape
    Lk = k.shape[1]
    bq, bk, Lq_pad, Lk_pad = _block_sizes(Lq, Lk)
    qp, kp, vp = (_pad_qkv(q, Lq_pad), _pad_qkv(k, Lk_pad),
                  _pad_qkv(v, Lk_pad))
    o, lse = _fwd_call(qp, kp, vp, scale=scale, Lq=Lq, Lk=Lk,
                       interpret=interpret, save_lse=save_lse)
    return _unpad(o, B, H, Lq, D), (qp, kp, vp, o, lse)


def _unpad_lse(lse, B, H, L):
    """Lane-replicated ``[B*H, L_pad, LANE]`` -> ``[B, L, H]``."""
    return jnp.moveaxis(lse[:, :L, 0].reshape(B, H, L), 1, 2)


def _pad_lse(g, B, H, L, L_pad):
    """``[B, L, H]`` -> lane-replicated ``[B*H, L_pad, LANE]``."""
    g = jnp.moveaxis(g, 2, 1).reshape(B * H, L)
    g = jnp.pad(g, ((0, 0), (0, L_pad - L)))
    return jnp.broadcast_to(g[..., None], (B * H, L_pad, LANE))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale: float, interpret: bool):
    # Primal (inference) path: no residuals materialised.
    return _run_fwd(q, k, v, scale, interpret, save_lse=False)[0]


def _flash_fwd(q, k, v, scale: float, interpret: bool):
    out, (qp, kp, vp, o, lse) = _run_fwd(q, k, v, scale, interpret,
                                         save_lse=True)
    B, Lq, H, D = q.shape
    return out, (qp, kp, vp, o, lse, (B, H, Lq, k.shape[1], D))


def _flash_bwd(scale, interpret, res, g):
    qp, kp, vp, o, lse, (B, H, Lq, Lk, D) = res
    Lq_pad = qp.shape[1]
    dop = _pad_qkv(g, Lq_pad)
    dq, dk, dv = _bwd_call(qp, kp, vp, o, lse, dop, jnp.zeros_like(lse),
                           scale=scale, Lq=Lq, Lk=Lk, interpret=interpret)
    return (_unpad(dq, B, H, Lq, D), _unpad(dk, B, H, Lk, D),
            _unpad(dv, B, H, Lk, D))


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_lse(q, k, v, scale: float, interpret: bool):
    out, (_, _, _, _, lse) = _run_fwd(q, k, v, scale, interpret,
                                      save_lse=True)
    B, Lq, H, _ = q.shape
    return out, _unpad_lse(lse, B, H, Lq)


def _flash_lse_fwd(q, k, v, scale: float, interpret: bool):
    out, (qp, kp, vp, o, lse) = _run_fwd(q, k, v, scale, interpret,
                                         save_lse=True)
    B, Lq, H, D = q.shape
    return ((out, _unpad_lse(lse, B, H, Lq)),
            (qp, kp, vp, o, lse, (B, H, Lq, k.shape[1], D)))


def _flash_lse_bwd(scale, interpret, res, gs):
    g_o, g_lse = gs
    qp, kp, vp, o, lse, (B, H, Lq, Lk, D) = res
    Lq_pad = qp.shape[1]
    dop = _pad_qkv(g_o, Lq_pad)
    glse = _pad_lse(g_lse.astype(jnp.float32), B, H, Lq, Lq_pad)
    dq, dk, dv = _bwd_call(qp, kp, vp, o, lse, dop, glse, scale=scale,
                           Lq=Lq, Lk=Lk, interpret=interpret)
    return (_unpad(dq, B, H, Lq, D), _unpad(dk, B, H, Lk, D),
            _unpad(dv, B, H, Lk, D))


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    scale: Optional[float] = None,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention over ``[B, L, H, D]`` (jax.nn layout).

    ``scale`` defaults to ``1/sqrt(D)`` (matching
    ``jax.nn.dot_product_attention``).  ``interpret`` defaults to True off
    TPU so the same kernel runs everywhere (tests exercise the exact tile
    program the TPU executes).
    """
    assert supports(q, k, v), (q.shape, k.shape, v.shape, q.dtype)
    if scale is None:
        scale = float(1.0 / np.sqrt(q.shape[-1]))
    if interpret is None:
        try:
            interpret = jax.devices()[0].platform != "tpu"
        except RuntimeError:  # pragma: no cover
            interpret = True
    return _flash(q, k, v, scale, bool(interpret))


def flash_attention_lse(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        scale: Optional[float] = None,
                        interpret: Optional[bool] = None):
    """Like :func:`flash_attention` but also returns the per-row
    log-sum-exp, ``(o [B, L, H, D], lse [B, L, H] float32)``.

    This is the building block for blockwise/ring attention
    (:func:`diff3d_tpu.parallel.ring_attention.ring_sdpa`): partial
    attention outputs over KV shards combine exactly via
    ``lse = logaddexp(lse1, lse2); o = o1*exp(lse1-lse) + o2*exp(lse2-lse)``.
    Differentiable in both outputs (the lse cotangent folds into the
    backward kernels' ``dS`` term).
    """
    assert supports(q, k, v), (q.shape, k.shape, v.shape, q.dtype)
    if scale is None:
        scale = float(1.0 / np.sqrt(q.shape[-1]))
    if interpret is None:
        try:
            interpret = jax.devices()[0].platform != "tpu"
        except RuntimeError:  # pragma: no cover
            interpret = True
    return _flash_lse(q, k, v, scale, bool(interpret))
