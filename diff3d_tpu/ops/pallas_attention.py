"""TPU Pallas flash-attention kernel (stub pending; see ops/attention.py).

Until the kernel lands, ``supports()`` returns False and the dispatcher
falls back to ``jax.nn.dot_product_attention`` (which XLA fuses well on TPU
for the model's 4096-16384 token sequences).
"""

from __future__ import annotations

import jax.numpy as jnp


def supports(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray) -> bool:
    return False


def flash_attention(q: jnp.ndarray, k: jnp.ndarray,
                    v: jnp.ndarray) -> jnp.ndarray:
    raise NotImplementedError("pallas flash attention kernel pending")
