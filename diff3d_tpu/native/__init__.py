"""ctypes bindings for the native (C++) data-loader runtime.

``decoder.cpp`` is compiled on first use with the system ``g++`` into
``libd3dnative.so`` next to this file (rebuilt automatically when the
source is newer).  Everything degrades gracefully: if the toolchain or
libpng is missing, :func:`available` is False and callers (SRNDataset,
InfiniteLoader) stay on the pure-PIL path.

Public surface:
  * :func:`available` — native runtime usable?
  * :func:`decode_image` — one PNG -> ``[s, s, 3] float32`` in [-1, 1].
  * :class:`DecoderPool` — persistent C++ worker pool decoding whole
    batches GIL-free.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "decoder.cpp")
_LIB = os.path.join(_DIR, "libd3dnative.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None  # guarded-by: _lock
_tried = False  # guarded-by: _lock

_ERRORS = {1: "cannot open file", 2: "not a PNG", 3: "PNG decode error",
           4: "bad arguments"}


def _build() -> bool:
    # Compile to a per-pid temp path and os.rename into place: concurrent
    # processes (multi-process jax.distributed, pytest-xdist) may race on
    # a shared checkout, and rename is atomic while `g++ -o final` is not.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", _SRC,
           "-o", tmp, "-lpng", "-pthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.rename(tmp, _LIB)
        return True
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        stale = (not os.path.exists(_LIB)
                 or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))
        if stale and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            return None
        lib.d3d_version.restype = ctypes.c_int
        lib.d3d_decode.restype = ctypes.c_int
        lib.d3d_decode.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_float)]
        lib.d3d_pool_create.restype = ctypes.c_void_p
        lib.d3d_pool_create.argtypes = [ctypes.c_int]
        lib.d3d_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.d3d_pool_decode.restype = ctypes.c_int
        lib.d3d_pool_decode.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
            ctypes.c_int, ctypes.POINTER(ctypes.c_float)]
        if lib.d3d_version() != 1:
            return None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


_shared_pool: Optional["DecoderPool"] = None  # guarded-by: _pool_lock


_pool_lock = threading.Lock()


def shared_pool() -> Optional["DecoderPool"]:
    """Process-wide decoder pool (lazy).  The data pipeline routes batch
    decodes through this; None when the native runtime is unavailable."""
    global _shared_pool
    if _load() is None:      # before _pool_lock: _load takes its own lock
        return None
    with _pool_lock:
        if _shared_pool is None:
            _shared_pool = DecoderPool()
        return _shared_pool


def decode_image(path: str, size: int) -> np.ndarray:
    """Decode + box-resize + normalize one PNG via the native runtime."""
    lib = _load()
    if lib is None:
        raise RuntimeError("native decoder unavailable")
    out = np.empty((size, size, 3), np.float32)
    err = lib.d3d_decode(path.encode(), size,
                         out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
    if err:
        raise IOError(f"{_ERRORS.get(err, err)}: {path}")
    return out


class DecoderPool:
    """Persistent native worker pool: ``decode_batch(paths) -> [N,s,s,3]``.

    The pool's std::threads never touch the GIL while decoding, so a
    training host can assemble the next global batch entirely during
    device compute (the reference needs 16 DataLoader worker *processes*
    for the same overlap, ``train.py:217``)."""

    def __init__(self, num_threads: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native decoder unavailable")
        self._lib = lib
        self._pool = lib.d3d_pool_create(num_threads)
        if not self._pool:
            raise RuntimeError("pool creation failed")

    def decode_batch(self, paths: Sequence[str], size: int) -> np.ndarray:
        n = len(paths)
        out = np.empty((n, size, size, 3), np.float32)
        arr = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
        err = self._lib.d3d_pool_decode(
            self._pool, arr, n, size,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if err:
            raise IOError(f"batch decode failed: {_ERRORS.get(err, err)}")
        return out

    def close(self) -> None:
        if getattr(self, "_pool", None):
            self._lib.d3d_pool_destroy(self._pool)
            self._pool = None

    def __del__(self):  # pragma: no cover - best effort
        try:
            self.close()
        except Exception:
            pass
