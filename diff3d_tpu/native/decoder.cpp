// Native data-loader runtime: threaded PNG decode -> box-resize ->
// [-1,1] float32 HWC, exposed over a C ABI for ctypes.
//
// This is the TPU framework's native equivalent of the external ATen/PIL
// decode layer behind the reference's DataLoader workers
// (/root/reference/SRNdataset.py:12-40,76-83): a persistent worker pool
// decodes whole view-batches without touching the Python GIL, so host-side
// input processing overlaps device compute.  Bound in
// diff3d_tpu/native/__init__.py; the Python PIL path remains as fallback.
//
// Decode semantics match the Python path (srn.py:_decode_image):
//   * 8/16-bit gray/palette/RGB/RGBA PNGs -> 8-bit RGB(A).
//   * box-filter (area-average) resize to size x size — exact 2x2 mean for
//     the SRN 128->64 case, fractional-weight area average otherwise.  For
//     RGBA sources the average is alpha-weighted (premultiplied), then the
//     alpha channel is dropped — exactly what PIL's resize + `[..., :3]`
//     does in the reference (SRNdataset.py:78-82).
//   * out = pixel/255 * 2 - 1, float32, HWC.

#include <png.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

namespace {

constexpr int kErrOpen = 1;
constexpr int kErrNotPng = 2;
constexpr int kErrDecode = 3;
constexpr int kErrArgs = 4;

// ---------------------------------------------------------------- decode
struct Image {
  int w = 0, h = 0, ch = 3;   // ch: 3 (RGB) or 4 (RGBA)
  std::vector<uint8_t> px;    // w*h*ch
};

int decode_png_rgb(const char* path, Image* out) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return kErrOpen;
  uint8_t sig[8];
  if (std::fread(sig, 1, 8, fp) != 8 || png_sig_cmp(sig, 0, 8)) {
    std::fclose(fp);
    return kErrNotPng;
  }
  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  png_infop info = png ? png_create_info_struct(png) : nullptr;
  if (!png || !info) {
    if (png) png_destroy_read_struct(&png, &info, nullptr);
    std::fclose(fp);
    return kErrDecode;
  }
  if (setjmp(png_jmpbuf(png))) {  // libpng error path
    png_destroy_read_struct(&png, &info, nullptr);
    std::fclose(fp);
    return kErrDecode;
  }
  png_init_io(png, fp);
  png_set_sig_bytes(png, 8);
  png_read_info(png, info);

  // Normalise every PNG flavour to 8-bit RGB.
  png_byte color = png_get_color_type(png, info);
  png_byte depth = png_get_bit_depth(png, info);
  if (color == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (color == PNG_COLOR_TYPE_GRAY && depth < 8)
    png_set_expand_gray_1_2_4_to_8(png);
  if (depth == 16) png_set_strip_16(png);
  if (color == PNG_COLOR_TYPE_GRAY || color == PNG_COLOR_TYPE_GRAY_ALPHA)
    png_set_gray_to_rgb(png);
  if (png_get_valid(png, info, PNG_INFO_tRNS)) png_set_tRNS_to_alpha(png);
  png_set_interlace_handling(png);
  png_read_update_info(png, info);

  out->w = static_cast<int>(png_get_image_width(png, info));
  out->h = static_cast<int>(png_get_image_height(png, info));
  out->ch = static_cast<int>(png_get_channels(png, info));
  if (out->ch != 3 && out->ch != 4) {
    png_destroy_read_struct(&png, &info, nullptr);
    std::fclose(fp);
    return kErrDecode;
  }
  out->px.resize(static_cast<size_t>(out->w) * out->h * out->ch);
  std::vector<png_bytep> rows(out->h);
  for (int y = 0; y < out->h; ++y)
    rows[y] = out->px.data() + static_cast<size_t>(y) * out->w * out->ch;
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  std::fclose(fp);
  return 0;
}

// --------------------------------------------------------------- resize
// Area-average (box filter) resize to dst x dst, writing float32 RGB HWC
// in [-1, 1].  RGBA sources use alpha-weighted (premultiplied) averaging
// — PIL's RGBA resize semantics — then drop alpha.
void box_resize_normalize(const Image& img, int dst, float* out) {
  const bool has_alpha = img.ch == 4;
  const int ch = img.ch;
  const double sx = static_cast<double>(img.w) / dst;
  const double sy = static_cast<double>(img.h) / dst;
  for (int oy = 0; oy < dst; ++oy) {
    const double y0 = oy * sy, y1 = (oy + 1) * sy;
    const int iy0 = static_cast<int>(y0);
    const int iy1 = std::min(static_cast<int>(std::ceil(y1)), img.h);
    for (int ox = 0; ox < dst; ++ox) {
      const double x0 = ox * sx, x1 = (ox + 1) * sx;
      const int ix0 = static_cast<int>(x0);
      const int ix1 = std::min(static_cast<int>(std::ceil(x1)), img.w);
      double acc[3] = {0, 0, 0}, wsum = 0;
      for (int iy = iy0; iy < iy1; ++iy) {
        const double wy =
            std::min<double>(y1, iy + 1) - std::max<double>(y0, iy);
        const uint8_t* row =
            img.px.data() + (static_cast<size_t>(iy) * img.w + ix0) * ch;
        for (int ix = ix0; ix < ix1; ++ix, row += ch) {
          const double wx =
              std::min<double>(x1, ix + 1) - std::max<double>(x0, ix);
          // alpha-weighted area weight (PIL premultiplied semantics)
          const double w = wx * wy * (has_alpha ? row[3] / 255.0 : 1.0);
          acc[0] += w * row[0];
          acc[1] += w * row[1];
          acc[2] += w * row[2];
          wsum += w;
        }
      }
      float* px = out + (static_cast<size_t>(oy) * dst + ox) * 3;
      for (int c = 0; c < 3; ++c) {
        const double v = wsum > 0 ? acc[c] / wsum : 0.0;
        px[c] = static_cast<float>(v / 255.0 * 2.0 - 1.0);
      }
    }
  }
}

int decode_one(const char* path, int size, float* out) {
  Image img;
  if (int err = decode_png_rgb(path, &img)) return err;
  box_resize_normalize(img, size, out);
  return 0;
}

// ------------------------------------------------------------- thread pool
class Pool {
 public:
  explicit Pool(int n_threads) {
    for (int i = 0; i < n_threads; ++i)
      workers_.emplace_back([this] { Run(); });
  }
  ~Pool() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : workers_) t.join();
  }

  // Decodes paths[0..n) into out (n * size*size*3 floats).  Returns the
  // first nonzero per-image error code, or 0.
  int DecodeBatch(const char** paths, int n, int size, float* out) {
    std::atomic<int> remaining(n), first_err(0);
    std::mutex done_mu;
    std::condition_variable done_cv;
    {
      std::unique_lock<std::mutex> lk(mu_);
      for (int i = 0; i < n; ++i) {
        const char* p = paths[i];
        float* dst = out + static_cast<size_t>(i) * size * size * 3;
        jobs_.push([p, size, dst, &remaining, &first_err, &done_mu,
                    &done_cv] {
          int err = decode_one(p, size, dst);
          if (err) {
            int expected = 0;
            first_err.compare_exchange_strong(expected, err);
          }
          // Decrement under done_mu: the caller holds it while checking
          // the predicate, so it cannot observe remaining==0 and destroy
          // the stack-allocated mutex/cv while this worker still uses them.
          {
            std::unique_lock<std::mutex> dlk(done_mu);
            if (remaining.fetch_sub(1) == 1) done_cv.notify_all();
          }
        });
      }
    }
    cv_.notify_all();
    std::unique_lock<std::mutex> dlk(done_mu);
    done_cv.wait(dlk, [&] { return remaining.load() == 0; });
    return first_err.load();
  }

 private:
  void Run() {
    for (;;) {
      std::function<void()> job;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return stop_ || !jobs_.empty(); });
        if (stop_ && jobs_.empty()) return;
        job = std::move(jobs_.front());
        jobs_.pop();
      }
      job();
    }
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<std::function<void()>> jobs_;
  std::vector<std::thread> workers_;
  bool stop_ = false;
};

}  // namespace

extern "C" {

int d3d_version() { return 1; }

// Single image: decode+resize+normalize into out[size*size*3].
int d3d_decode(const char* path, int size, float* out) {
  if (!path || size <= 0 || !out) return kErrArgs;
  return decode_one(path, size, out);
}

void* d3d_pool_create(int n_threads) {
  if (n_threads <= 0) n_threads = std::thread::hardware_concurrency();
  return new Pool(n_threads);
}

void d3d_pool_destroy(void* pool) { delete static_cast<Pool*>(pool); }

int d3d_pool_decode(void* pool, const char** paths, int n, int size,
                    float* out) {
  if (!pool || !paths || n <= 0 || size <= 0 || !out) return kErrArgs;
  return static_cast<Pool*>(pool)->DecodeBatch(paths, n, size, out);
}

}  // extern "C"
