"""Cross-cutting runtime services shared by training, serving and the
benchmark harness.

Today this is the fault-tolerance layer's retry shim
(:mod:`diff3d_tpu.runtime.retry`): one policy object for "how do we
classify and survive a transient backend/IO fault" so the trainer, the
serving engine and ``bench.py`` stop hand-rolling three divergent copies
of the same failure handling.
"""

from diff3d_tpu.runtime.retry import (BackendDialTimeout, RetryPolicy,
                                      RetryableError, acquire_backend,
                                      is_transient_backend_error,
                                      is_transient_io_error)

__all__ = [
    "BackendDialTimeout", "RetryPolicy", "RetryableError",
    "acquire_backend", "is_transient_backend_error",
    "is_transient_io_error",
]
