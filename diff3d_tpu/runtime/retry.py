"""Typed retry policy for transient backend and IO faults.

One policy object answers three questions the trainer, the serving
engine and ``bench.py`` used to answer independently (and differently):

* **Is this error worth retrying?**  Typed classification: anything
  deriving from :class:`RetryableError` is, a :class:`BackendDialTimeout`
  (the runtime *hung* rather than failed — re-dialing just hangs again)
  is not, and for everything else a small set of transport-level message
  markers ("UNAVAILABLE", "DEADLINE_EXCEEDED", ...) decides.
* **How long do we wait?**  Exponential backoff with a cap and
  deterministic seeded jitter, so chaos tests replay exactly and a fleet
  of preempted workers does not re-dial in lockstep.
* **What happened?**  ``call(..., attempts_log=...)`` records every
  failed attempt and its backoff so callers (bench's structured failure
  JSON, the checkpoint writer log) can report what the policy did.

This module deliberately imports no JAX at module scope — classifying
errors and sleeping must stay cheap and importable everywhere, including
before a backend exists.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Any, Callable, List, Optional

log = logging.getLogger(__name__)


class RetryableError(RuntimeError):
    """A fault the *caller* may safely retry.

    Raised (or subclassed) wherever the system rejects work for a
    transient reason: a failed/stuck engine step, degraded-mode
    admission control, a draining replica.  ``retry_after_s`` is an
    advisory wait; the HTTP layer maps it to a ``Retry-After`` header.
    """

    def __init__(self, msg: str, *, retry_after_s: Optional[float] = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class BackendDialTimeout(TimeoutError):
    """The accelerator runtime hung during initialization.

    Distinct from an ordinary dial *failure*: a hang past the alarm
    deadline means the runtime is wedged (dead dev tunnel, stuck
    coordinator) and re-dialing in-process tends to hang again, so the
    classifier treats this as non-retryable and callers fail fast with
    a structured record instead of burning the retry budget.
    """


#: Lower-cased substrings that mark an exception as a transient
#: transport/backend fault.  Sourced from gRPC status names plus the
#: failure strings seen in real bench rounds (RESULTS.md r04-r05).
_TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "connection reset",
    "connection refused",
    "socket closed",
    "broken pipe",
    "transport closed",
    "failed to connect",
    "temporarily",
)


def is_transient_backend_error(exc: BaseException) -> bool:
    """True if ``exc`` looks like a transient backend/transport fault."""
    if isinstance(exc, BackendDialTimeout):
        return False  # a hang, not a blip: fail fast
    if isinstance(exc, RetryableError):
        return True
    if isinstance(exc, ConnectionError):
        # Reset/refused/aborted against a worker socket: the transport
        # layer retries or the heartbeat declares the peer dead.
        return True
    msg = str(exc).lower()
    return any(marker in msg for marker in _TRANSIENT_MARKERS)


def is_transient_io_error(exc: BaseException) -> bool:
    """True if ``exc`` is a filesystem fault worth retrying.

    Checkpoint commits go to network filesystems in practice, where
    ``OSError`` is routinely transient.  Injected faults
    (:class:`RetryableError` subclasses) count so chaos tests exercise
    the same path.
    """
    return isinstance(exc, (OSError, RetryableError))


@dataclasses.dataclass
class RetryPolicy:
    """Bounded retry with capped exponential backoff and seeded jitter.

    ``classify`` decides retryability; a non-retryable error (or the
    final attempt's error) is re-raised as-is so callers keep their
    typed exceptions.  ``sleep`` is injectable so tests run at full
    speed, and jitter draws from ``random.Random(seed)`` per call so a
    given policy produces the same backoff sequence every time.
    """

    max_attempts: int = 3
    base_delay_s: float = 1.0
    max_delay_s: float = 60.0
    growth: float = 2.0         # 1.0 = constant backoff
    jitter: float = 0.25        # +/- fraction of the delay
    seed: int = 0
    classify: Callable[[BaseException], bool] = is_transient_backend_error
    sleep: Callable[[float], None] = time.sleep

    def delay_for(self, attempt: int, rng: random.Random) -> float:
        """Backoff after failed attempt number ``attempt`` (1-based)."""
        delay = min(self.max_delay_s,
                    self.base_delay_s * self.growth ** (attempt - 1))
        if self.jitter:
            delay *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, delay)

    def call(self, fn: Callable[[], Any], *,
             describe: str = "call",
             attempts_log: Optional[List[dict]] = None,
             on_retry: Optional[Callable[[int, BaseException, float], None]] = None) -> Any:
        """Run ``fn`` under this policy and return its result.

        Each failed-but-retried attempt appends
        ``{"attempt", "error", "backoff_s"}`` to ``attempts_log`` (if
        given) and invokes ``on_retry(attempt, exc, delay)`` before
        sleeping.  The last error is raised unchanged on exhaustion.
        """
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        rng = random.Random(self.seed)
        for attempt in range(1, self.max_attempts + 1):
            try:
                return fn()
            except Exception as exc:  # noqa: BLE001 - classifier decides
                try:
                    retryable = bool(self.classify(exc))
                except Exception:  # a broken classifier must not mask the fault
                    retryable = False
                if not retryable or attempt >= self.max_attempts:
                    raise
                delay = self.delay_for(attempt, rng)
                if attempts_log is not None:
                    attempts_log.append({
                        "attempt": attempt,
                        "error": str(exc).splitlines()[0][:200] if str(exc) else type(exc).__name__,
                        "backoff_s": round(delay, 4),
                    })
                log.warning("%s: attempt %d/%d failed (%s); retrying in %.2fs",
                            describe, attempt, self.max_attempts, exc, delay)
                if on_retry is not None:
                    on_retry(attempt, exc, delay)
                self.sleep(delay)
        raise AssertionError("unreachable")  # pragma: no cover


class RetryBudget:
    """Progress-aware failure budget for long-lived supervision loops.

    A plain ``RetryPolicy`` bounds *consecutive* attempts of one call; an
    elasticity loop instead needs "give up only after N failures *without
    forward progress*": a run that trains for an hour, gets preempted,
    re-meshes and trains on has earned a fresh budget, while a mesh that
    crashes at bring-up N times in a row is genuinely dead.

    ``spend()`` consumes one unit and returns True while budget remains;
    ``reset()`` refills it (call on observed progress, e.g. the step
    counter advanced past where the cycle started).  Not thread-safe —
    owned by a single supervisor loop.
    """

    def __init__(self, max_failures: int):
        if max_failures < 1:
            raise ValueError(f"max_failures must be >= 1, got {max_failures}")
        self.max_failures = max_failures
        self.spent = 0

    def spend(self) -> bool:
        """Consume one failure; True iff the budget is not yet exhausted."""
        self.spent += 1
        return self.spent < self.max_failures

    def reset(self) -> None:
        self.spent = 0

    @property
    def remaining(self) -> int:
        return max(0, self.max_failures - self.spent)


def acquire_backend(attempts: int = 6, wait_s: float = 75.0, *,
                    dial_timeout_s: int = 180,
                    attempts_log: Optional[List[dict]] = None,
                    on_retry: Optional[Callable[[int, BaseException, float], None]] = None):
    """Initialize the JAX backend, surviving transient dial failures.

    Each attempt runs under a SIGALRM deadline of ``dial_timeout_s``
    seconds: exceeding it raises :class:`BackendDialTimeout`, which is
    *not* retried (a hung runtime stays hung — callers should emit a
    structured failure and exit).  Any other dial error is retried up to
    ``attempts`` times with constant ``wait_s`` backoff, clearing the
    partially-initialized backend between attempts.

    Returns ``jax.devices()`` on success.
    """
    import signal

    import jax

    def _dial():
        def _on_alarm(signum, frame):
            raise BackendDialTimeout(
                f"jax backend initialization exceeded {dial_timeout_s}s")

        prev = signal.signal(signal.SIGALRM, _on_alarm)
        signal.alarm(dial_timeout_s)
        try:
            return jax.devices()
        finally:
            signal.alarm(0)
            signal.signal(signal.SIGALRM, prev)

    def _reset_and_notify(attempt: int, exc: BaseException, delay: float):
        # Drop the poisoned registry state so the next jax.devices()
        # re-dials the backend instead of returning the cached failure
        # (private API; guarded so an API move degrades to plain retry).
        # ONLY when no client was ever constructed: a cached *failed*
        # initialization is the one state a clear helps with, and the one
        # state it is safe in.  Tearing down a live client is a native
        # use-after-free — buffers, compiled-executable caches and
        # jax-internal globals keep raw references to it, and the freed
        # heap chunks get rewritten by the next dial (observed as
        # ``cpu_client.cc CHECK`` failures / malloc aborts in whatever
        # large computation runs next).  If a client exists, the dial
        # error was transient and plain retry suffices.
        try:
            from jax._src import xla_bridge
            if not xla_bridge._backends:
                xla_bridge._clear_backends()
        except Exception:  # pragma: no cover - best effort
            pass
        if on_retry is not None:
            on_retry(attempt, exc, delay)

    policy = RetryPolicy(
        max_attempts=attempts,
        base_delay_s=wait_s,
        max_delay_s=max(wait_s, 1e-9),
        growth=1.0,  # constant: the TPU runtime needs a fixed settle time
        jitter=0.0,
        classify=lambda exc: not isinstance(exc, BackendDialTimeout),
    )
    return policy.call(_dial, describe="backend dial",
                       attempts_log=attempts_log, on_retry=_reset_and_notify)
