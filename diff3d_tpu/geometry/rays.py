"""Pinhole-camera ray generation, pure jnp, runs on device inside jit.

The reference computes per-pixel rays with ``visu3d`` **on CPU numpy inside
the hot forward path** (``/root/reference/xunet.py:311-318``) — a
device→host→device round-trip per training step.  Here the same geometry is
~10 lines of jnp that XLA fuses straight into the conditioning convs.

Conventions (matching visu3d's ``v3d.Camera(spec, world_from_cam).rays()``):
  * pixel centers at half-integer coordinates: pixel (row i, col j) maps to
    ``(u, v) = (j + 0.5, i + 0.5)`` with u along width;
  * camera-space direction ``K^-1 @ [u, v, 1]``;
  * world direction ``R @ dir_cam``, L2-normalised;
  * ray origin = camera position = ``t`` (broadcast per pixel).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp


def pinhole_rays_cam(K: jnp.ndarray, H: int, W: int,
                     dtype: Optional[jnp.dtype] = None) -> jnp.ndarray:
    """Camera-space ray directions ``K^-1 @ [u, v, 1]`` per pixel.

    This half of :func:`pinhole_rays` depends only on the intrinsics —
    per diffusion trajectory they are loop constants, so the sampler's
    scan (``diffusion/core.py::sample_loop_scan``) hoists this stage out
    of the per-step body (the K_inv·px contraction is the MC404-pinned
    loop-invariant work).  Returns ``[..., H, W, 3]``.
    """
    if dtype is None:
        dtype = K.dtype
    u = jnp.arange(W, dtype=dtype) + 0.5
    v = jnp.arange(H, dtype=dtype) + 0.5
    uu, vv = jnp.meshgrid(u, v)            # each [H, W]
    px = jnp.stack([uu, vv, jnp.ones_like(uu)], axis=-1)     # [H, W, 3]

    K_inv = jnp.linalg.inv(K)                                # [..., 3, 3]
    # dir_cam[..., h, w, i] = K_inv[..., i, j] @ px[h, w, j]
    return jnp.einsum("...ij,hwj->...hwi", K_inv, px)


def pinhole_rays_world(R: jnp.ndarray, t: jnp.ndarray,
                       dir_cam: jnp.ndarray, normalize: bool = True
                       ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Pose-dependent half of :func:`pinhole_rays`: rotate camera-space
    directions into the world frame and broadcast ray origins."""
    dir_world = jnp.einsum("...ij,...hwj->...hwi", R, dir_cam)
    if normalize:
        dir_world = dir_world / jnp.linalg.norm(dir_world, axis=-1, keepdims=True)

    pos = jnp.broadcast_to(t[..., None, None, :], dir_world.shape)
    return pos, dir_world


def pinhole_rays(R: jnp.ndarray, t: jnp.ndarray, K: jnp.ndarray,
                 H: int, W: int, normalize: bool = True
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-pixel ray origins and directions for pinhole cameras.

    Args:
      R: ``[..., 3, 3]`` world-from-camera rotations.
      t: ``[..., 3]`` camera positions in world frame.
      K: ``[..., 3, 3]`` intrinsics (broadcastable against R's batch dims).
      H, W: image resolution.
    Returns:
      ``(pos, dir)``, each ``[..., H, W, 3]`` — parity with the reference's
      ``rays.pos`` / ``rays.dir`` (``xunet.py:317-318``).

    Composes :func:`pinhole_rays_cam` and :func:`pinhole_rays_world`
    bit-identically to the original single-stage form.
    """
    dir_cam = pinhole_rays_cam(K, H, W, dtype=R.dtype)
    return pinhole_rays_world(R, t, dir_cam, normalize=normalize)
