from diff3d_tpu.geometry.posenc import posenc_ddpm, posenc_nerf
from diff3d_tpu.geometry.rays import (pinhole_rays, pinhole_rays_cam,
                                      pinhole_rays_world)

__all__ = ["posenc_ddpm", "posenc_nerf", "pinhole_rays",
           "pinhole_rays_cam", "pinhole_rays_world"]
