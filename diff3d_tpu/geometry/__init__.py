from diff3d_tpu.geometry.posenc import posenc_ddpm, posenc_nerf
from diff3d_tpu.geometry.rays import pinhole_rays

__all__ = ["posenc_ddpm", "posenc_nerf", "pinhole_rays"]
