"""Positional encodings, pure jnp.

Parity targets: ``posenc_ddpm`` (reference ``xunet.py:32-46``) and
``posenc_nerf`` (reference ``xunet.py:49-59``).  Both are shape-polymorphic
over leading dimensions here (the reference hardcodes the ``b f h w c``
layout in an einops string).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def posenc_ddpm(timesteps: jnp.ndarray, emb_ch: int, max_time: float = 1000.0,
                dtype=jnp.float32) -> jnp.ndarray:
    """DDPM sinusoidal embedding of noise levels.

    Matches reference ``xunet.py:32-46``: input scaled by ``1000/max_time``
    (the model calls it with ``max_time=1.`` on raw logsnr values,
    ``xunet.py:307``), frequencies ``exp(-arange(half) * ln(10000)/(half-1))``,
    output ``concat([sin, cos], -1)`` of width ``emb_ch``.

    Args:
      timesteps: ``[...]`` float array.
      emb_ch: embedding width (must be even).
    Returns:
      ``[..., emb_ch]``.
    """
    timesteps = jnp.asarray(timesteps, dtype) * (1000.0 / max_time)
    half_dim = emb_ch // 2
    freq = np.exp(np.arange(half_dim) * -(np.log(10000.0) / (half_dim - 1)))
    emb = timesteps[..., None] * jnp.asarray(freq, dtype)
    return jnp.concatenate([jnp.sin(emb), jnp.cos(emb)], axis=-1)


def posenc_nerf(x: jnp.ndarray, min_deg: int = 0, max_deg: int = 15) -> jnp.ndarray:
    """NeRF positional encoding, concatenated with the input.

    Matches reference ``xunet.py:49-59``: ``xb[..., i, c] = x[..., c] * 2**i``
    flattened scale-major, then ``sin(concat([xb, xb + pi/2]))`` appended to
    ``x``.  Output channels: ``C + 2*C*(max_deg - min_deg)``.
    """
    if min_deg == max_deg:
        return x
    scales = jnp.asarray([2.0 ** i for i in range(min_deg, max_deg)], x.dtype)
    # [..., D, C] -> [..., D*C] (scale-major, matching the reference's
    # einops "(c d)" flatten where its `c` is the scale axis).
    xb = x[..., None, :] * scales[:, None]
    xb = xb.reshape(*x.shape[:-1], -1)
    emb = jnp.sin(jnp.concatenate([xb, xb + jnp.pi / 2.0], axis=-1))
    return jnp.concatenate([x, emb], axis=-1)


def posenc_nerf_channels(min_deg: int, max_deg: int, base: int = 3) -> int:
    """Output channel count of :func:`posenc_nerf` for a ``base``-dim input."""
    if min_deg == max_deg:
        return base
    return base + 2 * base * (max_deg - min_deg)
