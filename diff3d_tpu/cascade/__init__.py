from diff3d_tpu.cascade.plan import CascadePlan, PhaseSpec
from diff3d_tpu.cascade.sampler import CascadeSampler, upsample_draft
from diff3d_tpu.cascade.request import CascadeRequest

__all__ = [
    "CascadePlan",
    "CascadeRequest",
    "CascadeSampler",
    "PhaseSpec",
    "upsample_draft",
]
