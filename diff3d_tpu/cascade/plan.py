"""Cascade plans: what runs at which resolution, and where refinement
starts.

A cascade serves one object twice (DESIGN.md §20): a cheap low-resolution
*draft* pass (typically the distilled student, few DDIM steps) whose
frames stream to the client immediately, and a truncated high-resolution
*refine* pass that upsamples each draft, renoises it to ``start_t`` via
the forward process, and runs only the remaining reverse steps.  The plan
is the static description of that pair — everything the serving layer
needs to build both compiled programs before any request arrives.

The CLI grammar (``serve_cli --cascade``) is
``draft=64:ddim:8,refine=128:ancestral:64@t0.4`` — per phase
``resolution:sampler:steps``, the refine phase carrying its truncation
point as ``@t<start_t>``.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from diff3d_tpu.diffusion import SAMPLER_KINDS


@dataclasses.dataclass(frozen=True)
class PhaseSpec:
    """One cascade phase: ``resolution`` (square H=W), the reverse-process
    ``sampler_kind``/``steps`` schedule, and — refine phase only — the
    ``start_t`` grid point truncation begins at."""

    resolution: int
    sampler_kind: str
    steps: int
    start_t: Optional[float] = None

    def __post_init__(self):
        if self.resolution < 1:
            raise ValueError(f"resolution={self.resolution} must be >= 1")
        if self.sampler_kind not in SAMPLER_KINDS:
            raise ValueError(
                f"sampler_kind={self.sampler_kind!r} not in "
                f"{SAMPLER_KINDS}")
        if self.steps < 1:
            raise ValueError(f"steps={self.steps} must be >= 1")

    def spec(self) -> str:
        """The CLI form, e.g. ``"128:ancestral:64@t0.4"``."""
        s = f"{self.resolution}:{self.sampler_kind}:{self.steps}"
        if self.start_t is not None:
            s += f"@t{self.start_t:g}"
        return s

    @classmethod
    def parse(cls, text: str) -> "PhaseSpec":
        body, _, trunc = text.partition("@")
        parts = body.split(":")
        if len(parts) != 3:
            raise ValueError(
                f"phase spec {text!r}: expected "
                "'<resolution>:<sampler>:<steps>[@t<start_t>]'")
        start_t = None
        if trunc:
            if not trunc.startswith("t"):
                raise ValueError(
                    f"phase spec {text!r}: truncation suffix must be "
                    "'@t<start_t>' (e.g. '@t0.4')")
            start_t = float(trunc[1:])
        try:
            resolution, steps = int(parts[0]), int(parts[2])
        except ValueError:
            raise ValueError(
                f"phase spec {text!r}: resolution and steps must be "
                "integers") from None
        return cls(resolution=resolution, sampler_kind=parts[1],
                   steps=steps, start_t=start_t)


@dataclasses.dataclass(frozen=True)
class CascadePlan:
    """The draft → upsample → refine pair.

    Invariants enforced here (not per-phase): the draft never truncates
    (it starts from pure noise — there is nothing upstream of it), the
    refine phase always does (``start_t`` is what makes it a refinement
    rather than a second full pass), and refinement runs at a strictly
    higher resolution than the draft it consumes.
    """

    draft: PhaseSpec
    refine: PhaseSpec

    def __post_init__(self):
        if self.draft.start_t is not None:
            raise ValueError(
                f"draft phase {self.draft.spec()!r} must not carry a "
                "start_t — drafts start from pure noise")
        if self.refine.start_t is None:
            raise ValueError(
                f"refine phase {self.refine.spec()!r} needs a start_t "
                "truncation point ('@t<start_t>')")
        if self.refine.resolution <= self.draft.resolution:
            raise ValueError(
                f"refine resolution {self.refine.resolution} must exceed "
                f"the draft's {self.draft.resolution}")

    def spec(self) -> str:
        return f"draft={self.draft.spec()},refine={self.refine.spec()}"

    @classmethod
    def parse(cls, text: str) -> "CascadePlan":
        """Parse ``draft=64:ddim:8,refine=128:ancestral:64@t0.4``."""
        phases = {}
        for item in text.split(","):
            name, eq, spec = item.partition("=")
            if not eq or name not in ("draft", "refine"):
                raise ValueError(
                    f"cascade plan item {item!r}: expected "
                    "'draft=<spec>' or 'refine=<spec>'")
            if name in phases:
                raise ValueError(f"cascade plan {text!r} repeats {name!r}")
            phases[name] = PhaseSpec.parse(spec)
        missing = {"draft", "refine"} - phases.keys()
        if missing:
            raise ValueError(
                f"cascade plan {text!r} is missing {sorted(missing)}")
        return cls(draft=phases["draft"], refine=phases["refine"])
