"""The cascade sampler: a 64² student draft pass feeding a truncated 128²
refinement pass.

Both phases run through the ordinary :class:`~diff3d_tpu.sampling.Sampler`
— the draft is a plain few-step sampler at the low resolution (its params
default to the refine params resolution-adapted via
``convert/progressive.py``; a distilled student checkpoint can be passed
instead), and the refine phase is a ``start_t``-truncated sampler whose
per-view ``draft`` operand is the upsampled draft view renoised inside
the compiled scan.  So every mesh/sharding/donation property of the
single-pass path carries over unchanged, and the cascade programs are
lowered and audited by the same four analysis pillars
(``step_many_cascade_draft`` / ``step_many_cascade_refine``).

RNG across phases: one parent key splits into independent draft and
refine streams (``split(rng)``), each then threaded per view exactly like
the single-pass sampler — the refine stream is the one that must match
the single-pass oracle under truncation-at-t=1.0 (the bit-parity
acceptance test), so it never depends on how many draws the draft made.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.cascade.plan import CascadePlan
from diff3d_tpu.config import Config
from diff3d_tpu.convert.progressive import adapt_params_resolution
from diff3d_tpu.models import XUNet
from diff3d_tpu.sampling import Sampler


def upsample_draft(draft, dst_hw: Tuple[int, int]):
    """Bilinearly upsample ``[..., h, w, 3]`` draft images to ``dst_hw``
    — the same interpolation ``convert/progressive.py`` uses for the
    positional embedding, so the draft the refine pass renoises is
    spatially aligned with the prior the 128² model learned."""
    draft = jnp.asarray(draft)
    shape = draft.shape[:-3] + (dst_hw[0], dst_hw[1], draft.shape[-1])
    return jax.image.resize(draft, shape, method="bilinear")


def downsample_views(views: Dict[str, np.ndarray],
                     resolution: int) -> Dict[str, np.ndarray]:
    """An ``all_views``-style dict resized to ``resolution``² for the
    draft phase: images area-matched via bilinear resize, intrinsics
    rescaled (fx/fy/cx/cy rows scale with the image), poses unchanged."""
    imgs = np.asarray(views["imgs"], np.float32)
    H = imgs.shape[1]
    scale = resolution / H
    out = dict(views)
    out["imgs"] = np.asarray(jax.image.resize(
        imgs, (imgs.shape[0], resolution, resolution, imgs.shape[-1]),
        method="bilinear"))
    K = np.array(views["K"], np.float32)
    K[:2] *= scale
    out["K"] = K
    return out


class CascadeSampler:
    """Runs the two-phase cascade for one object.

    Args:
      model / params / cfg: the refine-resolution (served) model — the
        same triple a single-pass :class:`Sampler` takes; ``cfg.model``
        must match ``plan.refine.resolution``.
      plan: the :class:`CascadePlan`.
      mesh: optional MeshEnv, shared by both phases.
      draft_params: optional distilled-student params at the draft
        resolution; ``None`` resolution-adapts the refine params
        (``convert/progressive.py`` — everything but ``pos_emb`` is
        resolution-independent).
    """

    def __init__(self, model: XUNet, params, cfg: Config,
                 plan: CascadePlan, *, mesh=None, draft_params=None):
        if (cfg.model.H, cfg.model.W) != (plan.refine.resolution,) * 2:
            raise ValueError(
                f"cfg.model is {cfg.model.H}x{cfg.model.W} but the plan "
                f"refines at {plan.refine.resolution}² — the served "
                "model IS the refine phase")
        self.cfg = cfg
        self.plan = plan
        dr = plan.draft.resolution
        self.draft_cfg = dataclasses.replace(
            cfg, model=dataclasses.replace(cfg.model, H=dr, W=dr))
        if draft_params is None:
            draft_params = adapt_params_resolution(params, (dr, dr))
        self.draft = Sampler(
            XUNet(self.draft_cfg.model), draft_params, self.draft_cfg,
            mesh=mesh, sampler_kind=plan.draft.sampler_kind,
            steps=plan.draft.steps)
        self.refine = Sampler(
            model, params, cfg, mesh=mesh,
            sampler_kind=plan.refine.sampler_kind,
            steps=plan.refine.steps, start_t=plan.refine.start_t)

    @property
    def model_calls_per_view(self) -> int:
        """Draft + refine denoiser invocations per view (the refine
        sampler already subtracts its truncated steps)."""
        return (self.draft.model_calls_per_view
                + self.refine.model_calls_per_view)

    def upsample(self, drafts):
        """Draft views → refine resolution (see :func:`upsample_draft`)."""
        return upsample_draft(drafts, (self.cfg.model.H, self.cfg.model.W))

    def synthesize_draft(self, views: Dict[str, np.ndarray],
                         rng: jax.Array,
                         max_views: Optional[int] = None) -> np.ndarray:
        """The draft pass: downsample the conditioning views and run the
        student.  Returns ``[n_views-1, B, dr, dr, 3]``."""
        return self.draft.synthesize(
            downsample_views(views, self.plan.draft.resolution), rng,
            max_views=max_views)

    def refine_views(self, views: Dict[str, np.ndarray],
                     drafts: Sequence[np.ndarray], rng: jax.Array,
                     max_views: Optional[int] = None) -> np.ndarray:
        """The refine pass: autoregressively re-synthesise views
        ``1..n_views-1`` at full resolution, each view's reverse scan
        entered at ``start_t`` from its (upsampled) draft.

        ``drafts`` is ``[n_views-1, B, h, w, 3]`` at either resolution
        (upsampled here if needed).  The record/RNG contract is exactly
        :meth:`Sampler.synthesize`'s — same per-view key stream, the
        record conditioning on *refined* outputs — so at
        ``start_t = 1.0`` this is bit-identical to the single-pass
        sampler given the same ``rng``.
        """
        imgs = np.asarray(views["imgs"], np.float32)
        R = np.asarray(views["R"], np.float32)
        T = np.asarray(views["T"], np.float32)
        K = np.asarray(views["K"], np.float32)
        n_views = imgs.shape[0] if max_views is None else min(
            imgs.shape[0], max_views)
        B = int(self.refine.w.shape[0])
        H, W = self.cfg.model.H, self.cfg.model.W
        if n_views < 2:
            return np.zeros((0, B, H, W, 3), np.float32)
        if len(drafts) < n_views - 1:
            raise ValueError(
                f"{len(drafts)} drafts for {n_views - 1} refined views")
        drafts_up = np.asarray(self.upsample(np.asarray(drafts)),
                               np.float32)

        record_imgs, record_R, record_T = self.refine._record_init(
            imgs[0], R, T, n_views)
        rec_i, step_d, rng_d = record_imgs, 1, np.asarray(rng)
        for v in range(1, n_views):
            _, rec_i, step_d, rng_d = self.refine.step(
                rec_i, record_R, record_T, step_d, K, rng_d,
                draft=drafts_up[v - 1])
        return np.asarray(jax.block_until_ready(rec_i[1:n_views]))

    def synthesize_cascade(self, views: Dict[str, np.ndarray],
                           rng: jax.Array,
                           max_views: Optional[int] = None) -> dict:
        """The full draft → upsample → refine pipeline for one object.

        Returns ``{"draft": [V, B, dr, dr, 3],
        "refined": [V, B, H, W, 3]}`` (V = n_views - 1).  The parent key
        splits once into the two phase streams.
        """
        k_draft, k_refine = jax.random.split(jnp.asarray(rng))
        drafts = self.synthesize_draft(views, k_draft, max_views=max_views)
        refined = self.refine_views(views, drafts, k_refine,
                                    max_views=max_views)
        return {"draft": drafts, "refined": refined}
