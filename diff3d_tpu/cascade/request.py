"""The progressive-preview request: one client job, two engine passes.

A :class:`CascadeRequest` is what the client submits (full-resolution
views payload, exactly like a plain :class:`ViewRequest`).  It never
queues itself; the engine's ``submit_cascade`` derives two
:class:`_PhaseRequest` children from it — a draft-resolution child first,
then (once every draft view resolved) a refine child carrying the
upsampled drafts — and chains them, so each child co-batches with plain
views through the ordinary scheduler/engine path under its own
``(resolution, phase)`` bucket.

What the parent adds over a trajectory request is the *phase-tagged
event buffer*: every committed frame from either child lands here as
``{"phase", "view", "frame"}`` in commit order, served through the same
``?from=K`` cursor / NDJSON streaming surface as PR 13's trajectories.
Draft events for view k arrive first (preview), the refine event for
view k later replaces it in place client-side.  A finished cascade has
exactly ``2 * (n_views - 1)`` events.

RNG across phases mirrors :meth:`CascadeSampler.synthesize_cascade`:
``PRNGKey(seed)`` splits once into the draft and refine streams, so the
refined output is deterministic under a pinned seed and independent of
the draft phase's draw count.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

import jax
import numpy as np

from diff3d_tpu.cascade.plan import CascadePlan
from diff3d_tpu.cascade.sampler import downsample_views, upsample_draft
from diff3d_tpu.serving.scheduler import ViewRequest


class CascadeRequest(ViewRequest):
    """A progressive-preview synthesis job (see module docstring).

    Constructed at the *refine* (served-model) resolution; the plan's
    refine phase must match the payload's H/W.  The request resolves
    with the refined result ``[n_views-1, B, H, W, 3]``; draft frames
    are preview-only and reachable exclusively through the event
    surface.
    """

    def __init__(self, views: dict, plan: CascadePlan, **kwargs):
        kwargs.setdefault("sampler_kind", plan.refine.sampler_kind)
        kwargs.setdefault("steps", plan.refine.steps)
        super().__init__(views, **kwargs)
        H, W = self._HW
        if (H, W) != (plan.refine.resolution,) * 2:
            raise ValueError(
                f"cascade payload is {H}x{W} but the plan refines at "
                f"{plan.refine.resolution}² — submit at the refine "
                "resolution")
        self.plan = plan
        # The full views dict is kept (plain ViewRequest only keeps
        # imgs0): the draft child re-derives its downsampled payload
        # from it.
        self._views = {
            "imgs": np.asarray(views["imgs"], np.float32)[:1],
            "R": self.R, "T": self.T, "K": self.K,
        }
        self._events_lock = threading.Lock()
        self._events_cv = threading.Condition(self._events_lock)
        # Phase-tagged frame events, append-only in commit order.
        self._events: List[dict] = []  # guarded-by: self._events_lock
        self._children: List[ViewRequest] = []  # guarded-by: self._events_lock
        self.first_draft_time: Optional[float] = None
        self.first_refined_time: Optional[float] = None

    @property
    def is_cascade(self) -> bool:
        return True

    @property
    def n_frames(self) -> int:
        """Frames per phase (views past the conditioning one); the event
        buffer holds two of each, one per phase."""
        return self.n_views - 1

    @property
    def n_events(self) -> int:
        return 2 * (self.n_views - 1)

    # -- event surface (the ?from=K cursor) -----------------------------

    def _cascade_event(self, phase: str, view_index: int,
                       frame: np.ndarray) -> None:
        """Child commit hook: append one phase-tagged frame event."""
        with self._events_cv:
            if phase == "draft" and self.first_draft_time is None:
                self.first_draft_time = time.monotonic()
            if phase == "refine" and self.first_refined_time is None:
                self.first_refined_time = time.monotonic()
            self._events.append(
                {"phase": phase, "view": int(view_index), "frame": frame})
            self._events_cv.notify_all()

    def events_done(self) -> int:
        with self._events_lock:
            return len(self._events)

    def events_since(self, start: int = 0) -> List[dict]:
        """Committed events ``start..`` (non-blocking snapshot)."""
        with self._events_lock:
            return list(self._events[max(0, int(start)):])

    def wait_events(self, start: int,
                    timeout: Optional[float] = None) -> List[dict]:
        """Block until at least one event past ``start`` exists (or the
        request resolves), then return events ``start..`` — same
        contract as ``TrajectoryRequest.wait_frames``."""
        start = max(0, int(start))
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._events_cv:
            while len(self._events) <= start and not self._event.is_set():
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                self._events_cv.wait(remaining)
            got = list(self._events[start:])
        if not got and self._event.is_set():
            err = self.error
            if err is not None:
                raise err
        return got

    # -- child derivation ------------------------------------------------

    def _phase_keys(self):
        k_draft, k_refine = jax.random.split(jax.random.PRNGKey(self.seed))
        return np.asarray(k_draft), np.asarray(k_refine)

    def make_draft_child(self,
                         on_resolve: Callable[[np.ndarray], None]
                         ) -> "_PhaseRequest":
        """The draft-resolution phase request (downsampled payload,
        rescaled intrinsics, ``phase="draft"`` bucket)."""
        views = downsample_views(self._views, self.plan.draft.resolution)
        child = _PhaseRequest(
            self, "draft", views, on_resolve,
            rng_key=self._phase_keys()[0],
            sampler_kind=self.plan.draft.sampler_kind,
            steps=self.plan.draft.steps)
        with self._events_lock:
            self._children.append(child)
        return child

    def make_refine_child(self, draft_result: np.ndarray
                          ) -> "_PhaseRequest":
        """The refine phase request: full-resolution payload plus the
        upsampled drafts the truncated scan renoises from.  Carries the
        parent's session id, so router affinity keeps refinement on the
        replica holding the session's 128² record."""
        child = _PhaseRequest(
            self, "refine", self._views, self._resolve,
            rng_key=self._phase_keys()[1],
            sampler_kind=self.plan.refine.sampler_kind,
            steps=self.plan.refine.steps)
        H, W = self._HW
        child.drafts = np.asarray(
            upsample_draft(np.asarray(draft_result, np.float32), (H, W)),
            np.float32)
        with self._events_lock:
            self._children.append(child)
        return child

    # -- terminal-state overrides ----------------------------------------

    def _resolve(self, result: np.ndarray) -> None:
        super()._resolve(result)
        with self._events_cv:
            # Backfill refine events on a short-circuit resolve (result
            # cache / direct resolve) so the cursor surface still
            # terminates at a full event set.
            seen = {e["view"] for e in self._events
                    if e["phase"] == "refine"}
            for k in range(1, result.shape[0] + 1):
                if k not in seen:
                    self._events.append({"phase": "refine", "view": k,
                                         "frame": result[k - 1]})
            self._events_cv.notify_all()

    def _reject(self, exc: BaseException) -> None:
        super()._reject(exc)
        with self._events_cv:
            children = list(self._children)
            self._events_cv.notify_all()
        for c in children:
            c.cancel()

    def cancel(self) -> bool:
        ok = super().cancel()
        if ok:
            with self._events_lock:
                children = list(self._children)
            for c in children:
                c.cancel()
        return ok


class _PhaseRequest(ViewRequest):
    """One phase of a cascade, shaped like an ordinary view request so it
    co-batches with plain views under its ``(resolution, phase)`` bucket.
    Relays frame commits to the parent's event buffer and its terminal
    state to ``on_resolve`` / the parent's reject."""

    def __init__(self, parent: CascadeRequest, phase: str, views: dict,
                 on_resolve: Callable[[np.ndarray], None], *,
                 rng_key: np.ndarray, **kwargs):
        super().__init__(
            views, seed=parent.seed, n_views=parent.n_views,
            timeout_s=parent.timeout_s,
            request_id=f"{parent.id}:{phase}",
            session_id=parent.session_id, **kwargs)
        self.parent = parent
        self.phase = phase
        self.bucket = self.bucket._replace(phase=phase)
        # The engine's slot seeds its carry from this key instead of
        # PRNGKey(seed): each phase runs its own split of the parent
        # stream (see the module docstring).
        self.rng_key = np.asarray(rng_key)
        self._on_resolve = on_resolve
        self.drafts: Optional[np.ndarray] = None  # refine phase only

    def content_key(self, params_version: str, extra: str = "") -> str:
        # A phase child must never collide with a plain request on the
        # same inputs — its output depends on the cascade plan (and, for
        # refine, on the draft it renoised from, itself a deterministic
        # function of seed + plan).
        tag = f"cascade:{self.phase}:{self.parent.plan.spec()}"
        return super().content_key(params_version,
                                   extra=f"{extra}|{tag}")

    def _commit_frame(self, view_index: int, frame: np.ndarray) -> None:
        self.parent._cascade_event(self.phase, view_index, frame)

    def _resolve(self, result: np.ndarray) -> None:
        super()._resolve(result)
        try:
            self._on_resolve(result)
        except BaseException as e:  # chain failure -> parent terminal
            self.parent._reject(e)

    def _reject(self, exc: BaseException) -> None:
        super()._reject(exc)
        self.parent._reject(exc)
