"""Typed configuration for the whole framework.

The reference scatters its configuration across hardcoded constants
(``/root/reference/train.py:210-217``), argparse flags
(``/root/reference/lightning/train.py:19-28``) and class-attribute defaults
overridden via ``self.__dict__.update(kwargs)``
(``/root/reference/xunet.py:356-369``).  Here everything lives in one place as
frozen dataclasses, including the paper config documented in the reference
docstring (``/root/reference/lightning/diff3d.py:11-20``): peak lr 1e-4 with
linear warmup over the first 10M examples, global batch 128, cond_prob 0.1,
Adam betas (0.9, 0.99), EMA half-life 500K examples.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """X-UNet hyperparameters (reference ``xunet.py:355-366``).

    ``attn_levels`` are *depth levels* (0..num_resolutions), not pixel
    resolutions — same semantics as the reference's ``attn_resolutions``.
    """

    H: int = 128
    W: int = 128
    ch: int = 256
    ch_mult: Sequence[int] = (1, 2, 2, 4)
    emb_ch: int = 1024
    num_res_blocks: int = 3
    attn_levels: Sequence[int] = (2, 3, 4)
    attn_heads: int = 4
    dropout: float = 0.1
    use_pos_emb: bool = True
    use_ref_pose_emb: bool = True
    # Noise-level embedding clip bound; keep equal to
    # DiffusionConfig.logsnr_max (reference hardcodes 20, xunet.py:305).
    logsnr_clip: float = 20.0
    # TPU-first additions (no reference counterpart):
    dtype: str = "bfloat16"        # compute dtype; params stay float32
    remat: bool = False            # jax.checkpoint each UNet block
    # What each rematted block keeps: 'nothing' recomputes everything in
    # the backward (min memory); 'dots' saves matmul/conv outputs and
    # recomputes only cheap elementwise ops (less recompute, more HBM).
    remat_policy: str = "nothing"  # 'nothing' | 'dots'
    # 'auto' | 'pallas' | 'xla', or a sequence-parallel core
    # 'ring:<axis>' / 'ulysses:<axis>' for token-sharded attention inside
    # shard_map (long-context scaling; see ops/attention.py).
    attn_impl: str = "auto"
    # Optional per-resolution-level override of attn_impl (one entry per
    # ch_mult level; the middle block uses the last entry).  The 128^2
    # config's attention sites differ sharply by level — L=1024/D=128 at
    # level 2 vs L=256/D=256 at level 3 + middle — and the best engine
    # per site is a measured question (tools/profile128.py), not one a
    # single global attn_impl can answer.
    attn_impl_levels: Optional[Sequence[str]] = None
    # Kernel backend for the fused GroupNorm->FiLM/SiLU epilogues
    # (ops/pallas_film.py via ops/dispatch.py): 'xla' (default) keeps the
    # plain composition — bit-identical graphs to pre-kernel-layer
    # checkpoints; 'pallas' forces the fused kernels (interpret mode
    # off-TPU, so CPU tests exercise the TPU tile program); 'auto' uses
    # pallas only on a TPU-default-backend process.  CLI: --pallas.
    kernels: str = "xla"

    @property
    def num_resolutions(self) -> int:
        return len(self.ch_mult)

    def attn_impl_at(self, i_level: int) -> str:
        """Attention engine for UNet level ``i_level`` (middle block =
        deepest level)."""
        if self.attn_impl_levels is None:
            return self.attn_impl
        return self.attn_impl_levels[min(i_level,
                                         len(self.attn_impl_levels) - 1)]

    def validate(self) -> None:
        down = 2 ** (len(self.ch_mult) - 1)
        if self.H % down or self.W % down:
            raise ValueError(
                f"H={self.H}, W={self.W} must be divisible by {down} "
                f"(len(ch_mult)-1 downsamplings)"
            )
        if self.remat_policy not in ("nothing", "dots"):
            raise ValueError(
                f"remat_policy={self.remat_policy!r} not in "
                "('nothing', 'dots')")
        def _impl_ok(impl: str) -> bool:
            return (impl in ("auto", "pallas", "xla")
                    or (impl.partition(":")[0] in ("ring", "ulysses")
                        and bool(impl.partition(":")[2])))

        if self.kernels not in ("auto", "pallas", "xla"):
            raise ValueError(
                f"kernels={self.kernels!r} not in ('auto', 'pallas', "
                "'xla')")
        if not _impl_ok(self.attn_impl):
            raise ValueError(
                f"attn_impl={self.attn_impl!r}: expected 'auto', 'pallas', "
                "'xla', 'ring:<axis>' or 'ulysses:<axis>'")
        if self.attn_impl_levels is not None:
            if len(self.attn_impl_levels) != self.num_resolutions:
                raise ValueError(
                    f"attn_impl_levels needs {self.num_resolutions} "
                    f"entries (one per ch_mult level), got "
                    f"{len(self.attn_impl_levels)}")
            for impl in self.attn_impl_levels:
                if not _impl_ok(impl):
                    raise ValueError(
                        f"attn_impl_levels entry {impl!r} invalid")


@dataclasses.dataclass(frozen=True)
class DiffusionConfig:
    """Continuous-time logSNR-parameterised VP diffusion (reference
    ``train.py:30-177``)."""

    logsnr_min: float = -20.0
    logsnr_max: float = 20.0
    cond_prob: float = 0.1           # CFG dropout prob (train.py:80)
    loss_type: str = "l2"            # 'l1' | 'l2' | 'huber'
    timesteps: int = 256             # sampler steps (sampling.py:130)
    guidance_weights: Sequence[float] = (0, 1, 2, 3, 4, 5, 6, 7)
    clip_x0: bool = True             # clamp z_start to [-1,1] (train.py:160)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Trainer settings (reference ``train.py:210-217,235,267`` +
    paper config ``lightning/diff3d.py:11-20``)."""

    lr: float = 1e-4
    betas: Sequence[float] = (0.9, 0.99)
    warmup_examples: int = 10_000_000   # linear warmup over examples
    global_batch: int = 128
    max_steps: int = 100_000
    ckpt_every: int = 50
    log_every: int = 50
    ema_halflife_examples: int = 500_000
    # Gradient accumulation: each optimizer step scans over `accum_steps`
    # microbatches of global_batch/accum_steps examples, averaging grads.
    # Lets the reference's batch-128 config train on HBM that only holds
    # batch-64 activations (no reference counterpart; their answer to OOM
    # was "use a smaller image size", README.md:39).
    accum_steps: int = 1
    # Validation-loss cadence (0 disables).  The reference's own TODO #1
    # ("Assessing the behavior of the loss along training", README.md:32)
    # — it never had a val path; here attach Trainer.val_loader and the
    # EMA params are scored on held-out batches every `eval_every` steps.
    eval_every: int = 0
    seed: int = 0
    checkpoint_dir: str = "checkpoints"
    keep_checkpoints: int = 3
    # "full" = whole TrainState (exact resume); "ema_bf16" = bf16 EMA
    # params only, ~1/16 the bytes — for checkpointing full-width models
    # over constrained device->host links (see train/checkpoint.py).
    # None follows an existing directory marker (resume keeps whatever
    # mode the run started with), defaulting to "full" on fresh dirs.
    ckpt_mode: Optional[str] = None
    # full_sliced only: snapshot device->host on the training thread,
    # commit files from a background writer (retry + backoff + atomic
    # rename), so a slow filesystem no longer stalls the step loop.  The
    # preemption path still waits on the durability barrier before
    # exiting.  False = fully synchronous saves (the parity oracle).
    ckpt_async: bool = True
    grad_clip: float = 0.0            # 0 disables (reference has none)


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """SRN dataset settings (reference ``SRNdataset.py:42-95``)."""

    path: str = "./data/SRN/cars_train"
    picklefile: str = "./data/cars.pickle"
    imgsize: int = 64
    split_seed: int = 0               # random.seed(0) split (SRNdataset.py:52)
    train_fraction: float = 0.9
    num_views_per_sample: int = 2
    prefetch: int = 2                 # device prefetch depth


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout.  The reference's entire distributed surface is data
    parallelism over NCCL/gloo (``train.py:187,224-233``); here the mesh also
    reserves a model axis for tensor/fsdp sharding so scaling beyond DP is a
    config change, not a rewrite."""

    data_axis: str = "data"
    model_axis: str = "model"
    data_parallel: int = -1           # -1: all devices
    model_parallel: int = 1
    # 'replicated' keeps params/opt-state replicated like the reference's
    # DDP; 'fsdp' shards them over the data axis (ZeRO-ish); 'tp' applies
    # Megatron-style rules over the model axis (attention q/k/v column-,
    # out-proj row-parallel, conv output channels); 'fsdp+tp' composes
    # both (TP rule first, then the largest free axis over data).
    param_sharding: str = "replicated"
    # GSPMD context parallelism: shard the activations' spatial (image-row
    # = token) axis over the model axis via sharding constraints between
    # UNet blocks; XLA inserts conv halo exchanges, global GroupNorm
    # reductions, and attention KV gathers.  Activation memory per device
    # drops by the axis size — for resolutions past what one chip's HBM
    # holds.  (The shard_map alternative for the attention op alone is
    # ModelConfig.attn_impl='ring:<axis>'.)
    context_parallel: bool = False


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Batched novel-view inference service (``diff3d_tpu/serving``).

    The service shares the chip across concurrent requests by microbatching
    them into fixed-shape device batches (bucketed by image size and record
    capacity) and admitting new requests between view steps (continuous
    batching at view granularity).  No reference counterpart — the
    reference stops at a one-shot offline sampler (``sampling.py:169-184``).
    """

    host: str = "127.0.0.1"
    port: int = 8080
    # Backpressure: submissions beyond this many pending requests are
    # REJECTED (HTTP 429), never silently queued without bound.
    max_queue: int = 64
    # Device-batch lane ceiling per bucket; the engine pads the active set
    # up to the next power of two <= max_batch (logarithmic number of
    # compiled programs per bucket, same trick as the record capacity).
    # When the sampler rides a mesh, the engine additionally rounds lane
    # counts — and this ceiling itself — UP to a multiple of the mesh's
    # data-axis size (a sharded object axis must divide evenly; see
    # serving/engine.py lane_count).
    max_batch: int = 8
    # Microbatcher flush deadline: after the first request of a bucket
    # arrives, wait at most this long for co-batchable requests before
    # launching underfull.
    max_wait_ms: float = 50.0
    # Per-request wall-clock deadline (queue wait + compute); expired
    # requests get an explicit timeout error, not a hang.
    default_timeout_s: float = 300.0
    # LRU result cache entries keyed by request content hash (0 disables).
    result_cache_entries: int = 32
    # Per-request view-count ceiling (bounds record capacity / HBM).
    max_views: int = 16
    # ---- fault tolerance (serving/engine.py watchdog + health) ------
    # Stuck-step watchdog: a view-step dispatch older than this is
    # declared stuck — its in-flight requests are failed with a typed
    # retryable error and the engine degrades.  Generous by default
    # (srn128 runs ~107 s/view); 0 disables the watchdog.
    watchdog_timeout_s: float = 600.0
    # Attempts per view-step dispatch (1 = no retry) and the base
    # backoff between them.  Inputs are re-stacked host buffers, so a
    # re-dispatch after a transient backend fault is safe and bit-exact.
    step_retry_attempts: int = 2
    step_retry_backoff_s: float = 0.2
    # Consecutive clean steps required to leave `degraded` for `ok`.
    degraded_recovery_steps: int = 3
    # Advisory client wait carried on typed retryable rejections
    # (HTTP maps it to a Retry-After header).
    retry_after_s: float = 5.0
    # Watchdog respawns of a dead engine loop before giving up and
    # failing new submissions fast.
    engine_max_restarts: int = 3
    # ---- fleet (serving/router.py) ----------------------------------
    # In-process engine replicas behind the fleet router's front door
    # (1 = single-replica ServingService, no router).  Each replica owns
    # its own scheduler/engine/program cache; sessions pin to replicas.
    replicas: int = 1
    # ---- cross-process fleet (serving/transport.py, DESIGN.md §19) ---
    # RemoteReplica connection supervision: probe the worker every
    # `interval`; a worker silent past `timeout` is marked dead (its
    # sticky sessions get SessionLost, exactly like an in-process kill).
    heartbeat_interval_s: float = 0.25
    heartbeat_timeout_s: float = 3.0
    # Transport frame-size ceiling (a garbage length prefix must not
    # demand gigabytes of buffer).
    max_frame_bytes: int = 1 << 30

    def validate(self) -> None:
        if self.max_batch < 1:
            raise ValueError(f"max_batch={self.max_batch} must be >= 1")
        if self.max_queue < 1:
            raise ValueError(f"max_queue={self.max_queue} must be >= 1")
        if self.max_wait_ms < 0:
            raise ValueError(f"max_wait_ms={self.max_wait_ms} must be >= 0")
        if self.default_timeout_s <= 0:
            raise ValueError(
                f"default_timeout_s={self.default_timeout_s} must be > 0")
        if self.max_views < 2:
            raise ValueError(
                f"max_views={self.max_views} must be >= 2 (one "
                "conditioning view + one target)")
        if self.watchdog_timeout_s < 0:
            raise ValueError(
                f"watchdog_timeout_s={self.watchdog_timeout_s} must be "
                ">= 0 (0 disables)")
        if self.step_retry_attempts < 1:
            raise ValueError(
                f"step_retry_attempts={self.step_retry_attempts} must be "
                ">= 1 (1 = no retry)")
        if self.step_retry_backoff_s < 0:
            raise ValueError(
                f"step_retry_backoff_s={self.step_retry_backoff_s} must "
                "be >= 0")
        if self.degraded_recovery_steps < 1:
            raise ValueError(
                f"degraded_recovery_steps={self.degraded_recovery_steps} "
                "must be >= 1")
        if self.retry_after_s <= 0:
            raise ValueError(
                f"retry_after_s={self.retry_after_s} must be > 0")
        if self.engine_max_restarts < 0:
            raise ValueError(
                f"engine_max_restarts={self.engine_max_restarts} must be "
                ">= 0")
        if self.replicas < 1:
            raise ValueError(f"replicas={self.replicas} must be >= 1")
        if self.heartbeat_interval_s <= 0:
            raise ValueError(
                f"heartbeat_interval_s={self.heartbeat_interval_s} must "
                "be > 0")
        if self.heartbeat_timeout_s <= self.heartbeat_interval_s:
            raise ValueError(
                f"heartbeat_timeout_s={self.heartbeat_timeout_s} must "
                f"exceed heartbeat_interval_s={self.heartbeat_interval_s} "
                "(a single missed probe must not kill a replica)")
        if self.max_frame_bytes < (1 << 16):
            raise ValueError(
                f"max_frame_bytes={self.max_frame_bytes} must be >= 64 KiB "
                "(a single 8x8 view frame already needs ~1 KiB of JSON)")


@dataclasses.dataclass(frozen=True)
class Config:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    diffusion: DiffusionConfig = dataclasses.field(default_factory=DiffusionConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)

    def validate(self) -> None:
        self.model.validate()
        self.serving.validate()
        if self.mesh.context_parallel and self.mesh.model_parallel <= 1:
            raise ValueError(
                "context_parallel shards the spatial axis over the model "
                f"axis, but model_parallel={self.mesh.model_parallel} makes "
                "that a no-op — set model_parallel > 1")
        if self.train.global_batch % max(1, self.train.accum_steps):
            raise ValueError(
                f"global_batch ({self.train.global_batch}) must be "
                f"divisible by accum_steps ({self.train.accum_steps})")
        if self.model.logsnr_clip != self.diffusion.logsnr_max:
            raise ValueError(
                f"model.logsnr_clip ({self.model.logsnr_clip}) must equal "
                f"diffusion.logsnr_max ({self.diffusion.logsnr_max}) — the "
                "noise-level embedding clip and the schedule bound are the "
                "same quantity")


def srn64_config() -> Config:
    """The config every reference entry point actually runs:
    ``XUNet(H=64, W=64, ch=128)`` (train.py:229, lightning/diff3d.py:38,
    sampling.py:51) at batch 128."""
    return Config(model=ModelConfig(H=64, W=64, ch=128))


def srn128_config() -> Config:
    """The paper's full-resolution config (README.md:39 notes it OOMs on the
    reference's 8x3090; on TPU we enable bf16 + remat instead)."""
    return Config(model=ModelConfig(H=128, W=128, ch=256, remat=True))


def test_config(imgsize: int = 16, ch: int = 8,
                shallow: bool = False) -> Config:
    """Tiny config for unit tests / CPU-mesh dry runs.

    ``shallow=True`` uses a 2-level UNet (vs the reference's 4) — half
    the blocks to compile.  For tests of *properties that don't depend on
    depth* (sharded==replicated equality, NaN guards, accumulation);
    structure-sensitive tests (up-path bookkeeping, whole-model torch
    parity, the driver dryrun) keep the full 4-level shape.
    """
    model_kw = dict(H=imgsize, W=imgsize, ch=ch, emb_ch=32,
                    num_res_blocks=1, dropout=0.0, dtype="float32")
    if shallow:
        model_kw.update(ch_mult=(1, 2), attn_levels=(1, 2))
    return Config(
        model=ModelConfig(**model_kw),
        train=TrainConfig(global_batch=8, warmup_examples=1024,
                          max_steps=4, ckpt_every=2, log_every=1),
        data=DataConfig(imgsize=imgsize),
        diffusion=DiffusionConfig(timesteps=4),
    )
