from diff3d_tpu.convert.torch_ckpt import (convert_state_dict,
                                           expected_torch_state,
                                           load_torch_checkpoint,
                                           verify_state_dict)

__all__ = ["convert_state_dict", "expected_torch_state",
           "load_torch_checkpoint", "verify_state_dict"]
