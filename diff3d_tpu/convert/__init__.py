from diff3d_tpu.convert.torch_ckpt import (convert_state_dict,
                                           load_torch_checkpoint)

__all__ = ["convert_state_dict", "load_torch_checkpoint"]
