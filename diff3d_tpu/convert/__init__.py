from diff3d_tpu.convert.torch_ckpt import (convert_state_dict,
                                           expected_torch_state,
                                           load_torch_checkpoint,
                                           verify_state_dict)
from diff3d_tpu.convert.progressive import (adapt_params_resolution,
                                            check_resolution_compatible,
                                            init_student_from_teacher)

__all__ = ["convert_state_dict", "expected_torch_state",
           "load_torch_checkpoint", "verify_state_dict",
           "adapt_params_resolution", "check_resolution_compatible",
           "init_student_from_teacher"]
