"""Convert reference PyTorch checkpoints to this framework's params.

Reference users have pretrained ``.pt`` files (``torch.save({'model':
state_dict, 'optim': ..., 'step': ...})`` — ``/root/reference/
train.py:287-298``; distributed weights on Google Drive, README.md:37).
This module maps that state dict onto the Flax X-UNet's parameter tree so
they can resume/sample here without retraining.

Key-scheme source (reference ``xunet.py``, naming read from the module
constructors — see file:line notes inline):

  * ``conditioningprocessor.logsnr_emb_emb.{0,2}`` (Sequential Linear/
    SiLU/Linear, xunet.py:272-277) -> ``conditioningprocessor/Dense_{0,1}``
  * ``conditioningprocessor.{pos_emb,first_emb,other_emb}``
    (xunet.py:280-290, channel-first) -> channels-last params
  * ``conditioningprocessor.convs.{i}`` (xunet.py:292-299) ->
    ``level_conv_{i}``
  * ``conv`` (stem, xunet.py:385) -> ``stem_conv``
  * ``xunetblocks.{L}.{B}`` (xunet.py:393-415): B < num_res_blocks is an
    XUNetBlock -> ``down_{L}_{B}``; the trailing ResnetBlock(resample=
    'down') -> ``down_{L}_downsample``
  * ``middle`` (xunet.py:419-424) -> ``middle``
  * ``upsample.{L}.{B}`` (ModuleDict keyed str(L), xunet.py:427-465):
    B <= num_res_blocks -> ``up_{L}_{B}``; trailing up-ResnetBlock ->
    ``up_{L}_upsample``
  * ``lastgn``/``lastconv`` (xunet.py:472-474) -> ``last_gn``/``last_conv``

Layout conversions: Linear ``[out,in]`` -> ``kernel [in,out]``; Conv2d
``[O,I,kh,kw]`` -> ``[kh,kw,I,O]``; ``nn.MultiheadAttention``'s packed
``in_proj_weight [3C,C]`` -> separate ``q/k/v_proj`` kernels; GroupNorm
``weight/bias`` -> ``scale/bias``.  A leading ``module.`` (DataParallel,
reference sampling.py:52) is stripped.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np

from diff3d_tpu.config import ModelConfig


def _linear(sd: Mapping[str, np.ndarray], tkey: str) -> Dict[str, np.ndarray]:
    return {"kernel": np.ascontiguousarray(sd[f"{tkey}.weight"].T),
            "bias": np.asarray(sd[f"{tkey}.bias"])}


def _conv(sd: Mapping[str, np.ndarray], tkey: str) -> Dict[str, np.ndarray]:
    w = np.asarray(sd[f"{tkey}.weight"])           # [O, I, kh, kw]
    return {"kernel": np.ascontiguousarray(w.transpose(2, 3, 1, 0)),
            "bias": np.asarray(sd[f"{tkey}.bias"])}


def _groupnorm(sd: Mapping[str, np.ndarray], tkey: str
               ) -> Dict[str, Dict[str, np.ndarray]]:
    # reference GroupNorm wraps nn.GroupNorm as `.gn` (xunet.py:66)
    return {"GroupNorm_0": {"scale": np.asarray(sd[f"{tkey}.gn.weight"]),
                            "bias": np.asarray(sd[f"{tkey}.gn.bias"])}}


def _attn_layer(sd: Mapping[str, np.ndarray], tkey: str
                ) -> Dict[str, Dict[str, np.ndarray]]:
    """``nn.MultiheadAttention`` (xunet.py:161) -> q/k/v/out projections."""
    w = np.asarray(sd[f"{tkey}.attn.in_proj_weight"])   # [3C, C]
    b = np.asarray(sd[f"{tkey}.attn.in_proj_bias"])     # [3C]
    C = w.shape[1]
    out = {}
    for i, name in enumerate(("q_proj", "k_proj", "v_proj")):
        out[name] = {"kernel": np.ascontiguousarray(w[i * C:(i + 1) * C].T),
                     "bias": b[i * C:(i + 1) * C].copy()}
    out["out_proj"] = _linear(sd, f"{tkey}.attn.out_proj")
    return out


def _resnet_block(sd: Mapping[str, np.ndarray], tkey: str,
                  has_skip_proj: bool) -> Dict:
    out = {
        "FrameGroupNorm_0": _groupnorm(sd, f"{tkey}.groupnorm0"),
        "FrameGroupNorm_1": _groupnorm(sd, f"{tkey}.groupnorm1"),
        "conv1": _conv(sd, f"{tkey}.conv1"),
        "conv2": _conv(sd, f"{tkey}.conv2"),
        "FiLM_0": {"Dense_0": _linear(sd, f"{tkey}.film.dense")},
    }
    if has_skip_proj:
        # reference names the 1x1 skip projection `dense` (xunet.py:129)
        out["skip_proj"] = _conv(sd, f"{tkey}.dense")
    return out


def _attn_block(sd: Mapping[str, np.ndarray], tkey: str) -> Dict:
    return {
        "FrameGroupNorm_0": _groupnorm(sd, f"{tkey}.groupnorm"),
        "attn": _attn_layer(sd, f"{tkey}.attn_layer"),
        # zero-init 1x1 out conv is `linear` (xunet.py:190)
        "out_conv": _conv(sd, f"{tkey}.linear"),
    }


def _xunet_block(sd: Mapping[str, np.ndarray], tkey: str,
                 use_attn: bool) -> Dict:
    has_skip = f"{tkey}.resnetblock.dense.weight" in sd
    out = {"resnetblock": _resnet_block(sd, f"{tkey}.resnetblock",
                                        has_skip)}
    if use_attn:
        out["attnblock_self"] = _attn_block(sd, f"{tkey}.attnblock_self")
        out["attnblock_cross"] = _attn_block(sd, f"{tkey}.attnblock_cross")
    return out


def convert_state_dict(sd: Mapping[str, np.ndarray],
                       cfg: ModelConfig) -> Dict:
    """Reference torch state dict -> Flax ``params`` tree for ``XUNet(cfg)``.

    ``sd`` values may be torch tensors or numpy arrays; a ``module.``
    DataParallel prefix is stripped.
    """
    sd = {k[len("module."):] if k.startswith("module.") else k:
          (v.detach().cpu().numpy() if hasattr(v, "detach") else
           np.asarray(v))
          for k, v in sd.items()}

    num_res = cfg.num_resolutions
    params: Dict = {}

    cp = "conditioningprocessor"
    cp_tree = {
        "Dense_0": _linear(sd, f"{cp}.logsnr_emb_emb.0"),
        "Dense_1": _linear(sd, f"{cp}.logsnr_emb_emb.2"),
    }
    if cfg.use_pos_emb:
        # [D, H, W] -> [H, W, D]
        cp_tree["pos_emb"] = np.ascontiguousarray(
            np.asarray(sd[f"{cp}.pos_emb"]).transpose(1, 2, 0))
    if cfg.use_ref_pose_emb:
        for k in ("first_emb", "other_emb"):
            # [1, 1, D, 1, 1] -> [1, 1, 1, 1, D]
            cp_tree[k] = np.ascontiguousarray(
                np.asarray(sd[f"{cp}.{k}"]).transpose(0, 1, 3, 4, 2))
    for i in range(num_res):
        cp_tree[f"level_conv_{i}"] = _conv(sd, f"{cp}.convs.{i}")
    params[cp] = cp_tree

    params["stem_conv"] = _conv(sd, "conv")

    for lvl in range(num_res):
        use_attn = lvl in cfg.attn_levels
        for blk in range(cfg.num_res_blocks):
            params[f"down_{lvl}_{blk}"] = _xunet_block(
                sd, f"xunetblocks.{lvl}.{blk}", use_attn)
        if lvl != num_res - 1:
            params[f"down_{lvl}_downsample"] = _resnet_block(
                sd, f"xunetblocks.{lvl}.{cfg.num_res_blocks}",
                has_skip_proj=False)

    params["middle"] = _xunet_block(sd, "middle",
                                    num_res in cfg.attn_levels)

    for lvl in reversed(range(num_res)):
        use_attn = lvl in cfg.attn_levels
        for blk in range(cfg.num_res_blocks + 1):
            params[f"up_{lvl}_{blk}"] = _xunet_block(
                sd, f"upsample.{lvl}.{blk}", use_attn)
        if lvl != 0:
            params[f"up_{lvl}_upsample"] = _resnet_block(
                sd, f"upsample.{lvl}.{cfg.num_res_blocks + 1}",
                has_skip_proj=False)

    params["last_gn"] = _groupnorm(sd, "lastgn")
    params["last_conv"] = _conv(sd, "lastconv")
    return params


def _inv_linear(tree, tkey: str) -> Dict[str, tuple]:
    i, o = tree["kernel"].shape
    return {f"{tkey}.weight": (o, i), f"{tkey}.bias": (o,)}


def _inv_conv(tree, tkey: str) -> Dict[str, tuple]:
    kh, kw, i, o = tree["kernel"].shape
    return {f"{tkey}.weight": (o, i, kh, kw), f"{tkey}.bias": (o,)}


def _inv_groupnorm(tree, tkey: str) -> Dict[str, tuple]:
    c = tree["GroupNorm_0"]["scale"].shape[0]
    return {f"{tkey}.gn.weight": (c,), f"{tkey}.gn.bias": (c,)}


def _inv_attn_layer(tree, tkey: str) -> Dict[str, tuple]:
    c = tree["q_proj"]["kernel"].shape[0]
    out = {f"{tkey}.attn.in_proj_weight": (3 * c, c),
           f"{tkey}.attn.in_proj_bias": (3 * c,)}
    out.update(_inv_linear(tree["out_proj"], f"{tkey}.attn.out_proj"))
    return out


def _inv_resnet_block(tree, tkey: str) -> Dict[str, tuple]:
    out = {}
    out.update(_inv_groupnorm(tree["FrameGroupNorm_0"], f"{tkey}.groupnorm0"))
    out.update(_inv_groupnorm(tree["FrameGroupNorm_1"], f"{tkey}.groupnorm1"))
    out.update(_inv_conv(tree["conv1"], f"{tkey}.conv1"))
    out.update(_inv_conv(tree["conv2"], f"{tkey}.conv2"))
    out.update(_inv_linear(tree["FiLM_0"]["Dense_0"], f"{tkey}.film.dense"))
    if "skip_proj" in tree:
        out.update(_inv_conv(tree["skip_proj"], f"{tkey}.dense"))
    return out


def _inv_attn_block(tree, tkey: str) -> Dict[str, tuple]:
    out = {}
    out.update(_inv_groupnorm(tree["FrameGroupNorm_0"], f"{tkey}.groupnorm"))
    out.update(_inv_attn_layer(tree["attn"], f"{tkey}.attn_layer"))
    out.update(_inv_conv(tree["out_conv"], f"{tkey}.linear"))
    return out


def _inv_xunet_block(tree, tkey: str) -> Dict[str, tuple]:
    out = _inv_resnet_block(tree["resnetblock"], f"{tkey}.resnetblock")
    if "attnblock_self" in tree:
        out.update(_inv_attn_block(tree["attnblock_self"],
                                   f"{tkey}.attnblock_self"))
        out.update(_inv_attn_block(tree["attnblock_cross"],
                                   f"{tkey}.attnblock_cross"))
    return out


def expected_torch_state(cfg: ModelConfig) -> Dict[str, tuple]:
    """The COMPLETE reference state-dict key set (torch key -> shape) a
    ``.pt`` trained with the reference's ``XUNet(cfg)`` must contain.

    Built by inverting :func:`convert_state_dict`'s mapping over the Flax
    model's expected parameter shapes (``jax.eval_shape`` — no weights are
    materialised), so the skip-projection / attention-level branching and
    the up-path channel arithmetic come from the live model definition,
    not a hand-maintained table.  Used by ``convert_cli --verify`` to give
    the real published checkpoint (``/root/reference/README.md:35-39``) a
    meaningful failure mode: extra/missing/shape-mismatched keys are
    reported up front instead of a KeyError mid-conversion.
    """
    import jax

    from diff3d_tpu.models import XUNet

    H, W = cfg.H, cfg.W

    def init():
        model = XUNet(cfg)
        batch = {
            "x": jax.numpy.zeros((1, H, W, 3)),
            "z": jax.numpy.zeros((1, H, W, 3)),
            "logsnr": jax.numpy.zeros((1, 2)),
            "R": jax.numpy.zeros((1, 2, 3, 3)),
            "t": jax.numpy.zeros((1, 2, 3)),
            "K": jax.numpy.zeros((1, 3, 3)),
        }
        return model.init({"params": jax.random.PRNGKey(0)}, batch,
                          cond_mask=jax.numpy.ones((1,), bool))["params"]

    tree = jax.eval_shape(init)

    exp: Dict[str, tuple] = {}
    cp = "conditioningprocessor"
    cpt = tree[cp]
    exp.update(_inv_linear(cpt["Dense_0"], f"{cp}.logsnr_emb_emb.0"))
    exp.update(_inv_linear(cpt["Dense_1"], f"{cp}.logsnr_emb_emb.2"))
    if cfg.use_pos_emb:
        h, w, d = cpt["pos_emb"].shape
        exp[f"{cp}.pos_emb"] = (d, h, w)
    if cfg.use_ref_pose_emb:
        for k in ("first_emb", "other_emb"):
            d = cpt[k].shape[-1]
            exp[f"{cp}.{k}"] = (1, 1, d, 1, 1)
    for i in range(cfg.num_resolutions):
        exp.update(_inv_conv(cpt[f"level_conv_{i}"], f"{cp}.convs.{i}"))

    exp.update(_inv_conv(tree["stem_conv"], "conv"))
    num_res = cfg.num_resolutions
    for lvl in range(num_res):
        for blk in range(cfg.num_res_blocks):
            exp.update(_inv_xunet_block(tree[f"down_{lvl}_{blk}"],
                                        f"xunetblocks.{lvl}.{blk}"))
        if lvl != num_res - 1:
            exp.update(_inv_resnet_block(
                tree[f"down_{lvl}_downsample"],
                f"xunetblocks.{lvl}.{cfg.num_res_blocks}"))
    exp.update(_inv_xunet_block(tree["middle"], "middle"))
    for lvl in range(num_res):
        for blk in range(cfg.num_res_blocks + 1):
            exp.update(_inv_xunet_block(tree[f"up_{lvl}_{blk}"],
                                        f"upsample.{lvl}.{blk}"))
        if lvl != 0:
            exp.update(_inv_resnet_block(
                tree[f"up_{lvl}_upsample"],
                f"upsample.{lvl}.{cfg.num_res_blocks + 1}"))
    exp.update(_inv_groupnorm(tree["last_gn"], "lastgn"))
    exp.update(_inv_conv(tree["last_conv"], "lastconv"))
    return exp


def verify_state_dict(sd: Mapping[str, np.ndarray], cfg: ModelConfig
                      ) -> Dict[str, list]:
    """Compare a reference state dict against :func:`expected_torch_state`.

    Returns ``{"missing": [...], "extra": [...], "shape_mismatch":
    [(key, got, want), ...]}`` — all empty iff the checkpoint converts
    cleanly.  A ``module.`` DataParallel prefix is stripped first, like
    conversion itself does.
    """
    got = {k[len("module."):] if k.startswith("module.") else k:
           tuple(v.shape) for k, v in sd.items()}
    want = expected_torch_state(cfg)
    return {
        "missing": sorted(want.keys() - got.keys()),
        "extra": sorted(got.keys() - want.keys()),
        "shape_mismatch": sorted(
            (k, got[k], want[k]) for k in want.keys() & got.keys()
            if got[k] != want[k]),
    }


def load_torch_checkpoint(path: str, cfg: ModelConfig):
    """Load a reference ``.pt`` checkpoint (``{'model': state_dict, ...}``
    or a bare state dict) and convert its model weights.

    Returns ``(params, step)``; ``step`` is 0 when the file carries none.
    """
    import torch  # cpu build is in the image

    ckpt = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(ckpt, dict) and "model" in ckpt:
        sd, step = ckpt["model"], int(ckpt.get("step", 0))
    else:
        sd, step = ckpt, 0
    return convert_state_dict(sd, cfg), step
