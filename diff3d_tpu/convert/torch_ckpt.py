"""Convert reference PyTorch checkpoints to this framework's params.

Reference users have pretrained ``.pt`` files (``torch.save({'model':
state_dict, 'optim': ..., 'step': ...})`` — ``/root/reference/
train.py:287-298``; distributed weights on Google Drive, README.md:37).
This module maps that state dict onto the Flax X-UNet's parameter tree so
they can resume/sample here without retraining.

Key-scheme source (reference ``xunet.py``, naming read from the module
constructors — see file:line notes inline):

  * ``conditioningprocessor.logsnr_emb_emb.{0,2}`` (Sequential Linear/
    SiLU/Linear, xunet.py:272-277) -> ``conditioningprocessor/Dense_{0,1}``
  * ``conditioningprocessor.{pos_emb,first_emb,other_emb}``
    (xunet.py:280-290, channel-first) -> channels-last params
  * ``conditioningprocessor.convs.{i}`` (xunet.py:292-299) ->
    ``level_conv_{i}``
  * ``conv`` (stem, xunet.py:385) -> ``stem_conv``
  * ``xunetblocks.{L}.{B}`` (xunet.py:393-415): B < num_res_blocks is an
    XUNetBlock -> ``down_{L}_{B}``; the trailing ResnetBlock(resample=
    'down') -> ``down_{L}_downsample``
  * ``middle`` (xunet.py:419-424) -> ``middle``
  * ``upsample.{L}.{B}`` (ModuleDict keyed str(L), xunet.py:427-465):
    B <= num_res_blocks -> ``up_{L}_{B}``; trailing up-ResnetBlock ->
    ``up_{L}_upsample``
  * ``lastgn``/``lastconv`` (xunet.py:472-474) -> ``last_gn``/``last_conv``

Layout conversions: Linear ``[out,in]`` -> ``kernel [in,out]``; Conv2d
``[O,I,kh,kw]`` -> ``[kh,kw,I,O]``; ``nn.MultiheadAttention``'s packed
``in_proj_weight [3C,C]`` -> separate ``q/k/v_proj`` kernels; GroupNorm
``weight/bias`` -> ``scale/bias``.  A leading ``module.`` (DataParallel,
reference sampling.py:52) is stripped.
"""

from __future__ import annotations

from typing import Dict, Mapping

import numpy as np

from diff3d_tpu.config import ModelConfig


def _linear(sd: Mapping[str, np.ndarray], tkey: str) -> Dict[str, np.ndarray]:
    return {"kernel": np.ascontiguousarray(sd[f"{tkey}.weight"].T),
            "bias": np.asarray(sd[f"{tkey}.bias"])}


def _conv(sd: Mapping[str, np.ndarray], tkey: str) -> Dict[str, np.ndarray]:
    w = np.asarray(sd[f"{tkey}.weight"])           # [O, I, kh, kw]
    return {"kernel": np.ascontiguousarray(w.transpose(2, 3, 1, 0)),
            "bias": np.asarray(sd[f"{tkey}.bias"])}


def _groupnorm(sd: Mapping[str, np.ndarray], tkey: str
               ) -> Dict[str, Dict[str, np.ndarray]]:
    # reference GroupNorm wraps nn.GroupNorm as `.gn` (xunet.py:66)
    return {"GroupNorm_0": {"scale": np.asarray(sd[f"{tkey}.gn.weight"]),
                            "bias": np.asarray(sd[f"{tkey}.gn.bias"])}}


def _attn_layer(sd: Mapping[str, np.ndarray], tkey: str
                ) -> Dict[str, Dict[str, np.ndarray]]:
    """``nn.MultiheadAttention`` (xunet.py:161) -> q/k/v/out projections."""
    w = np.asarray(sd[f"{tkey}.attn.in_proj_weight"])   # [3C, C]
    b = np.asarray(sd[f"{tkey}.attn.in_proj_bias"])     # [3C]
    C = w.shape[1]
    out = {}
    for i, name in enumerate(("q_proj", "k_proj", "v_proj")):
        out[name] = {"kernel": np.ascontiguousarray(w[i * C:(i + 1) * C].T),
                     "bias": b[i * C:(i + 1) * C].copy()}
    out["out_proj"] = _linear(sd, f"{tkey}.attn.out_proj")
    return out


def _resnet_block(sd: Mapping[str, np.ndarray], tkey: str,
                  has_skip_proj: bool) -> Dict:
    out = {
        "FrameGroupNorm_0": _groupnorm(sd, f"{tkey}.groupnorm0"),
        "FrameGroupNorm_1": _groupnorm(sd, f"{tkey}.groupnorm1"),
        "conv1": _conv(sd, f"{tkey}.conv1"),
        "conv2": _conv(sd, f"{tkey}.conv2"),
        "FiLM_0": {"Dense_0": _linear(sd, f"{tkey}.film.dense")},
    }
    if has_skip_proj:
        # reference names the 1x1 skip projection `dense` (xunet.py:129)
        out["skip_proj"] = _conv(sd, f"{tkey}.dense")
    return out


def _attn_block(sd: Mapping[str, np.ndarray], tkey: str) -> Dict:
    return {
        "FrameGroupNorm_0": _groupnorm(sd, f"{tkey}.groupnorm"),
        "attn": _attn_layer(sd, f"{tkey}.attn_layer"),
        # zero-init 1x1 out conv is `linear` (xunet.py:190)
        "out_conv": _conv(sd, f"{tkey}.linear"),
    }


def _xunet_block(sd: Mapping[str, np.ndarray], tkey: str,
                 use_attn: bool) -> Dict:
    has_skip = f"{tkey}.resnetblock.dense.weight" in sd
    out = {"resnetblock": _resnet_block(sd, f"{tkey}.resnetblock",
                                        has_skip)}
    if use_attn:
        out["attnblock_self"] = _attn_block(sd, f"{tkey}.attnblock_self")
        out["attnblock_cross"] = _attn_block(sd, f"{tkey}.attnblock_cross")
    return out


def convert_state_dict(sd: Mapping[str, np.ndarray],
                       cfg: ModelConfig) -> Dict:
    """Reference torch state dict -> Flax ``params`` tree for ``XUNet(cfg)``.

    ``sd`` values may be torch tensors or numpy arrays; a ``module.``
    DataParallel prefix is stripped.
    """
    sd = {k[len("module."):] if k.startswith("module.") else k:
          (v.detach().cpu().numpy() if hasattr(v, "detach") else
           np.asarray(v))
          for k, v in sd.items()}

    num_res = cfg.num_resolutions
    params: Dict = {}

    cp = "conditioningprocessor"
    cp_tree = {
        "Dense_0": _linear(sd, f"{cp}.logsnr_emb_emb.0"),
        "Dense_1": _linear(sd, f"{cp}.logsnr_emb_emb.2"),
    }
    if cfg.use_pos_emb:
        # [D, H, W] -> [H, W, D]
        cp_tree["pos_emb"] = np.ascontiguousarray(
            np.asarray(sd[f"{cp}.pos_emb"]).transpose(1, 2, 0))
    if cfg.use_ref_pose_emb:
        for k in ("first_emb", "other_emb"):
            # [1, 1, D, 1, 1] -> [1, 1, 1, 1, D]
            cp_tree[k] = np.ascontiguousarray(
                np.asarray(sd[f"{cp}.{k}"]).transpose(0, 1, 3, 4, 2))
    for i in range(num_res):
        cp_tree[f"level_conv_{i}"] = _conv(sd, f"{cp}.convs.{i}")
    params[cp] = cp_tree

    params["stem_conv"] = _conv(sd, "conv")

    for lvl in range(num_res):
        use_attn = lvl in cfg.attn_levels
        for blk in range(cfg.num_res_blocks):
            params[f"down_{lvl}_{blk}"] = _xunet_block(
                sd, f"xunetblocks.{lvl}.{blk}", use_attn)
        if lvl != num_res - 1:
            params[f"down_{lvl}_downsample"] = _resnet_block(
                sd, f"xunetblocks.{lvl}.{cfg.num_res_blocks}",
                has_skip_proj=False)

    params["middle"] = _xunet_block(sd, "middle",
                                    num_res in cfg.attn_levels)

    for lvl in reversed(range(num_res)):
        use_attn = lvl in cfg.attn_levels
        for blk in range(cfg.num_res_blocks + 1):
            params[f"up_{lvl}_{blk}"] = _xunet_block(
                sd, f"upsample.{lvl}.{blk}", use_attn)
        if lvl != 0:
            params[f"up_{lvl}_upsample"] = _resnet_block(
                sd, f"upsample.{lvl}.{cfg.num_res_blocks + 1}",
                has_skip_proj=False)

    params["last_gn"] = _groupnorm(sd, "lastgn")
    params["last_conv"] = _conv(sd, "lastconv")
    return params


def load_torch_checkpoint(path: str, cfg: ModelConfig):
    """Load a reference ``.pt`` checkpoint (``{'model': state_dict, ...}``
    or a bare state dict) and convert its model weights.

    Returns ``(params, step)``; ``step`` is 0 when the file carries none.
    """
    import torch  # cpu build is in the image

    ckpt = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(ckpt, dict) and "model" in ckpt:
        sd, step = ckpt["model"], int(ckpt.get("step", 0))
    else:
        sd, step = ckpt, 0
    return convert_state_dict(sd, cfg), step
