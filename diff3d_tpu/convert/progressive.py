"""Progressive resolution transfer: reuse trained weights across H/W.

The X-UNet is resolution-independent everywhere except the
ConditioningProcessor's learned per-pixel embedding ``pos_emb [H, W, 144]``
(reference ``xunet.py:280-282``): convs slide, GroupNorm/FiLM act per
channel, attention runs over whatever H*W tokens arrive, and the ray/NeRF
pose embeddings are computed from the camera at the current resolution.
So a model trained at 64^2 transfers to 128^2 by copying every parameter
and bilinearly upsampling ``pos_emb`` — the coarse spatial prior it
learned stays aligned (pixel i of H covers the same image fraction as
pixel 2i of 2H).

Why this exists: the paper's 128^2 config costs ~4x the compute per
example of 64^2, and training it from scratch inside a fixed chip-hour
budget underfits (round-3: held-out PSNR 3.6 dB below the copy baseline
at 640K examples, RESULTS.md).  Seeding from a trained 64^2 model hands
the 128^2 run everything resolution-independent — geometry conditioning,
cross-view attention, the denoising prior — so its budget is spent on the
only new thing, fine spatial detail.  (The reference has no counterpart:
it cannot even run 128^2, ``/root/reference/README.md:39``.)
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def adapt_params_resolution(params, dst_hw: Tuple[int, int]):
    """Return ``params`` adapted to a model of resolution ``dst_hw``.

    Every leaf is copied unchanged except
    ``conditioningprocessor/pos_emb [H, W, C]``, which is resized with
    bilinear interpolation.  Raises KeyError if the tree has no
    conditioningprocessor (not an X-UNet param tree) — passing e.g. an
    opt-state pytree here would otherwise silently no-op.

    Works on concrete arrays and (for shape checks) ShapeDtypeStructs.
    """
    cp = dict(params["conditioningprocessor"])
    if "pos_emb" in cp:
        pe = cp["pos_emb"]
        H2, W2 = dst_hw
        if pe.shape[:2] != (H2, W2):
            cp["pos_emb"] = jax.image.resize(
                pe, (H2, W2, pe.shape[2]), method="bilinear")
    out = dict(params)
    out["conditioningprocessor"] = cp
    return out


def init_student_from_teacher(params, dst_hw: Tuple[int, int] | None = None):
    """Fresh student params for one progressive-distillation round
    (``diff3d_tpu.train.distill``): the teacher's weights, deep-copied so
    the student's donated train step can never alias the teacher buffers
    it must keep reading, optionally resolution-adapted first (a 64^2
    teacher can seed a 128^2 student the same way full training transfers
    across resolutions)."""
    if dst_hw is not None:
        params = adapt_params_resolution(params, dst_hw)
    return jax.tree.map(jnp.copy, params)


def check_resolution_compatible(src_params, dst_params) -> None:
    """Assert ``src_params`` (adapted) matches ``dst_params``'s tree —
    same widths everywhere; only pos_emb may have differed.  Raises
    ValueError naming the first mismatch (e.g. seeding a --ch 128 run
    from a --ch 64 checkpoint)."""
    src_flat = dict(jax.tree_util.tree_flatten_with_path(src_params)[0])
    dst_flat = dict(jax.tree_util.tree_flatten_with_path(dst_params)[0])
    if src_flat.keys() != dst_flat.keys():
        missing = sorted(map(jax.tree_util.keystr,
                             dst_flat.keys() - src_flat.keys()))
        extra = sorted(map(jax.tree_util.keystr,
                           src_flat.keys() - dst_flat.keys()))
        raise ValueError(
            f"init_from checkpoint tree mismatch: missing={missing[:4]} "
            f"extra={extra[:4]} — the source model's width/depth "
            "(--ch/--emb_ch/--num_res_blocks) must equal the target's")
    for k in dst_flat:
        if jnp.shape(src_flat[k]) != jnp.shape(dst_flat[k]):
            raise ValueError(
                f"init_from shape mismatch at {jax.tree_util.keystr(k)}: "
                f"source {jnp.shape(src_flat[k])} vs target "
                f"{jnp.shape(dst_flat[k])} — source width must equal "
                "target width (only H/W may differ)")
