"""X-UNet building blocks as Flax modules, NHWC with a frames axis.

All feature maps are ``[B, F, H, W, C]`` (channels-last — TPU/XLA's native
conv layout; the reference uses NCHW).  ``F`` is the number of frames
(source + target view = 2), kept general where the reference hardcodes 2
(``/root/reference/xunet.py:70``).

Parity targets (reference ``xunet.py``): ``GroupNorm`` over frames (:61-71),
``FiLM`` (:74-87), BigGAN-style ``ResnetBlock`` with zero-init second conv
and /sqrt(2) residual (:90-152), shared-weight frame self/cross attention
(:154-220), ``XUNetBlock`` (:222-256).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.ops import dispatch
from diff3d_tpu.ops import pallas_film  # noqa: F401 - registers 'groupnorm'
from diff3d_tpu.ops.attention import multi_head_attention


def nearest_neighbor_upsample(h: jnp.ndarray) -> jnp.ndarray:
    """x2 spatial nearest upsample of ``[B, F, H, W, C]``
    (reference ``xunet.py:17-20``)."""
    h = jnp.repeat(h, 2, axis=2)
    return jnp.repeat(h, 2, axis=3)


def avgpool_downsample(h: jnp.ndarray, k: int = 2) -> jnp.ndarray:
    """kxk average-pool downsample of ``[B, F, H, W, C]``
    (reference ``xunet.py:23-28``)."""
    B, F, H, W, C = h.shape
    h = h.reshape(B, F, H // k, k, W // k, k, C)
    return h.mean(axis=(3, 5))


def _num_groups(C: int, preferred: int = 32) -> int:
    """Largest group count <= preferred that divides C (the reference always
    has C a multiple of 32; this generalises for tiny test widths)."""
    g = min(preferred, C)
    while C % g:
        g -= 1
    return g


class _GroupNormParams(nn.Module):
    """Parameter-only stand-in for ``nn.GroupNorm`` on the fused-kernel
    path: same child name ("GroupNorm_0"), param names ("scale"/"bias"),
    shapes, dtypes and inits, so a checkpoint trained with either kernel
    backend restores bit-for-bit into the other."""

    features: int

    @nn.compact
    def __call__(self) -> Tuple[jnp.ndarray, jnp.ndarray]:
        gamma = self.param("scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        beta = self.param("bias", nn.initializers.zeros,
                          (self.features,), jnp.float32)
        return gamma, beta


class FrameGroupNorm(nn.Module):
    """Group normalization applied per frame (reference ``xunet.py:61-71``:
    frames are folded into the batch axis before GN), with optional fused
    FiLM/SiLU epilogues.

    ``kernels`` routes through :mod:`diff3d_tpu.ops.dispatch`: 'xla' (the
    default) runs the plain ``nn.GroupNorm`` composition — bit-identical
    graphs to the pre-kernel-layer code; 'pallas'/'auto' may run the fused
    GroupNorm->FiLM->SiLU Pallas kernel
    (:mod:`diff3d_tpu.ops.pallas_film`), which keeps the whole chain in
    VMEM.  ``scale``/``shift`` (both or neither, shaped like ``h``) append
    the FiLM modulation ``y*(1+scale)+shift``; ``silu`` appends the
    activation.  The parameter tree is identical on every path."""

    num_groups: int = 32
    dtype: jnp.dtype = jnp.float32
    kernels: str = "xla"
    silu: bool = False

    @nn.compact
    def __call__(self, h: jnp.ndarray,
                 scale: Optional[jnp.ndarray] = None,
                 shift: Optional[jnp.ndarray] = None) -> jnp.ndarray:
        B, F, H, W, C = h.shape
        groups = _num_groups(C, self.num_groups)
        flat = jax.ShapeDtypeStruct((B * F, H * W, C), h.dtype)
        impl = dispatch.resolve("groupnorm", self.kernels, flat,
                                num_groups=groups)
        if impl.name == "pallas":
            gamma, beta = _GroupNormParams(C, name="GroupNorm_0")()
            kw = {}
            if scale is not None:
                kw = dict(scale=scale.reshape(B * F, H * W, C),
                          shift=shift.reshape(B * F, H * W, C))
            out = impl.fn(h.reshape(B * F, H * W, C), gamma, beta,
                          num_groups=groups, silu=self.silu, **kw)
            return out.reshape(B, F, H, W, C)
        # epsilon matches torch.nn.GroupNorm's 1e-5 (reference xunet.py:66);
        # Flax's default 1e-6 drifts ~1e-5/application across the ~40 GNs of
        # a converted checkpoint's forward.
        out = nn.GroupNorm(num_groups=groups, epsilon=1e-5,
                           dtype=self.dtype)(h.reshape(B * F, H, W, C))
        out = out.reshape(B, F, H, W, C)
        if scale is not None:
            out = out * (1.0 + scale) + shift
        if self.silu:
            out = nn.silu(out)
        return out


class FiLM(nn.Module):
    """Feature-wise linear modulation (reference ``xunet.py:74-87``):
    ``Dense(emb_ch -> 2*features)`` on SiLU(emb), split into scale/shift,
    ``h * (1 + scale) + shift``.  ``emb`` is ``[B, F, h, w, emb_ch]`` —
    channels-last, so no transposes are needed (the reference transposes
    twice around its Linear).

    With ``h=None`` the module only *emits* ``(scale, shift)`` — the
    fused-kernel path hands them to :class:`FrameGroupNorm`'s epilogue
    instead of applying them here.  The parameter tree (``Dense_0``) is
    unchanged either way."""

    features: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h: Optional[jnp.ndarray], emb: jnp.ndarray
                 ) -> Union[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
        emb = nn.Dense(2 * self.features, dtype=self.dtype)(nn.silu(emb))
        scale, shift = jnp.split(emb, 2, axis=-1)
        if h is None:
            return scale, shift
        return h * (1.0 + scale) + shift


class ResnetBlock(nn.Module):
    """BigGAN-style residual block over frames (reference ``xunet.py:90-152``).

    GN -> SiLU -> conv3x3 -> GN -> FiLM -> dropout -> conv3x3(zero-init) ->
    (+ 1x1-projected skip if channels change) -> /sqrt(2) -> optional
    up/down resample of the summed output.
    """

    features: int
    dropout: float = 0.0
    resample: Optional[str] = None   # None | 'up' | 'down'
    dtype: jnp.dtype = jnp.float32
    kernels: str = "xla"

    @nn.compact
    def __call__(self, h_in: jnp.ndarray, emb: jnp.ndarray,
                 deterministic: bool = True) -> jnp.ndarray:
        B, F, H, W, C = h_in.shape

        # One trace-time dispatch decision (on conv1's output shape)
        # covers the whole block, so the FiLM emit/apply split always
        # agrees with the second GroupNorm's backend.
        flat2 = jax.ShapeDtypeStruct((B * F, H * W, self.features),
                                     jnp.dtype(self.dtype))
        use_fused = dispatch.resolve(
            "groupnorm", self.kernels, flat2,
            num_groups=_num_groups(self.features)).name == "pallas"

        h = FrameGroupNorm(dtype=self.dtype, kernels=self.kernels,
                           silu=True)(h_in)
        h = nn.Conv(self.features, (3, 3), dtype=self.dtype,
                    name="conv1")(h.reshape(B * F, H, W, C))
        h = h.reshape(B, F, H, W, self.features)
        if use_fused:
            scale, shift = FiLM(self.features, dtype=self.dtype)(None, emb)
            scale = jnp.broadcast_to(scale, h.shape)
            shift = jnp.broadcast_to(shift, h.shape)
            h = FrameGroupNorm(dtype=self.dtype, kernels=self.kernels)(
                h, scale=scale, shift=shift)
        else:
            h = FrameGroupNorm(dtype=self.dtype, kernels=self.kernels)(h)
            h = FiLM(self.features, dtype=self.dtype)(h, emb)
        h = nn.Dropout(self.dropout)(h, deterministic=deterministic)
        # Zero-init final conv (reference xunet.py:131) so the block starts
        # as (scaled) identity.
        h = nn.Conv(self.features, (3, 3), dtype=self.dtype,
                    kernel_init=nn.initializers.zeros,
                    name="conv2")(h.reshape(B * F, H, W, self.features))
        h = h.reshape(B, F, H, W, self.features)

        if C != self.features:
            h_in = nn.Conv(self.features, (1, 1), dtype=self.dtype,
                           name="skip_proj")(h_in.reshape(B * F, H, W, C))
            h_in = h_in.reshape(B, F, H, W, self.features)

        out = (h + h_in) / np.sqrt(2.0)
        if self.resample == "up":
            out = nearest_neighbor_upsample(out)
        elif self.resample == "down":
            out = avgpool_downsample(out)
        return out


class AttnLayer(nn.Module):
    """Multi-head attention over token sequences (reference
    ``xunet.py:154-177`` wraps ``torch.nn.MultiheadAttention``): q/k/v/out
    projections with bias + sdpa core (backend-dispatched for TPU)."""

    num_heads: int = 4
    attn_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, q: jnp.ndarray, kv: jnp.ndarray) -> jnp.ndarray:
        C = q.shape[-1]
        qp = nn.Dense(C, dtype=self.dtype, name="q_proj")(q)
        kp = nn.Dense(C, dtype=self.dtype, name="k_proj")(kv)
        vp = nn.Dense(C, dtype=self.dtype, name="v_proj")(kv)
        out = multi_head_attention(qp, kp, vp, self.num_heads,
                                   impl=self.attn_impl)
        return nn.Dense(C, dtype=self.dtype, name="out_proj")(out)


class AttnBlock(nn.Module):
    """Frame self/cross attention over ``H*W`` tokens (reference
    ``xunet.py:179-220``).  ONE ``AttnLayer`` is shared by both frames
    (reference ``xunet.py:188``); here both frames run in a single batched
    call (frames folded into the batch axis) instead of two sequential ones.
    Output: zero-init 1x1 conv, residual /sqrt(2).
    """

    attn_type: str                  # 'self' | 'cross'
    num_heads: int = 4
    attn_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32
    kernels: str = "xla"

    @nn.compact
    def __call__(self, h_in: jnp.ndarray) -> jnp.ndarray:
        B, F, H, W, C = h_in.shape
        h = FrameGroupNorm(dtype=self.dtype, kernels=self.kernels)(h_in)
        tokens = h.reshape(B, F, H * W, C)

        q = tokens.reshape(B * F, H * W, C)
        if self.attn_type == "self":
            kv = q
        elif self.attn_type == "cross":
            # Each frame attends to the other (reference xunet.py:206-211;
            # generalised beyond F=2 as "next frame, cyclically").
            kv = jnp.roll(tokens, shift=-1, axis=1).reshape(B * F, H * W, C)
        else:
            raise NotImplementedError(self.attn_type)

        h = AttnLayer(self.num_heads, self.attn_impl, self.dtype,
                      name="attn")(q, kv)
        h = h.reshape(B * F, H, W, C)
        h = nn.Conv(C, (1, 1), dtype=self.dtype,
                    kernel_init=nn.initializers.zeros, name="out_conv")(h)
        h = h.reshape(B, F, H, W, C)
        return (h + h_in) / np.sqrt(2.0)


class XUNetBlock(nn.Module):
    """ResnetBlock followed by optional self- then cross-attention
    (reference ``xunet.py:222-256``)."""

    features: int
    use_attn: bool = False
    num_heads: int = 4
    dropout: float = 0.0
    attn_impl: str = "auto"
    dtype: jnp.dtype = jnp.float32
    kernels: str = "xla"

    @nn.compact
    def __call__(self, x: jnp.ndarray, emb: jnp.ndarray,
                 deterministic: bool = True) -> jnp.ndarray:
        h = ResnetBlock(self.features, self.dropout, dtype=self.dtype,
                        kernels=self.kernels,
                        name="resnetblock")(x, emb, deterministic)
        if self.use_attn:
            h = AttnBlock("self", self.num_heads, self.attn_impl,
                          self.dtype, kernels=self.kernels,
                          name="attnblock_self")(h)
            h = AttnBlock("cross", self.num_heads, self.attn_impl,
                          self.dtype, kernels=self.kernels,
                          name="attnblock_cross")(h)
        return h
