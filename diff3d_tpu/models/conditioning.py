"""Pose/noise-level conditioning (reference ``xunet.py:259-352``), fully
on-device.

The reference drops to CPU numpy + visu3d for ray generation inside the hot
forward (``xunet.py:311-314``); here rays come from
:func:`diff3d_tpu.geometry.pinhole_rays` in pure jnp, so the whole
conditioning path lives inside the jitted step.
"""

from __future__ import annotations

from typing import List, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from diff3d_tpu.geometry import (pinhole_rays_cam, pinhole_rays_world,
                                 posenc_ddpm, posenc_nerf)
from diff3d_tpu.geometry.posenc import posenc_nerf_channels

# 93 (pos, degrees 0..15) + 51 (dir, degrees 0..8) = 144 channels,
# reference xunet.py:317-320.
POS_DEG = 15
DIR_DEG = 8
POSE_EMB_CH = posenc_nerf_channels(0, POS_DEG) + posenc_nerf_channels(0, DIR_DEG)


class ConditioningProcessor(nn.Module):
    """Produces ``(logsnr_emb [B,F,emb_ch], pose_embs[level])`` for the UNet.

    Mechanism (parity with reference ``xunet.py:301-352``):
      1. clip logsnr to the schedule bounds; DDPM-posenc it with
         ``max_time=1.`` and MLP to ``emb_ch``.  (The reference's unused
         ``lossnr`` arctan normalisation at ``xunet.py:306`` is dead code
         and intentionally NOT reproduced.)
      2. per-pixel rays from (R, t, K); NeRF-posenc pos (deg 15) and dir
         (deg 8) -> 144 channels.
      3. zero the pose embedding of BOTH frames where ``cond_mask`` is
         False (classifier-free guidance, ``xunet.py:323-326``).
      4. add learnable per-pixel ``pos_emb`` and per-frame first/other
         embeddings (``xunet.py:281-290,333-337``).
      5. strided 3x3 convs 144 -> emb_ch, stride ``2^level`` per UNet level.
    """

    emb_ch: int
    H: int
    W: int
    num_resolutions: int
    use_pos_emb: bool = True
    use_ref_pose_emb: bool = True
    logsnr_clip: float = 20.0
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, batch: dict, cond_mask: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, List[jnp.ndarray]]:
        B = batch["x"].shape[0]
        H, W = self.H, self.W
        D = POSE_EMB_CH

        logsnr = jnp.clip(batch["logsnr"], -self.logsnr_clip,
                          self.logsnr_clip)                      # [B, F]
        # Encodings stay float32: their sinusoid arguments reach ~2e4
        # (posenc_ddpm's x1000 scaling) and 2^14 (NeRF degree 15), far past
        # bf16's mantissa — bf16 here destroys all phase information.  The
        # Dense/Conv layers below cast to the compute dtype themselves.
        logsnr_emb = posenc_ddpm(logsnr, emb_ch=self.emb_ch, max_time=1.0,
                                 dtype=jnp.float32)              # [B, F, emb_ch]
        logsnr_emb = nn.Dense(self.emb_ch, dtype=self.dtype)(logsnr_emb)
        logsnr_emb = nn.Dense(self.emb_ch, dtype=self.dtype)(
            nn.silu(logsnr_emb))

        # [B, F, H, W, 3] each; K broadcast over the frame axis
        # (reference unsqueezes K at xunet.py:312).  The intrinsics-only
        # half (K_inv @ pixel grid) may arrive precomputed as
        # batch['cam_dirs'] — the sampler's scan hoists it once per
        # trajectory (diffusion/core.py) instead of recomputing it every
        # denoise step; both branches are bit-identical by construction
        # (pinhole_rays is the composition of the two stages).
        cam_dirs = batch.get("cam_dirs")
        if cam_dirs is None:
            cam_dirs = pinhole_rays_cam(
                batch["K"][:, None].astype(jnp.float32), H, W)
        pos, dirs = pinhole_rays_world(batch["R"].astype(jnp.float32),
                                       batch["t"].astype(jnp.float32),
                                       cam_dirs)
        pose_emb = jnp.concatenate(
            [posenc_nerf(pos, 0, POS_DEG), posenc_nerf(dirs, 0, DIR_DEG)],
            axis=-1)                                             # [B, F, H, W, 144]

        pose_emb = jnp.where(cond_mask[:, None, None, None, None], pose_emb,
                             jnp.zeros_like(pose_emb))

        if self.use_pos_emb:
            pos_emb = self.param(
                "pos_emb", nn.initializers.normal(1.0 / np.sqrt(D)),
                (H, W, D))
            pose_emb = pose_emb + pos_emb[None, None]
        if self.use_ref_pose_emb:
            first_emb = self.param(
                "first_emb", nn.initializers.normal(1.0 / np.sqrt(D)),
                (1, 1, 1, 1, D))
            other_emb = self.param(
                "other_emb", nn.initializers.normal(1.0 / np.sqrt(D)),
                (1, 1, 1, 1, D))
            # frame 0 = reference view, frames 1.. = others
            # (reference concat at xunet.py:336 assumes F=2).
            F = pose_emb.shape[1]
            ref_emb = jnp.concatenate(
                [first_emb] + [other_emb] * (F - 1), axis=1)
            pose_emb = pose_emb + ref_emb

        Bf, F = pose_emb.shape[:2]
        flat = pose_emb.reshape(Bf * F, H, W, D)
        pose_embs = []
        for i_level in range(self.num_resolutions):
            s = 2 ** i_level
            # Explicit (1, 1) padding = torch's padding=1 (reference
            # xunet.py:292-299).  NOT "SAME": at stride >= 2 SAME aligns
            # the sampling grid differently, which silently breaks
            # converted-checkpoint parity at every level below the first.
            lvl = nn.Conv(self.emb_ch, (3, 3), strides=(s, s),
                          padding=((1, 1), (1, 1)), dtype=self.dtype,
                          name=f"level_conv_{i_level}")(flat)
            pose_embs.append(lvl.reshape(Bf, F, H // s, W // s, self.emb_ch))

        return logsnr_emb, pose_embs
