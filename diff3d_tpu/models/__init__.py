from diff3d_tpu.models.xunet import XUNet

__all__ = ["XUNet"]
