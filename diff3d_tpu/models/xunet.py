"""The X-UNet (Watson et al., 3DiM) as a Flax module.

Parity target: reference ``/root/reference/xunet.py:355-536``.  One model
definition replaces the reference's two variants (root + lightning, which
differ only in device handling).  Differences by design, not omission:

  * channels-last ``[B, F, H, W, C]`` layout (TPU-native; reference is NCHW);
  * conditioning rays computed on-device (see
    :mod:`diff3d_tpu.models.conditioning`);
  * up-path input channel arithmetic (reference ``xunet.py:432-460``) is
    implicit — Flax convs infer input width, and the skip push/pop structure
    reproduces the same concatenations (asserted empty at the end, like
    reference ``xunet.py:533``);
  * optional bf16 compute and per-block rematerialisation for the 128^2
    config that OOMs the reference's GPUs (README.md:39).

Forward contract (reference ``xunet.py:477-536``): batch dict with
``x [B,H,W,3]``, ``z [B,H,W,3]``, ``logsnr [B,2]``, ``R [B,2,3,3]``,
``t [B,2,3]``, ``K [B,3,3]`` plus ``cond_mask [B] bool``; returns the
predicted noise for the target frame, ``[B, H, W, 3]``.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from diff3d_tpu.config import ModelConfig
from diff3d_tpu.models.conditioning import ConditioningProcessor
from diff3d_tpu.models.layers import FrameGroupNorm, ResnetBlock, XUNetBlock


class XUNet(nn.Module):
    cfg: ModelConfig

    @nn.compact
    def __call__(self, batch: dict, *, cond_mask: jnp.ndarray,
                 deterministic: bool = True,
                 constrain=None) -> jnp.ndarray:
        """``constrain`` (optional ``h -> h``): sharding-constraint hook
        applied to every block's ``[B, F, h, w, C]`` output — GSPMD context
        parallelism when it pins the spatial axis to a mesh axis
        (``MeshEnv.activation_constraint``); identity otherwise."""
        cfg = self.cfg
        cfg.validate()
        if constrain is None:
            constrain = lambda h: h  # noqa: E731
        dtype = jnp.dtype(cfg.dtype)
        B, H, W, C = batch["x"].shape
        assert (H, W) == (cfg.H, cfg.W), ((H, W), (cfg.H, cfg.W))
        assert cond_mask.shape == (B,), (cond_mask.shape, B)

        num_res = cfg.num_resolutions
        dim_out = [cfg.ch * m for m in cfg.ch_mult]

        if cfg.remat:
            import jax

            policy = {
                "nothing": None,   # save-nothing: recompute the whole block
                "dots": jax.checkpoint_policies.dots_saveable,
            }[cfg.remat_policy]
            # argnums count `self` as 0, so `deterministic` is 3
            block_cls = nn.remat(XUNetBlock, static_argnums=(3,),
                                 policy=policy)
            resnet_cls = nn.remat(ResnetBlock, static_argnums=(3,),
                                  policy=policy)
        else:
            block_cls, resnet_cls = XUNetBlock, ResnetBlock

        logsnr_emb, pose_embs = ConditioningProcessor(
            emb_ch=cfg.emb_ch, H=H, W=W, num_resolutions=num_res,
            use_pos_emb=cfg.use_pos_emb,
            use_ref_pose_emb=cfg.use_ref_pose_emb,
            logsnr_clip=cfg.logsnr_clip, dtype=dtype,
            name="conditioningprocessor")(batch, cond_mask)

        def level_emb(i):
            # [B, F, 1, 1, emb_ch] + [B, F, h, w, emb_ch]
            return logsnr_emb[:, :, None, None, :] + pose_embs[i]

        # Stem: both frames through one 3x3 conv (reference xunet.py:493-495).
        h = jnp.stack([batch["x"], batch["z"]], axis=1).astype(dtype)
        F = h.shape[1]
        h = nn.Conv(cfg.ch, (3, 3), dtype=dtype,
                    name="stem_conv")(h.reshape(B * F, H, W, C))
        h = constrain(h.reshape(B, F, H, W, cfg.ch))

        # Down path (reference xunet.py:498-512).
        hs = [h]
        for i_level in range(num_res):
            emb = level_emb(i_level)
            use_attn = i_level in cfg.attn_levels
            for i_block in range(cfg.num_res_blocks):
                h = constrain(block_cls(
                    features=dim_out[i_level], use_attn=use_attn,
                    num_heads=cfg.attn_heads, dropout=cfg.dropout,
                    attn_impl=cfg.attn_impl_at(i_level), dtype=dtype,
                    kernels=cfg.kernels,
                    name=f"down_{i_level}_{i_block}")(h, emb, deterministic))
                hs.append(h)
            if i_level != num_res - 1:
                h = constrain(resnet_cls(
                    features=dim_out[i_level], dropout=cfg.dropout,
                    resample="down", dtype=dtype, kernels=cfg.kernels,
                    name=f"down_{i_level}_downsample")(h, emb, deterministic))
                hs.append(h)

        # Middle (reference xunet.py:419-424,515-517).
        h = constrain(block_cls(
            features=dim_out[-1], use_attn=num_res in cfg.attn_levels,
            num_heads=cfg.attn_heads, dropout=cfg.dropout,
            attn_impl=cfg.attn_impl_at(num_res - 1), dtype=dtype,
            kernels=cfg.kernels,
            name="middle")(h, level_emb(num_res - 1), deterministic))

        # Up path (reference xunet.py:521-531): each block consumes
        # concat([h, skip]) on the channel axis.
        for i_level in reversed(range(num_res)):
            emb = level_emb(i_level)
            use_attn = i_level in cfg.attn_levels
            for i_block in range(cfg.num_res_blocks + 1):
                h = jnp.concatenate([h, hs.pop()], axis=-1)
                h = constrain(block_cls(
                    features=dim_out[i_level], use_attn=use_attn,
                    num_heads=cfg.attn_heads, dropout=cfg.dropout,
                    attn_impl=cfg.attn_impl_at(i_level), dtype=dtype,
                    kernels=cfg.kernels,
                    name=f"up_{i_level}_{i_block}")(h, emb, deterministic))
            if i_level != 0:
                h = constrain(resnet_cls(
                    features=dim_out[i_level], dropout=cfg.dropout,
                    resample="up", dtype=dtype, kernels=cfg.kernels,
                    name=f"up_{i_level}_upsample")(h, emb, deterministic))
        assert not hs

        # Head: GN -> SiLU -> zero-init conv -> target frame's eps-hat
        # (reference xunet.py:472-474,535-536).
        h = FrameGroupNorm(dtype=dtype, kernels=cfg.kernels, silu=True,
                           name="last_gn")(h)
        h = nn.Conv(3, (3, 3), dtype=dtype,
                    kernel_init=nn.initializers.zeros,
                    name="last_conv")(h.reshape(B * F, H, W, dim_out[0]))
        h = h.reshape(B, F, H, W, 3)
        return h[:, 1].astype(jnp.float32)
