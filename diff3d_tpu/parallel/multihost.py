"""Multi-host bring-up.

The reference hardcodes ``MASTER_ADDR=localhost`` and spawns one process
per GPU with gloo TCP rendezvous (``/root/reference/train.py:181-187``) —
single-node only.  On TPU pods, ``jax.distributed.initialize()`` picks up
the coordinator from the TPU runtime environment automatically; after it,
``jax.devices()`` spans every host and the mesh layer (``mesh.py``) scales
unchanged from 1 chip to a full pod.
"""

from __future__ import annotations

import logging

import jax

from diff3d_tpu.runtime.retry import (RetryPolicy,
                                      is_transient_backend_error)

log = logging.getLogger(__name__)

#: Coordinator dial retry: at pod bring-up the coordinator process and
#: the workers race, so the first dial routinely lands before the
#: coordinator listens (UNAVAILABLE / connection refused).  Only
#: transient transport faults retry; config errors surface immediately.
_INIT_RETRY = RetryPolicy(max_attempts=4, base_delay_s=5.0,
                          max_delay_s=30.0,
                          classify=is_transient_backend_error)


def maybe_initialize_distributed(coordinator_address: str | None = None,
                                 num_processes: int | None = None,
                                 process_id: int | None = None,
                                 retry: RetryPolicy | None = None) -> bool:
    """Initialise JAX's multi-host runtime if we're in a multi-process job.

    MUST run before any other JAX call (``jax.distributed.initialize``
    refuses once a backend exists) — call it first thing in ``main``.
    Single-process environments (no coordinator configured) fall through
    and return False; an already-initialised runtime returns True.
    Transient coordinator-dial faults (workers racing the coordinator at
    pod bring-up) are retried under ``retry`` (default: 4 attempts with
    5-30 s backoff) before surfacing.
    """
    policy = retry or _INIT_RETRY
    try:
        policy.call(
            lambda: jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id),
            describe="jax.distributed.initialize")
    except RuntimeError as e:
        # Either already initialised (fine) or initialise-after-backend-use
        # (a real bug in the caller's ordering) — distinguish loudly.
        if "already" in str(e).lower():
            return jax.process_count() > 1
        log.warning("jax.distributed.initialize failed: %s", e)
        return jax.process_count() > 1
    except ValueError as e:
        # No coordinator available: single-process run (CPU dev box or
        # single-host TPU without a pod runtime).
        log.debug("single-process run (no coordinator): %s", e)
        return False
    log.info("jax.distributed up: process %d/%d, %d global devices",
             jax.process_index(), jax.process_count(), jax.device_count())
    return True


def shutdown_distributed() -> bool:
    """Tear down the multi-host runtime if one is up; True if it was.

    The elasticity path (``train/trainer.py::ElasticSupervisor``) calls
    this between re-mesh cycles: after a host-set change the old
    coordinator channel is stale, and ``jax.distributed.initialize``
    refuses while a previous client exists.  Safe to call when nothing
    was initialised (returns False) — single-process chaos tests drive
    the same code path as a real pod shrink.
    """
    try:
        from jax._src import distributed as _dist
        live = getattr(_dist.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - private API moved
        live = True  # let shutdown() itself decide
    if not live:
        return False
    try:
        jax.distributed.shutdown()
    except Exception as e:  # pragma: no cover - best effort teardown
        log.warning("jax.distributed.shutdown failed: %s", e)
        return False
    log.info("jax.distributed torn down for re-mesh")
    return True


def reinitialize_distributed(coordinator_address: str | None = None,
                             num_processes: int | None = None,
                             process_id: int | None = None,
                             retry: RetryPolicy | None = None) -> bool:
    """Tear down and re-dial the multi-host runtime for a new host set.

    One re-mesh cycle of the elasticity loop: :func:`shutdown_distributed`
    drops the stale coordinator client, then
    :func:`maybe_initialize_distributed` re-dials under the usual
    bring-up retry policy (workers race the restarted coordinator exactly
    as at first launch).  Returns the new multi-process status.
    Single-process runs (no coordinator) are a cheap no-op returning
    False, so the supervisor can call this unconditionally.
    """
    shutdown_distributed()
    return maybe_initialize_distributed(
        coordinator_address=coordinator_address,
        num_processes=num_processes, process_id=process_id, retry=retry)


def is_primary() -> bool:
    """True on the process that owns checkpoint/metric writes (the
    reference gates these on rank 0, ``train.py:287-298``)."""
    return jax.process_index() == 0


def shard_host_local(tree, sharding):
    """Assemble per-host local batch arrays into global sharded arrays.

    Each host's loader yields its own ``global_batch / num_hosts`` slice
    (``InfiniteLoader(host_id=..., num_hosts=...)``); multi-process runs
    must go through ``jax.make_array_from_process_local_data`` so the
    global array's shards come from each host's slice — a plain
    ``device_put`` would treat every host's (different) local array as
    the same global value, which is undefined across processes.
    Single-process keeps the cheap ``device_put``.
    """
    import numpy as np

    if jax.process_count() > 1:
        return jax.tree.map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)), tree)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)
