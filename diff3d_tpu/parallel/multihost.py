"""Multi-host bring-up.

The reference hardcodes ``MASTER_ADDR=localhost`` and spawns one process
per GPU with gloo TCP rendezvous (``/root/reference/train.py:181-187``) —
single-node only.  On TPU pods, ``jax.distributed.initialize()`` picks up
the coordinator from the TPU runtime environment automatically; after it,
``jax.devices()`` spans every host and the mesh layer (``mesh.py``) scales
unchanged from 1 chip to a full pod.
"""

from __future__ import annotations

import logging

import jax

log = logging.getLogger(__name__)


def maybe_initialize_distributed(coordinator_address: str | None = None,
                                 num_processes: int | None = None,
                                 process_id: int | None = None) -> bool:
    """Initialise JAX's multi-host runtime if we're in a multi-process job.

    Safe to call unconditionally: single-process (one host, N local chips)
    skips initialisation, and a second call is a no-op.  Returns True when
    the distributed client is live.
    """
    if jax.process_count() > 1:
        return True  # already initialised (e.g. by the launcher)
    explicit = coordinator_address is not None
    if not explicit and jax.default_backend() != "tpu":
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
        log.info("jax.distributed up: process %d/%d, %d global devices",
                 jax.process_index(), jax.process_count(),
                 jax.device_count())
        return True
    except (RuntimeError, ValueError) as e:
        # Single-host TPU (no coordinator env) lands here; that's fine.
        log.debug("jax.distributed.initialize skipped: %s", e)
        return False


def is_primary() -> bool:
    """True on the process that owns checkpoint/metric writes (the
    reference gates these on rank 0, ``train.py:287-298``)."""
    return jax.process_index() == 0
