"""Sequence/context parallelism: ring attention + all-to-all (Ulysses).

The reference never shards its attention sequence (``/root/reference/
xunet.py:199-208`` runs full ``H*W``-token attention per device; SURVEY.md
§5.7) — long-context scaling is a capability the TPU framework adds.  Two
standard schemes, both pure-JAX collectives so XLA schedules them on ICI:

* :func:`ring_sdpa` — blockwise (flash-style) attention with the KV shard
  rotating around the mesh axis via ``lax.ppermute``; each of the
  ``n_shards`` steps combines a local [L/n x L/n] attention block into
  running (max, sum, acc) online-softmax state.  Memory per device is
  O(L/n), compute overlaps with the ring transfer.
* :func:`ulysses_sdpa` — ``all_to_all`` reshards tokens->heads so each
  device holds ALL tokens for H/n heads, runs an ordinary (flash) sdpa,
  and reshards back.  Cheaper for moderate L when heads divide evenly.

Both are drop-in sdpa cores over local shards ``[B, L/n, H, D]`` of a
global ``[B, L, H, D]`` array inside ``shard_map``; exactness vs unsharded
attention is covered by tests on the 8-device CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _block_stats(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                 scale: float):
    """One KV-block attention: returns (m, l, acc) with
    m/l ``[B, Lq, H]`` and acc ``[B, Lq, H, D]`` (un-normalised PV)."""
    s = jnp.einsum("blhd,bmhd->blhm", q, k,
                   preferred_element_type=jnp.float32) * scale
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("blhm,bmhd->blhd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return m, l, acc


def ring_sdpa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              axis_name: str, scale: Optional[float] = None) -> jnp.ndarray:
    """Ring attention over a sharded token axis.

    Args:
      q, k, v: local shards ``[B, L/n, H, D]`` (token axis sharded over
        ``axis_name``); every query attends to every global key.
      axis_name: the mesh axis the sequence is sharded over.

    Returns the local output shard ``[B, L/n, H, D]``.
    """
    n = jax.lax.psum(1, axis_name)
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0, l0, acc0 = _block_stats(q, k, v, scale)

    def step(carry, _):
        m, l, acc, k, v = carry
        # rotate KV to the next device while (logically) computing; XLA
        # overlaps the ppermute with the einsums where profitable.
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        bm, bl, bacc = _block_stats(q, k, v, scale)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l = l * alpha + bl * beta
        acc = acc * alpha[..., None] + bacc * beta[..., None]
        return (m_new, l, acc, k, v), None

    (m, l, acc, _, _), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), None, length=n - 1)
    return (acc / l[..., None]).astype(q.dtype)


def ulysses_sdpa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                 axis_name: str,
                 scale: Optional[float] = None) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Reshards ``[B, L/n, H, D]`` -> ``[B, L, H/n, D]``, runs full-sequence
    attention on the local head subset, reshards back.  Requires
    ``H % n == 0``.
    """
    n = jax.lax.psum(1, axis_name)
    H = q.shape[2]
    if H % n:
        raise ValueError(f"heads {H} not divisible by axis size {n}")
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5

    def scatter_heads(x):  # [B, L/n, H, D] -> [B, L, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def gather_heads(x):   # [B, L, H/n, D] -> [B, L/n, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = jax.nn.dot_product_attention(qg, kg, vg, scale=scale)
    return gather_heads(out)
