"""Sequence/context parallelism: ring attention + all-to-all (Ulysses).

The reference never shards its attention sequence (``/root/reference/
xunet.py:199-208`` runs full ``H*W``-token attention per device; SURVEY.md
§5.7) — long-context scaling is a capability the TPU framework adds.  Two
standard schemes, both pure-JAX collectives so XLA schedules them on ICI:

* :func:`ring_sdpa` — blockwise (flash-style) attention with the KV shard
  rotating around the mesh axis via ``lax.ppermute``; each of the
  ``n_shards`` steps computes a local ``(o, lse)`` partial attention and
  folds it into the running result via the exact log-sum-exp combine.
  Memory per device is O(L/n), compute overlaps with the ring transfer.
  The local block engine is the Pallas flash kernel
  (:func:`diff3d_tpu.ops.pallas_attention.flash_attention_lse`) when the
  shapes support it on TPU — nothing of size ``[L/n, L/n]`` touches HBM —
  with an einsum fallback elsewhere.  This is the kernel's designed role:
  the single-chip X-UNet shapes are XLA-fused-sdpa territory (measured —
  see ops/attention._resolve_auto), long-context ring shards are where a
  hand kernel pays.
* :func:`ulysses_sdpa` — ``all_to_all`` reshards tokens->heads so each
  device holds ALL tokens for H/n heads, runs an ordinary (flash) sdpa,
  and reshards back.  Cheaper for moderate L when heads divide evenly.

Both are drop-in sdpa cores over local shards ``[B, L/n, H, D]`` of a
global ``[B, L, H, D]`` array inside ``shard_map``; exactness vs unsharded
attention (values AND grads, both engines) is covered by tests on the
8-device CPU mesh.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _block_olse_einsum(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                       scale: float):
    """One KV-block attention: returns ``(o [B, Lq, H, D] float32,
    lse [B, Lq, H] float32)``."""
    s = jnp.einsum("blhd,bmhd->blhm", q, k,
                   preferred_element_type=jnp.float32) * scale
    m = jax.lax.stop_gradient(jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("blhm,bmhd->blhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32) / l[..., None]
    return o, m[..., 0] + jnp.log(l)


def _block_olse_pallas(q, k, v, scale: float):
    from diff3d_tpu.ops.pallas_attention import flash_attention_lse

    o, lse = flash_attention_lse(q, k, v, scale=scale)
    return o.astype(jnp.float32), lse


def _pick_engine(q, k, v, impl: str):
    if impl == "einsum":
        return _block_olse_einsum
    from diff3d_tpu.ops.pallas_attention import supports

    if impl == "pallas":
        assert supports(q, k, v), (q.shape, q.dtype)
        return _block_olse_pallas
    # 'auto': flash kernel wherever it lowers (TPU) and shapes qualify
    try:
        on_tpu = jax.devices()[0].platform == "tpu"
    except RuntimeError:  # pragma: no cover
        on_tpu = False
    return (_block_olse_pallas if on_tpu and supports(q, k, v)
            else _block_olse_einsum)


def ring_sdpa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              axis_name: str, scale: Optional[float] = None,
              impl: str = "auto") -> jnp.ndarray:
    """Ring attention over a sharded token axis.

    Args:
      q, k, v: local shards ``[B, L/n, H, D]`` (token axis sharded over
        ``axis_name``); every query attends to every global key.
      axis_name: the mesh axis the sequence is sharded over.
      impl: local block engine — 'auto' | 'pallas' | 'einsum'.

    Returns the local output shard ``[B, L/n, H, D]``.
    """
    n = jax.lax.psum(1, axis_name)
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5
    perm = [(i, (i + 1) % n) for i in range(n)]
    block = _pick_engine(q, k, v, impl)

    o0, lse0 = block(q, k, v, scale)

    def step(carry, _):
        o, lse, k, v = carry
        # rotate KV to the next device while (logically) computing; XLA
        # overlaps the ppermute with the block attention where profitable.
        k = jax.lax.ppermute(k, axis_name, perm)
        v = jax.lax.ppermute(v, axis_name, perm)
        bo, blse = block(q, k, v, scale)
        lse_new = jnp.logaddexp(lse, blse)
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + bo * jnp.exp(blse - lse_new)[..., None])
        return (o, lse_new, k, v), None

    (o, _, _, _), _ = jax.lax.scan(step, (o0, lse0, k, v), None,
                                   length=n - 1)
    return o.astype(q.dtype)


def ulysses_sdpa(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                 axis_name: str,
                 scale: Optional[float] = None) -> jnp.ndarray:
    """All-to-all (DeepSpeed-Ulysses style) sequence parallelism.

    Reshards ``[B, L/n, H, D]`` -> ``[B, L, H/n, D]``, runs full-sequence
    attention on the local head subset, reshards back.  Requires
    ``H % n == 0``.
    """
    n = jax.lax.psum(1, axis_name)
    H = q.shape[2]
    if H % n:
        raise ValueError(f"heads {H} not divisible by axis size {n}")
    if scale is None:
        scale = float(q.shape[-1]) ** -0.5

    def scatter_heads(x):  # [B, L/n, H, D] -> [B, L, H/n, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def gather_heads(x):   # [B, L, H/n, D] -> [B, L/n, H, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qg, kg, vg = scatter_heads(q), scatter_heads(k), scatter_heads(v)
    out = jax.nn.dot_product_attention(qg, kg, vg, scale=scale)
    return gather_heads(out)
