"""Device mesh + sharding-spec layer.

The reference's distributed surface is ``torch.distributed`` DDP over gloo
(``/root/reference/train.py:187,224-233`` — broken as shipped, SURVEY.md
§2.7) plus per-step ``dist.barrier()`` calls.  The TPU-native equivalent:
one ``jax.sharding.Mesh`` over ``(data, model)`` axes; ``jit`` with
``NamedSharding`` in/out specs compiles the gradient all-reduce into XLA
collectives that ride ICI within a slice and DCN across slices.  No
user-level barriers exist because every compiled step is globally
synchronous by construction.

Param placement is a config switch (``MeshConfig.param_sharding``):

  * ``'replicated'`` — DDP-like; params/opt-state replicated, gradients
    all-reduced (what the reference intends).
  * ``'fsdp'``       — ZeRO-style; each param's largest divisible axis is
    sharded over the data axis, all-gathered on use.

The ``model`` axis is reserved for tensor parallelism — not needed for
reference parity (SURVEY.md §2.8: the reference has DP only) but a config
change, not a rewrite, when models outgrow a chip.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from diff3d_tpu.config import MeshConfig


@dataclasses.dataclass(frozen=True)
class MeshEnv:
    """A mesh plus the sharding rules derived from config."""

    mesh: Mesh
    cfg: MeshConfig

    @property
    def data_axis(self) -> str:
        return self.cfg.data_axis

    def batch(self) -> NamedSharding:
        return batch_sharding(self.mesh, self.cfg.data_axis)

    def replicated(self) -> NamedSharding:
        return replicated_sharding(self.mesh)

    def params(self, pytree) -> object:
        """Sharding pytree for params/opt-state per the config policy."""
        if self.cfg.param_sharding == "replicated":
            return jax.tree.map(lambda _: self.replicated(), pytree)
        if self.cfg.param_sharding == "fsdp":
            return jax.tree.map(
                lambda x: param_sharding(self.mesh, np.shape(x),
                                         self.cfg.data_axis), pytree)
        raise ValueError(self.cfg.param_sharding)


def make_mesh(cfg: MeshConfig = MeshConfig(),
              devices: Optional[Sequence[jax.Device]] = None) -> MeshEnv:
    """Build a ``(data, model)`` mesh over all (or given) devices.

    ``data_parallel == -1`` takes every device not claimed by
    ``model_parallel``.  Device order follows ``jax.devices()``, which
    groups hosts contiguously — so the data axis splits across hosts (DCN)
    only after filling each host's chips (ICI), the layout the scaling
    playbook prescribes for pure DP.
    """
    devices = list(devices if devices is not None else jax.devices())
    mp = max(1, cfg.model_parallel)
    dp = cfg.data_parallel
    if dp == -1:
        dp = len(devices) // mp
    if dp * mp > len(devices):
        raise ValueError(
            f"mesh {dp}x{mp} needs {dp * mp} devices, have {len(devices)}")
    grid = np.asarray(devices[: dp * mp]).reshape(dp, mp)
    mesh = Mesh(grid, (cfg.data_axis, cfg.model_axis))
    return MeshEnv(mesh=mesh, cfg=cfg)


def batch_sharding(mesh: Mesh, data_axis: str = "data") -> NamedSharding:
    """Leading (batch) dim over the data axis, rest replicated."""
    return NamedSharding(mesh, P(data_axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_sharding(mesh: Mesh, shape: Sequence[int],
                   data_axis: str = "data") -> NamedSharding:
    """FSDP-style spec: shard the largest axis divisible by the data-axis
    size; replicate params too small to bother (< one tile per device)."""
    n = mesh.shape[data_axis]
    if n == 1 or not shape or int(np.prod(shape)) < n * 128:
        return NamedSharding(mesh, P())
    candidates = [i for i, s in enumerate(shape) if s % n == 0]
    if not candidates:
        return NamedSharding(mesh, P())
    axis = max(candidates, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[axis] = data_axis
    return NamedSharding(mesh, P(*spec))
