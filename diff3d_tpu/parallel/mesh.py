"""Device mesh + sharding-spec layer.

The reference's distributed surface is ``torch.distributed`` DDP over gloo
(``/root/reference/train.py:187,224-233`` — broken as shipped, SURVEY.md
§2.7) plus per-step ``dist.barrier()`` calls.  The TPU-native equivalent:
one ``jax.sharding.Mesh`` over ``(data, model)`` axes; ``jit`` with
``NamedSharding`` in/out specs compiles the gradient all-reduce into XLA
collectives that ride ICI within a slice and DCN across slices.  No
user-level barriers exist because every compiled step is globally
synchronous by construction.

Param placement is a config switch (``MeshConfig.param_sharding``):

  * ``'replicated'`` — DDP-like; params/opt-state replicated, gradients
    all-reduced (what the reference intends).
  * ``'fsdp'``       — ZeRO-style; each param's largest divisible axis is
    sharded over the data axis, all-gathered on use.

The ``model`` axis is reserved for tensor parallelism — not needed for
reference parity (SURVEY.md §2.8: the reference has DP only) but a config
change, not a rewrite, when models outgrow a chip.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from diff3d_tpu.config import MeshConfig


@dataclasses.dataclass(frozen=True)
class MeshEnv:
    """A mesh plus the sharding rules derived from config."""

    mesh: Mesh
    cfg: MeshConfig

    @property
    def data_axis(self) -> str:
        return self.cfg.data_axis

    @property
    def data_size(self) -> int:
        """Number of devices on the data axis — the divisibility quantum
        for any leading dim sharded with :meth:`batch` (the sampler's
        object axis, the serving engine's lane counts)."""
        return int(self.mesh.shape[self.cfg.data_axis])

    def batch(self) -> NamedSharding:
        return batch_sharding(self.mesh, self.cfg.data_axis)

    def replicated(self) -> NamedSharding:
        return replicated_sharding(self.mesh)

    def state_shardings(self, state):
        """Sharding pytree for a :class:`TrainState`-shaped object: step
        replicated, params / opt-state / EMA per the param policy.  The one
        placement rule every trainer, bench, and dry run shares."""
        return type(state)(
            step=self.replicated(),
            params=self.params(state.params),
            opt_state=self.params(state.opt_state),
            ema_params=self.params(state.ema_params),
        )

    def activation_constraint(self):
        """``h -> h`` hook sharding ``[B, F, H, W, C]`` activations: batch
        over the data axis, image rows (the token axis once flattened to
        ``H*W`` sequences — H is the outer dim of the merge, so GSPMD
        propagates the sharding through the reshape) over the model axis.
        Threaded through :meth:`XUNet.__call__ <diff3d_tpu.models.xunet.
        XUNet.__call__>`'s ``constrain`` kwarg when
        ``MeshConfig.context_parallel`` is on."""
        sh = NamedSharding(
            self.mesh, P(self.cfg.data_axis, None, self.cfg.model_axis))

        def constrain(h):
            if h.ndim != 5:
                return h
            return jax.lax.with_sharding_constraint(h, sh)

        return constrain

    def param_spec_table(self, pytree) -> dict:
        """Flat ``{leaf path: str(PartitionSpec)}`` of the policy's
        intended placement — works on abstract (``ShapeDtypeStruct``)
        templates since only shapes are read.  The human-readable side
        of :meth:`params`, used by shardcheck's reports to say which
        placement each param *should* have gotten."""
        flat = jax.tree_util.tree_flatten_with_path(
            self.params(pytree),
            is_leaf=lambda x: isinstance(x, NamedSharding))[0]
        return {jax.tree_util.keystr(path): str(tuple(sh.spec))
                for path, sh in flat}

    def topology_summary(self) -> dict:
        """JSON-able description of the mesh topology this env shards
        over.  Stamped into checkpoint manifests so a restore into a
        *different* topology is recognised as a first-class reshard (and
        logged as such) rather than silently assumed identical — the
        elasticity loop's re-mesh contract (docs/DESIGN.md §16)."""
        return {
            "axes": {k: int(v) for k, v in self.mesh.shape.items()},
            "n_devices": int(self.mesh.size),
            "n_processes": int(jax.process_count()),
            "param_sharding": self.cfg.param_sharding,
        }

    def params(self, pytree) -> object:
        """Sharding pytree for params/opt-state per the config policy."""
        mode = self.cfg.param_sharding
        if mode == "replicated":
            return jax.tree.map(lambda _: self.replicated(), pytree)
        if mode == "fsdp":
            return jax.tree.map(
                lambda x: param_sharding(self.mesh, np.shape(x),
                                         self.cfg.data_axis), pytree)
        if mode in ("tp", "fsdp+tp"):
            fsdp_axis = self.cfg.data_axis if mode == "fsdp+tp" else None
            return jax.tree_util.tree_map_with_path(
                lambda path, x: tp_param_sharding(
                    self.mesh, path, np.shape(x), self.cfg.model_axis,
                    fsdp_axis=fsdp_axis), pytree)
        raise ValueError(mode)


def make_mesh(cfg: MeshConfig = MeshConfig(),
              devices: Optional[Sequence[jax.Device]] = None) -> MeshEnv:
    """Build a ``(data, model)`` mesh over all (or given) devices.

    ``data_parallel == -1`` takes every device not claimed by
    ``model_parallel``.  Placement is ICI-topology-aware: on a full
    device set ``mesh_utils.create_device_mesh`` orders the grid so the
    (inner) model axis rides the fastest ICI links, and on multi-slice
    TPU (slices joined by DCN) ``create_hybrid_device_mesh`` keeps the
    model axis inside a slice and splits only the data axis across the
    DCN boundary — the scaling-playbook layout.  Explicit device subsets
    (tests, dry runs) fall back to a plain reshape of the given order.
    """
    explicit = devices is not None
    devices = list(devices if devices is not None else jax.devices())
    mp = max(1, cfg.model_parallel)
    dp = cfg.data_parallel
    if dp == -1:
        dp = len(devices) // mp
    if dp * mp > len(devices):
        raise ValueError(
            f"mesh {dp}x{mp} needs {dp * mp} devices, have {len(devices)}")
    grid = _device_grid(devices[: dp * mp], dp, mp,
                        topology_aware=not explicit)
    mesh = Mesh(grid, (cfg.data_axis, cfg.model_axis))
    return MeshEnv(mesh=mesh, cfg=cfg)


def _device_grid(devices: list, dp: int, mp: int,
                 topology_aware: bool) -> np.ndarray:
    """[dp, mp] device grid, ICI/DCN-aware when possible."""
    fallback = np.asarray(devices).reshape(dp, mp)
    if not topology_aware or len(devices) <= 1:
        return fallback
    try:
        from jax.experimental import mesh_utils

        slices = {getattr(d, "slice_index", 0) for d in devices}
        if len(slices) > 1:
            # Multi-slice: model axis must stay inside a slice (ICI); the
            # data axis absorbs the across-slice (DCN) factor.
            n_slices = len(slices)
            if dp % n_slices:
                return fallback
            return mesh_utils.create_hybrid_device_mesh(
                (dp // n_slices, mp), (n_slices, 1), devices=devices)
        return mesh_utils.create_device_mesh((dp, mp), devices=devices)
    except Exception:
        # Any topology helper failure (odd shapes, virtual devices) must
        # never block mesh construction.
        return fallback


def batch_sharding(mesh: Mesh, data_axis: str = "data") -> NamedSharding:
    """Leading (batch) dim over the data axis, rest replicated."""
    return NamedSharding(mesh, P(data_axis))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def tp_param_sharding(mesh: Mesh, path, shape: Sequence[int],
                      model_axis: str = "model",
                      fsdp_axis: Optional[str] = None) -> NamedSharding:
    """Megatron-style tensor-parallel spec for one X-UNet param leaf.

    GSPMD turns these seed shardings into the classic TP comm pattern at
    compile time (column-parallel q/k/v needs no collective; the row-
    parallel out-proj matmul reduces partial sums over the model axis):

      * attention ``q/k/v_proj`` kernels ``[C, C]`` — output dim over
        ``model`` (column parallel); their biases likewise.
      * attention ``out_proj`` kernel — input dim over ``model`` (row
        parallel); bias replicated.
      * conv kernels ``[kh, kw, cin, cout]`` and Dense kernels (FiLM,
        logsnr MLP) — output channels over ``model``; biases likewise.
      * everything else (norm scales, learned pose embeddings, tiny
        leaves) — replicated.

    Dims not divisible by the axis size fall back to replication.  With
    ``fsdp_axis`` set, the largest still-unsharded divisible dim is
    additionally sharded over it (ZeRO-style weight sharding on top of TP).
    """
    names = [getattr(p, "key", str(p)) for p in path]
    tp = mesh.shape[model_axis]
    spec: list = [None] * len(shape)

    def shardable(dim: int) -> bool:
        return len(shape) > dim and shape[dim] % tp == 0 and shape[dim] >= tp

    is_kernel = names and names[-1] == "kernel"
    if tp > 1 and is_kernel:
        if any(n in ("q_proj", "k_proj", "v_proj") for n in names):
            if shardable(len(shape) - 1):
                spec[-1] = model_axis
        elif "out_proj" in names:
            if shardable(0):
                spec[0] = model_axis
        elif shardable(len(shape) - 1) and shape[-1] > 4:
            spec[-1] = model_axis          # conv/Dense output channels
    elif tp > 1 and names and names[-1] == "bias":
        # Only biases of column-parallel layers (q/k/v, convs, Dense):
        # norm biases stay replicated with their (replicated) scales, and
        # the row-parallel out_proj bias is added after the reduce.
        parent = names[-2] if len(names) >= 2 else ""
        col_parallel = (parent in ("q_proj", "k_proj", "v_proj")
                        or "conv" in parent or parent.startswith("Dense")
                        or parent == "skip_proj")
        if col_parallel and shardable(0) and shape[0] > 4:
            spec[0] = model_axis

    if fsdp_axis is not None:
        n = mesh.shape[fsdp_axis]
        free = [i for i, s in enumerate(shape)
                if spec[i] is None and s % n == 0 and s >= n]
        if free and int(np.prod(shape)) >= n * 128:
            axis = max(free, key=lambda i: shape[i])
            spec[axis] = fsdp_axis
    return NamedSharding(mesh, P(*spec))


def param_sharding(mesh: Mesh, shape: Sequence[int],
                   data_axis: str = "data") -> NamedSharding:
    """FSDP-style spec: shard the largest axis divisible by the data-axis
    size; replicate params too small to bother (< one tile per device)."""
    n = mesh.shape[data_axis]
    if n == 1 or not shape or int(np.prod(shape)) < n * 128:
        return NamedSharding(mesh, P())
    candidates = [i for i, s in enumerate(shape) if s % n == 0]
    if not candidates:
        return NamedSharding(mesh, P())
    axis = max(candidates, key=lambda i: shape[i])
    spec = [None] * len(shape)
    spec[axis] = data_axis
    return NamedSharding(mesh, P(*spec))
