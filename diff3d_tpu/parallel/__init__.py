import inspect

try:                                     # jax >= 0.5: top-level export
    from jax import shard_map as _shard_map
except ImportError:                      # jax 0.4.x: experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

from diff3d_tpu.parallel.mesh import (MeshEnv, batch_sharding, make_mesh,
                                      param_sharding, replicated_sharding,
                                      tp_param_sharding)
from diff3d_tpu.parallel.multihost import maybe_initialize_distributed
from diff3d_tpu.parallel.ring_attention import ring_sdpa, ulysses_sdpa

_SHARD_MAP_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f, **kw):
    """Version-stable ``shard_map``: one import site for the whole repo.

    jax moved ``shard_map`` out of ``jax.experimental`` and renamed its
    replication check ``check_rep`` -> ``check_vma`` across the 0.4/0.5
    boundary; this wrapper resolves the import and translates the kwarg
    either way so callers write the modern spelling everywhere.
    """
    if "check_vma" in kw and "check_vma" not in _SHARD_MAP_PARAMS:
        kw["check_rep"] = kw.pop("check_vma")
    elif "check_rep" in kw and "check_rep" not in _SHARD_MAP_PARAMS:
        kw["check_vma"] = kw.pop("check_rep")
    return _shard_map(f, **kw)


__all__ = [
    "MeshEnv", "make_mesh", "batch_sharding", "param_sharding",
    "replicated_sharding", "tp_param_sharding",
    "maybe_initialize_distributed", "ring_sdpa", "ulysses_sdpa",
    "shard_map",
]
