from diff3d_tpu.parallel.mesh import (MeshEnv, batch_sharding, make_mesh,
                                      param_sharding, replicated_sharding,
                                      tp_param_sharding)
from diff3d_tpu.parallel.multihost import maybe_initialize_distributed
from diff3d_tpu.parallel.ring_attention import ring_sdpa, ulysses_sdpa

__all__ = [
    "MeshEnv", "make_mesh", "batch_sharding", "param_sharding",
    "replicated_sharding", "tp_param_sharding",
    "maybe_initialize_distributed", "ring_sdpa", "ulysses_sdpa",
]
