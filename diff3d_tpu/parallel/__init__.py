from diff3d_tpu.parallel.mesh import (MeshEnv, batch_sharding, make_mesh,
                                      param_sharding, replicated_sharding)
from diff3d_tpu.parallel.multihost import maybe_initialize_distributed

__all__ = [
    "MeshEnv", "make_mesh", "batch_sharding", "param_sharding",
    "replicated_sharding", "maybe_initialize_distributed",
]
